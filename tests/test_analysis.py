"""graftlint tests: every rule family proves it fires on its violating
fixture AND stays quiet on its clean fixture; waiver mechanics; the CLI
contract; and the capstone — the repo itself lints clean (what `make
lint` enforces)."""

import os

import pytest

from kubernetes_scheduler_tpu.analysis import run_lint
from kubernetes_scheduler_tpu.analysis.__main__ import main as lint_main
from kubernetes_scheduler_tpu.analysis.rules import RULES

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def lint_fixture(name, rule):
    return run_lint([os.path.join(FIXTURES, name)], rules=[rule])


def active(violations):
    return [v for v in violations if not v.waived]


# ---- one violating + one clean fixture per rule family --------------------


@pytest.mark.parametrize(
    "rule,violating,clean,min_hits",
    [
        ("jit-purity", "jit_purity_violation.py", "jit_purity_clean.py", 3),
        ("host-sync", "host_sync_violation.py", "host_sync_clean.py", 3),
        (
            "lock-discipline",
            "lock_discipline_violation.py",
            "lock_discipline_clean.py",
            1,
        ),
        (
            "wire-schema",
            "wire_schema_violation.py",
            "wire_schema_clean.py",
            4,
        ),
        (
            "wire-schema",
            "journal_schema_violation.py",
            "journal_schema_clean.py",
            6,
        ),
        ("dtype-shape", "dtype_shape_violation.py", "dtype_shape_clean.py", 3),
        ("timeout-hygiene", "timeout_violation.py", "timeout_clean.py", 5),
        (
            "timeout-hygiene",
            "timeout_swallow_violation.py",
            "timeout_swallow_clean.py",
            2,
        ),
        (
            "donation-aliasing",
            "donation_aliasing_violation.py",
            "donation_aliasing_clean.py",
            4,
        ),
        (
            "host-transfer",
            "host_transfer_violation.py",
            "host_transfer_clean.py",
            7,
        ),
        (
            "tracer-leak",
            "tracer_leak_violation.py",
            "tracer_leak_clean.py",
            4,
        ),
        (
            "lockset-race",
            "lockset_race_violation.py",
            "lockset_race_clean.py",
            5,
        ),
        (
            "pallas-vmem",
            "pallas_vmem_violation.py",
            "pallas_vmem_clean.py",
            4,
        ),
        (
            "pallas-vmem",
            "pallas_vmem_shard_violation.py",
            "pallas_vmem_shard_clean.py",
            2,
        ),
        (
            "metric-hygiene",
            "metric_hygiene_violation.py",
            "metric_hygiene_clean.py",
            8,
        ),
        (
            "sim-determinism",
            "sim_determinism_violation.py",
            "sim_determinism_clean.py",
            6,
        ),
        (
            "span-hygiene",
            "span_hygiene_violation.py",
            "span_hygiene_clean.py",
            5,
        ),
        (
            "capability-completeness",
            "capability_completeness_violation.py",
            "capability_completeness_clean.py",
            8,
        ),
        (
            "spmd-collective",
            "spmd_collective_violation.py",
            "spmd_collective_clean.py",
            5,
        ),
        (
            "thread-race",
            "thread_race_violation.py",
            "thread_race_clean.py",
            5,
        ),
        (
            "determinism-taint",
            "determinism_taint_violation.py",
            "determinism_taint_clean.py",
            4,
        ),
    ],
)
def test_rule_fires_and_stays_quiet(rule, violating, clean, min_hits):
    hits = active(lint_fixture(violating, rule))
    assert len(hits) >= min_hits, [v.format() for v in hits]
    assert all(v.rule == rule for v in hits)
    quiet = active(lint_fixture(clean, rule))
    assert quiet == [], [v.format() for v in quiet]


# ---- rule specifics -------------------------------------------------------


def test_jit_purity_flags_reachable_helper_only():
    vs = active(lint_fixture("jit_purity_violation.py", "jit-purity"))
    assert any("global" in v.message for v in vs)  # helper via call graph
    assert any("print" in v.message for v in vs)
    assert any("TRACE_LOG" in v.message for v in vs)
    # the clean fixture's host_only_reporting prints but is unreachable
    vs = active(lint_fixture("jit_purity_clean.py", "jit-purity"))
    assert vs == []


def test_host_sync_messages_name_the_sync():
    msgs = [
        v.message
        for v in active(lint_fixture("host_sync_violation.py", "host-sync"))
    ]
    assert any("block_until_ready" in m for m in msgs)
    assert any("np.asarray" in m for m in msgs)
    assert any(".item()" in m for m in msgs)


def test_lock_discipline_names_class_method_and_attr():
    (v,) = active(
        lint_fixture("lock_discipline_violation.py", "lock-discipline")
    )
    assert "SharedCache.drop" in v.message and "_store" in v.message


def test_wire_schema_catches_ctor_attr_and_unknown_message():
    msgs = [
        v.message
        for v in active(
            lint_fixture("wire_schema_violation.py", "wire-schema")
        )
    ]
    assert any("`bogus`" in m for m in msgs)        # ctor kwarg
    assert any("`nonexistent`" in m for m in msgs)  # annotated param attr
    assert any("`Missing`" in m for m in msgs)      # unknown message
    assert any("`status`" in m for m in msgs)       # assigned-var attr


def test_dtype_shape_allows_static_shape_branching():
    # the clean fixture branches on x.shape[0] — idiomatic, not flagged
    assert active(lint_fixture("dtype_shape_clean.py", "dtype-shape")) == []
    msgs = [
        v.message
        for v in active(
            lint_fixture("dtype_shape_violation.py", "dtype-shape")
        )
    ]
    assert any("float64 dtype" in m for m in msgs)
    assert any("astype" in m for m in msgs)
    assert any("any" in m for m in msgs)


def test_donation_aliasing_covers_every_shape():
    """The donate_argnums family (the resident-state apply_snapshot_delta
    signature), now interprocedural: plain re-reads, attribute-chain
    arguments (`st.snapshot`), and donating device_put all fire; the
    idiomatic `x = f(x)` rebind — attribute-chain rebinds included —
    and reads before the donation stay clean."""
    hits = active(
        lint_fixture("donation_aliasing_violation.py", "donation-aliasing")
    )
    assert len(hits) >= 8, [v.format() for v in hits]
    assert all("donated" in v.message for v in hits)
    assert any("st.snapshot" in v.message for v in hits)
    assert any("jax.device_put" in v.message for v in hits)
    # a jitted METHOD's donate_argnums counts the bound self at 0 —
    # the shifted summary must watch `buf`, not `d`
    method_lines = {
        i for i, ln in enumerate(
            open(os.path.join(
                FIXTURES, "donation_aliasing_violation.py"
            )).read().splitlines(), 1,
        ) if "re-read after method donation" in ln
    }
    assert any(v.line in method_lines for v in hits), [
        v.format() for v in hits
    ]
    src = open(
        os.path.join(FIXTURES, "donation_aliasing_violation.py")
    ).read().splitlines()
    # the match-arm re-read (Match.cases are suites to the path walker)
    match_lines = {
        i for i, ln in enumerate(src, 1) if "re-read inside the case" in ln
    }
    assert any(v.line in match_lines for v in hits), [
        v.format() for v in hits
    ]
    # ONE finding per re-read line, not one per preceding donation
    double_lines = {
        i for i, ln in enumerate(src, 1)
        if "re-read after double donation" in ln
    }
    assert sum(1 for v in hits if v.line in double_lines) == 1, [
        v.format() for v in hits
    ]
    quiet = active(
        lint_fixture("donation_aliasing_clean.py", "donation-aliasing")
    )
    assert quiet == [], [v.format() for v in quiet]


def test_donation_aliasing_interprocedural_across_modules():
    """The case a single-file AST scan CANNOT catch: the donator is
    imported from another module, and one call site donates through a
    helper wrapper (`fold` passes its own parameter into the donated
    position — the summary fixpoint marks the wrapper as donating).
    Linting the caller file ALONE stays silent — proof the finding
    needs the cross-file index."""
    pair = [
        os.path.join(FIXTURES, "donation_interproc_violation.py"),
        os.path.join(FIXTURES, "donation_helper_mod.py"),
    ]
    hits = active(run_lint(pair, rules=["donation-aliasing"]))
    assert len(hits) == 2, [v.format() for v in hits]
    assert any("`fold`" in v.message for v in hits)       # via the wrapper
    assert any("`apply_delta`" in v.message for v in hits)  # via the import
    solo = active(run_lint([pair[0]], rules=["donation-aliasing"]))
    assert solo == [], [v.format() for v in solo]


def test_host_transfer_names_each_sync_shape():
    msgs = [
        v.message
        for v in active(
            lint_fixture("host_transfer_violation.py", "host-transfer")
        )
    ]
    assert any(".item() on jax value" in m for m in msgs)
    assert any("float() on jax value" in m for m in msgs)
    assert any("int() on jax value" in m for m in msgs)
    assert any("np.asarray() on jax value" in m for m in msgs)
    assert any("branch on jax value" in m for m in msgs)
    assert any("assert on jax value" in m for m in msgs)
    # the direct-call form needs no binding at all
    assert any("jnp.mean" in m for m in msgs)
    # an annotated binding (`total: jnp.ndarray = jnp.sum(x)`) taints
    # exactly like a plain Assign, and a keyword-only annotated param
    # is a device value too
    assert sum("float() on jax value" in m for m in msgs) >= 3


def test_host_transfer_false_positive_patterns_stay_quiet():
    """The taught patterns, pinned: np.asarray materializes to HOST (so
    later int()/float() on the binding are free), jax.default_backend()
    returns a string, untainted receivers and shape branches never
    fire."""
    quiet = active(lint_fixture("host_transfer_clean.py", "host-transfer"))
    assert quiet == [], [v.format() for v in quiet]


def test_tracer_leak_sees_helper_through_call_graph():
    """`_helper_leak` has no jit anywhere in its body or decorators —
    only the project call graph connects it to the jitted entry."""
    hits = active(lint_fixture("tracer_leak_violation.py", "tracer-leak"))
    assert any("_helper_leak" in v.message for v in hits)
    assert any("argument container" in v.message for v in hits)
    quiet = active(lint_fixture("tracer_leak_clean.py", "tracer-leak"))
    assert quiet == [], [v.format() for v in quiet]


def test_lockset_race_private_helper_inherits_caller_locks():
    """The pattern per-file lock-discipline needs a hand waiver for —
    `_rebuild` mutating guarded state, every call site holding the lock
    — is PROVEN safe here (clean fixture); the violating fixture's
    `_wipe` (called lock-free) and the two-locks class both fire."""
    hits = active(lint_fixture("lockset_race_violation.py", "lockset-race"))
    assert any("_wipe" in v.message for v in hits)
    assert any("TornCache.drop" in v.message for v in hits)
    assert any("MixedGuards" in v.message for v in hits)
    quiet = active(lint_fixture("lockset_race_clean.py", "lockset-race"))
    assert quiet == [], [v.format() for v in quiet]


def test_pallas_vmem_covers_all_three_families():
    """The rule family's three checks each fire — tiling (a block that
    cannot divide the lane-padded axis), the VMEM budget, reduced-
    precision accumulators, and host callbacks — and runtime-valued dims
    (the clean fixture's n_res) are skipped, not guessed."""
    msgs = [
        v.message
        for v in active(lint_fixture("pallas_vmem_violation.py", "pallas-vmem"))
    ]
    assert any("multiple of 128" in m for m in msgs)
    # BinOp-resolved dims (64 * 3) are checked too, in AND out specs —
    # the resolution the fused megakernel's stacked-row shapes go through
    assert sum("multiple of 128" in m for m in msgs) >= 3, msgs
    assert any("VMEM budget" in m for m in msgs)
    assert any("accumulate in f32" in m for m in msgs)
    assert any("host callback" in m for m in msgs)
    # the real fused kernel stays clean (what `make lint` enforces)
    real = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "kubernetes_scheduler_tpu", "ops", "pallas_fused.py",
    )
    assert active(run_lint([real], rules=["pallas-vmem"])) == []


def test_journal_schema_messages_name_the_drift():
    """Each journal-schema failure mode fires with a message naming the
    drift — and the REAL trace/schema.py lints clean (what `make lint`
    enforces for the journal contract)."""
    msgs = [
        v.message
        for v in active(
            lint_fixture("journal_schema_violation.py", "wire-schema")
        )
    ]
    assert any("tag 1 reused" in m for m in msgs)
    assert any("`seq` declared twice" in m for m in msgs)
    assert any("positive integer LITERAL" in m for m in msgs)
    assert any("unknown journal field kind" in m for m in msgs)
    assert any("kind must be a string LITERAL" in m for m in msgs)
    assert any("float64" in m for m in msgs)
    assert any("not a declared `tensors`-kind" in m for m in msgs)
    real = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "kubernetes_scheduler_tpu", "trace", "schema.py",
    )
    assert active(run_lint([real], rules=["wire-schema"])) == []


def test_metric_hygiene_covers_every_failure_mode():
    """Each metric-hygiene failure mode fires with a message naming the
    metric — and the REAL metric surfaces (host/observe.py's _HELP +
    SHIPPED_METRICS registry, the scheduler's and sidecar's labeled
    collectors) lint clean across the package (what `make lint`
    enforces)."""
    msgs = [
        v.message
        for v in active(
            lint_fixture("metric_hygiene_violation.py", "metric-hygiene")
        )
    ]
    assert any("`queue_depth` has no unit suffix" in m for m in msgs)
    assert any("empty HELP string" in m for m in msgs)
    assert any("`binds_total` declared twice" in m for m in msgs)
    assert any("must end in `_total`" in m for m in msgs)
    assert any("no (or an empty) help string" in m for m in msgs)
    assert any("no HELP entry in any *_HELP table" in m for m in msgs)
    assert any("no longer declared anywhere" in m for m in msgs)
    assert any("not registered in SHIPPED_METRICS" in m for m in msgs)
    assert active(run_lint(rules=["metric-hygiene"])) == []


def test_shipped_registry_matches_help_table():
    """The live registry covers every _HELP key (the lint checks the
    static surfaces; this pins the runtime tables to each other)."""
    from kubernetes_scheduler_tpu.host.observe import _HELP, SHIPPED_METRICS

    assert set(_HELP) <= set(SHIPPED_METRICS)


def test_span_hygiene_covers_every_failure_mode():
    """Each span-hygiene failure mode fires with a message naming the
    stage — and the REAL span surfaces (Scheduler._span call sites, the
    sidecar's SpanSet.add sites, the replay emitter) lint clean against
    observe.SHIPPED_SPANS across the package (what `make lint`
    enforces)."""
    msgs = [
        v.message
        for v in active(
            lint_fixture("span_hygiene_violation.py", "span-hygiene")
        )
    ]
    assert any("`mystery_stage` is not registered" in m for m in msgs)
    assert any("`orphan_stage` is not registered" in m for m in msgs)
    assert any("'Bind-Phase' is not lower_snake_case" in m for m in msgs)
    assert any("`cycle` registered twice" in m for m in msgs)
    assert any(
        "`removed_stage` is no longer emitted" in m for m in msgs
    )
    assert active(run_lint(rules=["span-hygiene"])) == []


def test_shipped_spans_cover_attribution_stages():
    """The analytics layer's attribution table only names registered
    stages (a table row over an unshipped name could never fill)."""
    from kubernetes_scheduler_tpu.host.observe import SHIPPED_SPANS
    from kubernetes_scheduler_tpu.trace.analyze import (
        ATTRIBUTION_STAGES,
        NON_ATTRIBUTED_STAGES,
    )

    assert set(ATTRIBUTION_STAGES) <= set(SHIPPED_SPANS)
    assert set(NON_ATTRIBUTED_STAGES) <= set(SHIPPED_SPANS)
    assert "cycle" in SHIPPED_SPANS


def test_real_schedule_proto_parses():
    from kubernetes_scheduler_tpu.analysis.rules.wire_schema import parse_proto

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    messages = parse_proto(
        os.path.join(
            root, "kubernetes_scheduler_tpu", "bridge", "schedule.proto"
        )
    )
    assert "session_id" in messages["ScheduleRequest"]
    assert "field_cache" in messages["HealthReply"]
    assert messages["HealthRequest"] == set()  # single-line empty message
    assert "same_as_last" in messages["Tensor"]


def test_spmd_collective_covers_every_check():
    """Each SPMD failure mode fires with a message teaching the fix —
    double-counting psum, unbound axis, redundant gather of a
    replicated value, the all_gather axis=-name misuse, and out_specs
    replication the body never establishes (both the sharded and the
    varying flavor) — and the REAL mesh-sharded engine lints clean
    (what `make lint` enforces; the sanctioned pmax-over-equal
    discharge and the `psum(1, axes)` device-count idiom are taught,
    not waived)."""
    msgs = [
        v.message
        for v in active(
            lint_fixture("spmd_collective_violation.py", "spmd-collective")
        )
    ]
    assert any("double-counts" in m for m in msgs)
    assert any("'nodez'" in m and "no mesh" in m for m in msgs)
    assert any("identical copies" in m for m in msgs)
    assert any("insertion POSITION" in m for m in msgs)
    assert any("provably sharded" in m for m in msgs)
    assert any("provably varying" in m for m in msgs)
    assert all("pmax-over-equal" in m for m in msgs if "out_specs" in m)
    real = [
        "kubernetes_scheduler_tpu/parallel/engine.py",
        "kubernetes_scheduler_tpu/parallel/mesh.py",
    ]
    assert active(run_lint(real, rules=["spmd-collective"])) == []


def test_spmd_analyzer_catches_dropped_auction_discharge(tmp_path):
    """The out-spec check's teeth on the REAL engine source: deleting
    the auction's pmax-over-equal discharge (the pcast-varying carry's
    only re-replication point) must fire the out-spec-replication
    finding on both sharded factories. (The greedy scan's picks are a
    pure function of all-gathered values, so greedy's pmax is a
    vma-checker aid, not load-bearing replication — the analyzer
    rightly stays quiet when IT is dropped.)"""
    import shutil

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    eng = os.path.join(
        root, "kubernetes_scheduler_tpu", "parallel", "engine.py"
    )
    src = open(eng).read()
    mutated = src.replace(
        "    assigned = jax.lax.pmax(assigned, axes)\n", ""
    )
    assert mutated != src
    work = tmp_path / "engine_mutant.py"
    work.write_text(mutated)
    mesh = os.path.join(
        root, "kubernetes_scheduler_tpu", "parallel", "mesh.py"
    )
    shutil.copy(mesh, tmp_path / "mesh.py")
    vs = active(
        run_lint(
            [str(work), str(tmp_path / "mesh.py")],
            rules=["spmd-collective"],
        )
    )
    assert any(
        "out_specs declares a replicated output" in v.message
        and "node_idx" in v.message
        for v in vs
    ), [v.format() for v in vs]


# ---- thread model, races, determinism taint (families 17-18) --------------


def test_thread_race_messages_teach_each_discharge():
    """Every race shape fires with a message naming the attribute, both
    sites, AND the discharge menu (lock / publish-before-start / Event
    pairing / queue hand-off / join) — the finding teaches the fix."""
    vs = active(lint_fixture("thread_race_violation.py", "thread-race"))
    msgs = [v.message for v in vs]
    assert any("`Pump.rows`" in m and "happens-before" in m for m in msgs)
    assert any("`Pump.total`" in m and "written in `start`" in m for m in msgs)
    assert any("check-then-act on `Pump.cache`" in m for m in msgs)
    assert any("module global `COUNTER`" in m for m in msgs)
    race_msgs = [m for m in msgs if "happens-before" in m]
    assert all(
        "Event.set()/wait()" in m and "Queue" in m and "join" in m
        for m in race_msgs
    )


def test_thread_race_cross_file_pair():
    """Write in thread A (file B's worker), read in thread B (file B's
    main), state defined in file A: the interprocedural model must carry
    thread identities across the import and anchor the finding where the
    accesses live."""
    paths = [
        os.path.join(FIXTURES, "thread_race_xfile_state.py"),
        os.path.join(FIXTURES, "thread_race_xfile_threads.py"),
    ]
    vs = active(run_lint(paths, rules=["thread-race"]))
    assert vs, "cross-file race not detected"
    assert all(v.path.endswith("thread_race_xfile_state.py") for v in vs)
    assert any(
        "`Registry.items`" in v.message
        and "Loader._fill" in v.message
        and "main" in v.message
        for v in vs
    ), [v.format() for v in vs]


def test_thread_roots_verified_on_repo():
    """The declared thread model resolves against the live tree: every
    root's file, def, anchor fragments, and `reaches` edges hold."""
    from kubernetes_scheduler_tpu.analysis import threads

    assert threads.verify_thread_roots(_repo_index()) == []


def test_thread_model_drift_fails_lint():
    """Anchor drift is a FINDING, not a silent stale model: a root whose
    def vanished, whose fragment no longer appears, and whose declared
    dispatch edge is gone each fire."""
    from kubernetes_scheduler_tpu.analysis import threads

    index = _repo_index()
    gone_def = threads.ThreadRoot(
        name="drifted-def",
        thread="w",
        path="kubernetes_scheduler_tpu/host/scheduler.py",
        func="Scheduler.no_such_method",
        description="",
    )
    gone_frag = threads.ThreadRoot(
        name="drifted-fragment",
        thread="w",
        path="kubernetes_scheduler_tpu/kube/source.py",
        func="InformerCache._resource_loop",
        must_contain=("self.frobnicate_quux(",),
        description="",
    )
    gone_reach = threads.ThreadRoot(
        name="drifted-reach",
        thread="w",
        path="kubernetes_scheduler_tpu/kube/source.py",
        func="InformerCache._resource_loop",
        reaches=("Scheduler.no_such_sink",),
        description="",
    )
    for root in (gone_def, gone_frag, gone_reach):
        vs = threads.verify_thread_roots(index, roots=(root,))
        assert vs and all(v.rule == "thread-race" for v in vs), root.name
        assert any(root.name in v.message for v in vs), root.name


def test_thread_mutants_each_caught():
    """The analyzer's teeth, one seeded mutant at a time: the unmutated
    base is clean under both families, and each mutant is caught by the
    family that owns its bug class, with the rendered evidence naming
    the access pair (or tainted field) the mutation un-ordered."""
    from kubernetes_scheduler_tpu.analysis import thread_mutants

    assert thread_mutants.check_thread_mutants() == []
    evidence_frag = {
        "drop-mirror-lock": "`MiniMirror._dirty`",
        "event-set-before-write": "`MiniMirror.published`",
        "unsorted-dirty-iter": "set-order",
        "wallclock-journal-field": "journal-record field `seq`",
        "latch-check-then-act": "`MiniMirror.cache`",
        "unjoined-shutdown-read": "read in `close`",
    }
    for name, (_, _, family) in thread_mutants.THREAD_MUTANTS.items():
        got = thread_mutants.run_thread_mutant(name)
        hits = got[family]
        assert hits, f"mutant {name} survived {family}"
        assert any(
            evidence_frag[name] in v.message for v in hits
        ), (name, [v.message for v in hits])


def test_changed_only_thread_surfaces_wired():
    """Families 17-18 ride the changed-only machinery: the thread-mutant
    SURFACE patterns cover the analyzer files and every threaded layer,
    and a closure touching any declared thread root pulls in ALL root
    files (the model is whole-program — partial roots would under-report,
    breaking changed-only ⊆ full-run)."""
    import fnmatch

    from kubernetes_scheduler_tpu.analysis.thread_mutants import SURFACE
    from kubernetes_scheduler_tpu.analysis.threads import THREAD_ROOTS
    from kubernetes_scheduler_tpu.analysis.core import (
        reverse_dependency_closure,
    )

    for p in (
        "kubernetes_scheduler_tpu/analysis/threads.py",
        "kubernetes_scheduler_tpu/analysis/rules/thread_race.py",
        "kubernetes_scheduler_tpu/analysis/rules/determinism_taint.py",
        "kubernetes_scheduler_tpu/host/mirror.py",
        "kubernetes_scheduler_tpu/kube/source.py",
        "kubernetes_scheduler_tpu/bridge/server.py",
        "kubernetes_scheduler_tpu/trace/spans.py",
    ):
        assert any(fnmatch.fnmatch(p, pat) for pat in SURFACE), p
    ctx = _full_ctx()
    closure = reverse_dependency_closure(
        ctx, {"kubernetes_scheduler_tpu/host/mirror.py"}
    )
    for root in THREAD_ROOTS:
        assert root.path in closure, root.path


def test_determinism_taint_messages_name_the_fix():
    vs = active(
        lint_fixture("determinism_taint_violation.py", "determinism-taint")
    )
    msgs = [v.message for v in vs]
    assert any("wall-clock" in m and "inject the clock" in m for m in msgs)
    assert any("set-order" in m and "sorted" in m for m in msgs)
    assert any("id-order" in m and "stable identity" in m for m in msgs)
    assert any("engine operand" in m for m in msgs)
    assert any("CycleMetrics" in m for m in msgs)


def test_thread_race_regression_pins():
    """The genuine findings this family surfaced stay fixed: the
    sidecar's health/arm_profile reads take the service lock, the span
    recorder's drop counter increments under its id lock, and the
    snapshot builder's interned-names memo (the one cache the feeder
    thread also touches) publishes under its own lock."""
    import threading as _threading

    src = open("kubernetes_scheduler_tpu/bridge/server.py").read()
    assert "served = self.cycles_served" in src
    src = open("kubernetes_scheduler_tpu/host/observe.py").read()
    assert "with self._id_lock:\n                self.spans_dropped += 1" in src
    from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder

    b = SnapshotBuilder()
    assert isinstance(b._names_lock, type(_threading.Lock()))
    # and the families stay quiet on the fixed files (no waiver creep)
    vs = active(run_lint(
        [
            "kubernetes_scheduler_tpu/bridge/server.py",
            "kubernetes_scheduler_tpu/host/observe.py",
            "kubernetes_scheduler_tpu/host/snapshot.py",
        ],
        rules=["thread-race"],
    ))
    assert vs == [], [v.format() for v in vs]


# ---- waiver mechanics -----------------------------------------------------


def test_waivers_inline_and_preceding_line():
    vs = run_lint(
        [os.path.join(FIXTURES, "waiver_fixture.py")],
        rules=["timeout-hygiene"],
    )
    waived = [v for v in vs if v.waived]
    unwaived = [v for v in vs if not v.waived]
    # both waiver placements took effect, with their reasons preserved
    assert len(waived) == 2
    assert all(v.waiver_reason for v in waived)
    # the reason-less waiver: its own bad-waiver violation AND the
    # underlying finding stays active; the wrong-rule waiver leaves the
    # timeout finding active too
    assert any(v.rule == "bad-waiver" for v in unwaived)
    assert (
        len([v for v in unwaived if v.rule == "timeout-hygiene"]) == 2
    ), [v.format() for v in vs]


def test_bad_waiver_cannot_waive_itself():
    vs = run_lint(
        [os.path.join(FIXTURES, "waiver_fixture.py")],
        rules=["timeout-hygiene"],
    )
    assert all(not v.waived for v in vs if v.rule == "bad-waiver")


# ---- runner / CLI contract ------------------------------------------------


def test_unknown_rule_rejected():
    with pytest.raises(ValueError, match="unknown lint rules"):
        run_lint(rules=["no-such-rule"])


def test_registry_has_all_eighteen_families():
    assert set(RULES) == {
        "jit-purity", "host-sync", "lock-discipline", "wire-schema",
        "dtype-shape", "timeout-hygiene", "pallas-vmem", "metric-hygiene",
        "sim-determinism", "span-hygiene", "donation-aliasing",
        "host-transfer", "tracer-leak", "lockset-race",
        "capability-completeness", "spmd-collective",
        "thread-race", "determinism-taint",
    }


# ---- the interprocedural dataflow core ------------------------------------


def _repo_index():
    from kubernetes_scheduler_tpu.analysis import dataflow
    from kubernetes_scheduler_tpu.analysis.core import (
        Context,
        collect_files,
        load_file,
    )

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = [load_file(p, root) for p in collect_files(root)]
    ctx = Context(root=root, files=[f for f in files if f is not None])
    return dataflow.get_index(ctx)


def test_call_graph_spans_host_engine_ops():
    """The project call graph must connect the three layers the new
    families reason across: host/scheduler.py into engine.py (the
    in-host preemption fallback calls the jitted preempt_batch), and
    engine.py into ops/ (the fused dispatch calls the Pallas wrapper)."""
    index = _repo_index()
    graph = index.call_graph()

    def callees_of(path_part, fn_name):
        out = set()
        for q, edges in graph.items():
            fi = index.funcs[q]
            if path_part in fi.sf.path and fi.name == fn_name:
                out |= {index.funcs[c].name for c, _ in edges}
        return out

    assert "preempt_batch" in callees_of("host/scheduler.py", "_run_preemption")
    assert "fused_masked_score" in callees_of("engine.py", "_fused_masked_scores")
    assert "finish_cycle" in callees_of("engine.py", "schedule_batch")
    # reachability closes transitively host -> engine -> ops
    roots = {
        q for q, fi in index.funcs.items()
        if "host/scheduler.py" in fi.sf.path and fi.name == "_run_preemption"
    }
    reach = index.reachable_from(roots)
    assert any("engine.py" in q for q in reach)


def test_donation_summaries_seed_engine_entry_points():
    """The fixpoint must know the real donated signatures: engine's
    apply_snapshot_delta and apply_layout_delta donate position 0."""
    from kubernetes_scheduler_tpu.analysis import dataflow

    index = _repo_index()
    donors = dataflow.donation_summaries(index)
    by_name = {
        index.funcs[q].name: pos
        for q, pos in donors.items()
        if "engine.py" in q
    }
    assert by_name.get("apply_snapshot_delta") == (0,)
    assert by_name.get("apply_layout_delta") == (0,)


def test_jit_entries_cover_engine_surface():
    index = _repo_index()
    names = {index.funcs[q].name for q in index.jit_entries()}
    assert {
        "apply_snapshot_delta", "apply_layout_delta", "build_fused_layout",
        "schedule_windows", "preempt_batch",
    } <= names


def test_lockset_fixpoint_propagates_through_helpers():
    """Unit-level pin of the lockset walker on the clean fixture: the
    private `_rebuild` helper's ONLY entry lockset is {_lock} (inherited
    from its guarded call site — __init__'s lock-free call contributes
    nothing, happens-before), while public `put` enters lock-free."""
    import ast as ast_mod

    from kubernetes_scheduler_tpu.analysis import dataflow

    src = open(os.path.join(FIXTURES, "lockset_race_clean.py")).read()
    tree = ast_mod.parse(src)
    cls = next(
        n for n in ast_mod.walk(tree)
        if isinstance(n, ast_mod.ClassDef) and n.name == "DisciplinedCache"
    )
    facts = dataflow.class_lock_facts(cls)
    assert facts.locks == {"_lock"}
    contexts = dataflow.method_entry_locksets(facts)
    assert contexts["_rebuild"] == {frozenset({"_lock"})}
    assert contexts["put"] == {frozenset()}
    # definition-order regression: helpers defined BEFORE their only
    # lock-holding entry must still resolve to {_lock} — the fixpoint
    # must not inject a default empty context for a caller whose own
    # contexts are not computed yet
    cls2 = next(
        n for n in ast_mod.walk(tree)
        if isinstance(n, ast_mod.ClassDef)
        and n.name == "HelpersDefinedFirst"
    )
    contexts2 = dataflow.method_entry_locksets(
        dataflow.class_lock_facts(cls2)
    )
    assert contexts2["_deep"] == {frozenset({"_lock"})}
    assert contexts2["_shallow"] == {frozenset({"_lock"})}
    # a helper reachable ONLY from __init__ keeps an EMPTY context set
    # (construction happens-before publication) — the rule must read
    # "no contexts" as "exempt", never default it to a lock-free entry
    cls3 = next(
        n for n in ast_mod.walk(tree)
        if isinstance(n, ast_mod.ClassDef) and n.name == "InitOnlyHelper"
    )
    contexts3 = dataflow.method_entry_locksets(
        dataflow.class_lock_facts(cls3)
    )
    assert contexts3["_reset"] == set()


def test_branch_path_prefix_semantics():
    from kubernetes_scheduler_tpu.analysis import dataflow

    assert dataflow.path_prefix((), ((1, "body"),))
    assert dataflow.path_prefix(((1, "body"),), ((1, "body"), (2, "orelse")))
    assert not dataflow.path_prefix(((1, "body"),), ((1, "orelse"),))


# ---- layer 2: engine contracts (jax.eval_shape) ---------------------------


def test_contract_drift_fixture_pair():
    """The violating fixture's transposed/promoted returns are caught at
    every declared grid point; the clean twin traces silently."""
    from kubernetes_scheduler_tpu.analysis.contracts import (
        check_fixture_module,
    )

    vs = check_fixture_module(
        os.path.join(FIXTURES, "contract_drift_violation.py")
    )
    msgs = [v.message for v in vs]
    assert len(vs) >= 3, msgs
    assert all(v.rule == "engine-contract" for v in vs)
    assert any("(4, 8)" in m and "(8, 4)" in m for m in msgs)  # transpose
    assert any("int32" in m for m in msgs)                      # dtype drift
    clean = check_fixture_module(
        os.path.join(FIXTURES, "contract_drift_clean.py")
    )
    assert clean == [], [v.format() for v in clean]


def test_engine_contracts_clean_and_covering():
    """Every engine entry point the host/bridge dispatch to traces to
    its declared spec across the bucket grid (what `make lint` runs),
    and the declared coverage includes the full required surface —
    schedule_batch (all three paths), schedule_windows, the donated
    folds, the layout build, and the three Pallas wrappers."""
    from kubernetes_scheduler_tpu.analysis import contracts

    assert set(contracts.CONTRACT_NAMES) >= {
        "schedule_batch", "schedule_batch(auction)",
        "schedule_batch(fused)", "schedule_windows",
        "apply_snapshot_delta", "apply_layout_delta",
        "build_fused_layout", "fused_masked_score",
        "fused_score_row_stats", "fused_auction_bid",
    }
    vs = contracts.check_contracts()
    assert vs == [], "\n".join(v.format() for v in vs)


def test_spmd_traced_contracts_and_budget_clean():
    """The sharded half of layer 2 (what `make lint` runs): every
    declared sharded surface traces through shard_map on the virtual
    8-device mesh to EXACTLY the dense spec (the resident appliers
    spec-preserving, the layout build honoring the per-shard padding
    formula), the divisibility formula predicts both success and
    failure, the collective counts match the checked-in
    COLLECTIVE_BUDGET.json, and the declared coverage includes the
    four schedule surfaces plus the four sharded-RESIDENT surfaces."""
    from kubernetes_scheduler_tpu.analysis import contracts

    assert set(contracts.SHARDED_CONTRACT_NAMES) == {
        "sharded_schedule(greedy)", "sharded_schedule(auction)",
        "sharded_windows(greedy)", "sharded_windows(auction)",
        "sharded_schedule(fused)", "sharded_apply_delta",
        "sharded_build_layout", "sharded_apply_layout_delta",
    }
    vs = contracts.check_sharded_contracts()
    assert vs == [], "\n".join(v.format() for v in vs)


def test_collective_budget_staleness_fails_loudly(tmp_path):
    """Every budget-file failure mode is a finding, never a silent
    pass: missing file, unparseable file, per-kind count drift, a
    stale budgeted surface, and an unbudgeted new surface."""
    import json

    from kubernetes_scheduler_tpu.analysis.contracts import (
        check_collective_budget,
    )

    traced = {"sharded_schedule(greedy)": {
        "psum": 4, "pmax": 2, "pmin": 2, "all_gather": 2,
        "axis_index": 2,
    }}
    missing = str(tmp_path / "nope.json")
    vs = check_collective_budget(missing, traced=traced)
    assert len(vs) == 1 and "missing" in vs[0].message

    garbage = tmp_path / "garbage.json"
    garbage.write_text("{not json")
    vs = check_collective_budget(str(garbage), traced=traced)
    assert len(vs) == 1 and "does not parse" in vs[0].message

    doc = {"surfaces": {
        "sharded_schedule(greedy)": {
            "psum": 4, "pmax": 2, "pmin": 2, "all_gather": 1,
            "axis_index": 2,
        },
        "ghost_surface": {"psum": 1},
    }}
    drifted = tmp_path / "drifted.json"
    drifted.write_text(json.dumps(doc))
    vs = check_collective_budget(str(drifted), traced=traced)
    msgs = [v.message for v in vs]
    assert any(
        "all_gather: traced 2 != budgeted 1" in m for m in msgs
    ), msgs
    assert any("`ghost_surface`" in m and "stale" in m for m in msgs)

    vs = check_collective_budget(
        str(drifted),
        traced={**traced, "sharded_schedule(new)": {"psum": 1}},
    )
    assert any("has no budget entry" in v.message for v in vs)

    # a surface whose TRACE failed is exempt from the staleness check:
    # the trace failure is its own finding, and "stale — regenerate"
    # advice there would point at dropping the pin, not at the bug
    vs = check_collective_budget(
        str(drifted), traced=traced, failed={"ghost_surface"},
    )
    assert not any("`ghost_surface`" in v.message for v in vs), [
        v.format() for v in vs
    ]


def test_checked_in_collective_budget_matches_traced_jaxprs():
    """The acceptance pin: COLLECTIVE_BUDGET.json at the repo root
    matches the traced jaxprs of every declared sharded surface, and
    budgets every one of them (no ghosts, no gaps)."""
    import json

    from kubernetes_scheduler_tpu.analysis.contracts import (
        COLLECTIVE_BUDGET_NAME,
        traced_surface_counts,
    )

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc = json.load(open(os.path.join(root, COLLECTIVE_BUDGET_NAME)))
    traced = traced_surface_counts()
    assert doc["surfaces"] == traced


# ---- structural waivers (decorated defs, multi-line statements) -----------


def test_waiver_above_decorator_covers_whole_def():
    vs = run_lint(
        [os.path.join(FIXTURES, "waiver_structural_fixture.py")],
        rules=["dtype-shape"],
    )
    waived = [v for v in vs if v.waived]
    act = active(vs)
    # the waived def's body finding is covered; the unwaived twin fires
    assert any(
        "gated_waived" in v.message and v.waiver_reason for v in waived
    ), [v.format() for v in vs]
    assert any("gated_unwaived" in v.message for v in act)
    # multi-line statement: the dtype kw two lines in is covered too
    assert any("float64" in v.message for v in waived)
    assert not any("float64" in v.message for v in act)


def test_waiver_on_multiline_statement_covers_statement():
    vs = run_lint(
        [os.path.join(FIXTURES, "waiver_structural_fixture.py")],
        rules=["timeout-hygiene"],
    )
    waived = [v for v in vs if v.waived]
    act = [v for v in active(vs) if v.rule == "timeout-hygiene"]
    assert len(waived) == 1 and len(act) == 1, [v.format() for v in vs]


# ---- baseline suppression file --------------------------------------------


def _baseline(tmp_path, entries):
    import json

    p = tmp_path / "LINT_BASELINE.json"
    p.write_text(json.dumps({"entries": entries}))
    return str(p)


def test_baseline_suppresses_matching_finding(tmp_path):
    from kubernetes_scheduler_tpu.analysis.core import (
        apply_baseline,
        load_baseline,
    )

    vs = run_lint(
        [os.path.join(FIXTURES, "timeout_violation.py")],
        rules=["timeout-hygiene"],
    )
    target = active(vs)[0]
    path = _baseline(tmp_path, [{
        "rule": "timeout-hygiene", "path": target.path,
        "contains": "timeout", "reason": "triage window for the fixture",
    }])
    extra = apply_baseline(vs, load_baseline(path), path)
    assert extra == []
    assert all(
        v.waived for v in vs if v.path == target.path
    ) or any(v.waived and "baseline:" in v.waiver_reason for v in vs)


def test_baseline_stale_and_unexplained_entries_fail(tmp_path):
    from kubernetes_scheduler_tpu.analysis.core import (
        apply_baseline,
        load_baseline,
    )

    vs = run_lint(
        [os.path.join(FIXTURES, "timeout_clean.py")],
        rules=["timeout-hygiene"],
    )
    path = _baseline(tmp_path, [
        {"rule": "timeout-hygiene", "path": "nowhere.py",
         "reason": "points at nothing"},
        {"rule": "timeout-hygiene", "path": "nowhere.py", "reason": ""},
    ])
    extra = apply_baseline(vs, load_baseline(path), path)
    rules = sorted(v.rule for v in extra)
    assert rules == ["bad-baseline", "stale-baseline"], [
        v.format() for v in extra
    ]


def test_baseline_malformed_entries_fail_cleanly(tmp_path):
    """A non-object entry becomes a bad-baseline finding, not an
    AttributeError traceback; a non-list `entries` fails load."""
    import json

    import pytest

    from kubernetes_scheduler_tpu.analysis.core import (
        apply_baseline,
        load_baseline,
    )

    path = _baseline(tmp_path, ["oops", 7, {
        # hygiene pseudo-rules police the suppression machinery itself
        # and must never be baselinable
        "rule": "stale-baseline", "path": "LINT_BASELINE.json",
        "reason": "trying to silence the police",
    }])
    extra = apply_baseline([], load_baseline(path), path)
    assert [v.rule for v in extra] == ["bad-baseline"] * 3
    assert "str" in extra[0].message and "int" in extra[1].message
    assert "pseudo-rule" in extra[2].message

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"entries": {"rule": "x"}}))
    with pytest.raises(ValueError, match="entries"):
        load_baseline(str(bad))


def test_scoped_run_skips_stale_baseline_check(tmp_path):
    """A path/rule-scoped lint produces no findings for out-of-scope
    entries, so it cannot tell 'out of scope' from 'stale' — a live
    entry pointing elsewhere must not fail the scoped run; only the
    full-repo run polices baseline liveness."""
    from kubernetes_scheduler_tpu.analysis.__main__ import main
    from kubernetes_scheduler_tpu.analysis.core import (
        apply_baseline,
        load_baseline,
    )

    path = _baseline(tmp_path, [{
        "rule": "timeout-hygiene",
        "path": "kubernetes_scheduler_tpu/engine.py",
        "reason": "lives outside the scoped paths",
    }])
    rc = main([
        os.path.join(FIXTURES, "timeout_clean.py"),
        "--rules", "timeout-hygiene",
        "--baseline", path,
    ])
    assert rc == 0
    # the same entry against an empty finding set IS stale on a full run
    extra = apply_baseline(
        [], load_baseline(path), path, check_stale=True
    )
    assert [v.rule for v in extra] == ["stale-baseline"]


def test_checked_in_baseline_loads_and_is_explained():
    from kubernetes_scheduler_tpu.analysis.core import load_baseline

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    entries = load_baseline(os.path.join(root, "LINT_BASELINE.json"))
    assert all((e.get("reason") or "").strip() for e in entries)


# ---- docs-drift (README table <-> registry) -------------------------------


def test_docs_drift_fires_both_directions():
    from kubernetes_scheduler_tpu.analysis.core import _check_readme_rules

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # current table vs current registry: clean
    assert _check_readme_rules(root, RULES) == []
    # a family the table does not document
    fake = dict(RULES)
    fake["brand-new-family"] = RULES["host-sync"]
    vs = _check_readme_rules(root, fake)
    assert any("brand-new-family" in v.message for v in vs)
    # a documented family that is not registered
    missing = dict(RULES)
    missing.pop("host-sync")
    vs = _check_readme_rules(root, missing)
    assert any(
        "`host-sync`" in v.message and "not a registered" in v.message
        for v in vs
    )


# ---- SARIF ---------------------------------------------------------------


def test_sarif_render_validates_and_carries_waivers():
    from kubernetes_scheduler_tpu.analysis.sarif import (
        render_sarif,
        validate_sarif,
    )

    vs = run_lint(
        [os.path.join(FIXTURES, "waiver_fixture.py")],
        rules=["timeout-hygiene"],
    )
    doc = render_sarif(vs, {"timeout-hygiene": "timeouts everywhere"})
    validate_sarif(doc)  # must not raise
    results = doc["runs"][0]["results"]
    assert any(r.get("suppressions") for r in results)  # waivers survive
    assert any(r["level"] == "error" for r in results)
    rule_ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert "bad-waiver" in rule_ids  # pseudo-rules registered on the fly


def test_sarif_validator_rejects_malformed():
    from kubernetes_scheduler_tpu.analysis.sarif import validate_sarif

    with pytest.raises(ValueError, match="version"):
        validate_sarif({"version": "2.0.0", "runs": []})
    with pytest.raises(ValueError, match="ruleId"):
        validate_sarif({
            "$schema": "x/sarif-schema-2.1.0.json", "version": "2.1.0",
            "runs": [{
                "tool": {"driver": {"name": "g", "rules": []}},
                "results": [{"ruleId": "ghost", "level": "error",
                             "message": {"text": "m"}}],
            }],
        })


def test_lint_main_sarif_and_budget(capsys):
    import json

    rc = lint_main(
        [os.path.join(FIXTURES, "timeout_violation.py"),
         "--rules", "timeout-hygiene", "--format", "sarif"]
    )
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    from kubernetes_scheduler_tpu.analysis.sarif import validate_sarif

    validate_sarif(doc)
    # an absurd budget trips even a clean scoped run
    rc = lint_main(
        [os.path.join(FIXTURES, "timeout_clean.py"),
         "--rules", "timeout-hygiene", "--budget-seconds", "0.0"]
    )
    assert rc == 1
    assert "budget" in capsys.readouterr().err


def test_sim_determinism_messages_name_the_fix():
    msgs = [
        v.message
        for v in active(
            lint_fixture("sim_determinism_violation.py", "sim-determinism")
        )
    ]
    assert any("default_rng(seed)" in m for m in msgs)
    assert any("GLOBAL RNG" in m for m in msgs)
    # unseeded default_rng gets its own targeted message
    assert any("unseeded default_rng()" in m for m in msgs)


def test_sim_determinism_real_simulators_clean():
    import glob

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    real = glob.glob(
        os.path.join(repo_root, "kubernetes_scheduler_tpu", "sim", "**", "*.py"),
        recursive=True,
    )
    assert real, "sim/ sources not found"
    assert active(run_lint(real, rules=["sim-determinism"])) == []


def test_lint_main_exit_codes(capsys):
    rc = lint_main(
        [os.path.join(FIXTURES, "timeout_violation.py"),
         "--rules", "timeout-hygiene"]
    )
    assert rc == 1
    out = capsys.readouterr().out
    assert "timeout-hygiene" in out
    rc = lint_main(
        [os.path.join(FIXTURES, "timeout_clean.py"),
         "--rules", "timeout-hygiene"]
    )
    assert rc == 0


def test_lint_main_json_format(capsys):
    import json

    rc = lint_main(
        [os.path.join(FIXTURES, "lock_discipline_violation.py"),
         "--rules", "lock-discipline", "--format", "json"]
    )
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload and payload[0]["rule"] == "lock-discipline"


# ---- capability-completeness specifics ------------------------------------


def test_capability_completeness_names_every_gap():
    msgs = [
        v.message
        for v in active(lint_fixture(
            "capability_completeness_violation.py",
            "capability-completeness",
        ))
    ]
    # table vs proto, both directions, both sides of the bridge
    assert any("`cap_b` is missing from CAPABILITY_LATCHES" in m
               for m in msgs)
    assert any("`cap_zz` names no HealthReply bool" in m for m in msgs)
    assert any("`cap_b` is missing from CAPABILITY_SWITCHES" in m
               for m in msgs)
    # hand-rolled probe/invalidate instead of the table
    assert any("_probe_capabilities` does not iterate" in m for m in msgs)
    assert any("_invalidate_session` does not iterate" in m for m in msgs)
    # a latch nobody reads, a switch nobody assigns, a health() that
    # bypasses the table
    assert any("has no accessor" in m for m in msgs)
    assert any("never assigned" in m for m in msgs)
    assert any("does not render through" in m for m in msgs)
    # the except-path discipline (the historical Preempt gap)
    assert any("sends through _call_with_retry" in m for m in msgs)


def test_capability_completeness_on_the_real_bridge():
    """The live bridge wires every HealthReply bit end to end (this is
    the family that found the Preempt except-path gap)."""
    client = "kubernetes_scheduler_tpu/bridge/client.py"
    server = "kubernetes_scheduler_tpu/bridge/server.py"
    vs = active(run_lint([client, server],
                         rules=["capability-completeness"]))
    assert vs == [], [v.format() for v in vs]
    # and the proto reader sees the full capability set, fused_min_max
    # included
    from kubernetes_scheduler_tpu.analysis.rules.capability_completeness import (
        health_bool_fields,
    )
    from kubernetes_scheduler_tpu.bridge.client import CAPABILITY_LATCHES

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fields = health_bool_fields(
        os.path.join(root, "kubernetes_scheduler_tpu/bridge/schedule.proto")
    )
    assert fields == set(CAPABILITY_LATCHES)
    assert "fused_min_max" in fields


# ---- --changed-only: the fast pre-commit loop -----------------------------


def _full_ctx():
    sink = []
    run_lint(rules=["timeout-hygiene"], ctx_out=sink)
    return sink[0]


def test_reverse_dependency_closure_follows_imports_and_calls():
    from kubernetes_scheduler_tpu.analysis.core import (
        reverse_dependency_closure,
    )

    ctx = _full_ctx()
    client = "kubernetes_scheduler_tpu/bridge/client.py"
    closure = reverse_dependency_closure(ctx, {client})
    assert client in closure
    # the host scheduler dispatches through RemoteEngine — it depends
    # on the client, so a client edit pulls it into scope
    assert "kubernetes_scheduler_tpu/host/scheduler.py" in closure
    # kernel code has no path into the bridge client
    assert "kubernetes_scheduler_tpu/ops/normalize.py" not in closure
    # closure of nothing is nothing
    assert reverse_dependency_closure(ctx, set()) == set()


def test_changed_vs_ref_maps_proto_to_bridge(monkeypatch):
    import subprocess

    from kubernetes_scheduler_tpu.analysis import core as core_mod

    def fake_run(args, **kw):
        out = (
            "kubernetes_scheduler_tpu/bridge/schedule.proto\n"
            "kubernetes_scheduler_tpu/host/queue.py\n"
            "COLLECTIVE_BUDGET.json\n"
            "README.md\n"
            if args[1] == "diff" else ""
        )
        return subprocess.CompletedProcess(args, 0, stdout=out, stderr="")

    monkeypatch.setattr("subprocess.run", fake_run)
    changed = core_mod.changed_vs_ref(core_mod._REPO_ROOT, "HEAD")
    # proto edits pull the modules that encode the schema into scope;
    # non-package files are ignored
    assert "kubernetes_scheduler_tpu/bridge/client.py" in changed
    assert "kubernetes_scheduler_tpu/bridge/server.py" in changed
    assert "kubernetes_scheduler_tpu/host/queue.py" in changed
    assert "README.md" not in changed
    # a budget edit pulls the sharded surfaces it pins into scope
    assert "kubernetes_scheduler_tpu/parallel/engine.py" in changed


def test_changed_only_findings_subset_of_full(tmp_path, monkeypatch, capsys):
    """The pinned --changed-only contract: a scoped run never reports a
    finding the full run would not."""
    import json

    from kubernetes_scheduler_tpu.analysis import core as core_mod

    monkeypatch.setattr(
        core_mod, "changed_vs_ref",
        lambda root, ref: {"kubernetes_scheduler_tpu/bridge/client.py"},
    )
    full_art = tmp_path / "full.json"
    changed_art = tmp_path / "changed.json"
    base = ["--no-contracts", "--no-models", "--no-baseline"]
    assert lint_main(base + ["--json-artifact", str(full_art)]) == 0
    assert lint_main(
        base + ["--changed-only", "HEAD", "--json-artifact",
                str(changed_art)]
    ) == 0
    capsys.readouterr()
    key = lambda v: (v["rule"], v["path"], v["line"])  # noqa: E731
    full = {key(v) for v in json.loads(full_art.read_text())}
    changed = {key(v) for v in json.loads(changed_art.read_text())}
    assert changed <= full
    # and the scoped run is non-trivial: the closure of the bridge
    # client reaches the host scheduler's waived boundary syncs
    assert any(p.startswith("kubernetes_scheduler_tpu/") for _, p, _ in changed)


def test_changed_only_spmd_surfaces_wired():
    """The new SPMD surfaces ride the changed-only machinery: a
    parallel/ edit's closure contains the edited file, the contracts
    SURFACE patterns match it (so a changed-only run re-traces the
    sharded contracts + collective budget), and the spmd_mutants
    harness file is itself on the surface. Changed-only ⊆ full-run is
    already pinned family-independently above; this pins the surface
    tuples the subset guarantee rides on for the sixteenth family."""
    import fnmatch

    from kubernetes_scheduler_tpu.analysis.contracts import SURFACE
    from kubernetes_scheduler_tpu.analysis.core import (
        reverse_dependency_closure,
    )

    engine_path = "kubernetes_scheduler_tpu/parallel/engine.py"
    ctx = _full_ctx()
    closure = reverse_dependency_closure(ctx, {engine_path})
    assert engine_path in closure
    for p in (
        engine_path,
        "kubernetes_scheduler_tpu/parallel/mesh.py",
        "kubernetes_scheduler_tpu/analysis/spmd.py",
        "kubernetes_scheduler_tpu/analysis/spmd_mutants.py",
    ):
        assert any(fnmatch.fnmatch(p, pat) for pat in SURFACE), p


def test_changed_only_rejects_explicit_paths(capsys):
    with pytest.raises(SystemExit) as e:
        lint_main(["--changed-only", "HEAD",
                   "kubernetes_scheduler_tpu/engine.py"])
    assert e.value.code == 2
    capsys.readouterr()


# ---- the capstone: the repo itself lints clean ----------------------------


def test_repo_lints_clean():
    """`make lint` must exit 0: every genuine violation in the tree is
    either fixed or carries an inline justification. New unwaived
    findings fail HERE, in tier-1, before CI even reaches `make lint`."""
    vs = run_lint()
    bad = active(vs)
    assert bad == [], "\n".join(v.format() for v in bad)
    # the waivers that exist all carry their justifications
    assert all(v.waiver_reason for v in vs if v.waived)
