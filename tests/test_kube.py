"""Kubernetes API boundary: conversion, list/watch/bind e2e, Lease CAS.

Hermetic: every test runs against tests/fake_kube.FakeKube, an
httptest-style stdlib server with resourceVersion CAS on leases and
fieldSelector filtering on pods — no cluster required (the reference's
own tests hit live services; SURVEY.md §4 calls for fixing that here).
"""

import time

import pytest

from kubernetes_scheduler_tpu.host import NodeUtil, Scheduler, StaticAdvisor
from kubernetes_scheduler_tpu.host.leader import LeaderElector, LeaseRecord
from kubernetes_scheduler_tpu.kube import (
    KubeApiError,
    KubeBinder,
    KubeClient,
    KubeClusterSource,
    KubeConfig,
    KubeEvictor,
    KubeLease,
    node_from_api,
    pod_from_api,
)
from kubernetes_scheduler_tpu.kube.source import run_kube_loop
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig
from tests.fake_kube import FakeKube, make_node_obj, make_pod_obj


@pytest.fixture()
def fake():
    srv = FakeKube().start()
    yield srv
    srv.stop()


def client_for(fake, **kw):
    return KubeClient(KubeConfig(base_url=fake.url, **kw))


# ---- conversion ---------------------------------------------------------


def test_pod_from_api_full_spec():
    obj = {
        "metadata": {
            "name": "web-0",
            "namespace": "prod",
            "labels": {"app": "web", "scv/priority": "3"},
            "annotations": {"diskIO": "10"},
        },
        "spec": {
            "schedulerName": "yoda-tpu",
            "nodeSelector": {"disk": "ssd"},
            "containers": [
                {
                    "resources": {
                        "requests": {"cpu": "500m", "memory": "2Gi"}
                    },
                    "ports": [{"containerPort": 80, "hostPort": 8080}],
                },
                {"resources": {"requests": {"cpu": "1"}}},
            ],
            "initContainers": [
                {"resources": {"requests": {"memory": "4Gi"}}}
            ],
            "overhead": {"cpu": "100m"},
            "tolerations": [
                {"key": "gpu", "operator": "Exists", "effect": "NoSchedule"}
            ],
            "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {
                                "matchExpressions": [
                                    {
                                        "key": "zone",
                                        "operator": "In",
                                        "values": ["a", "b"],
                                    }
                                ]
                            }
                        ]
                    },
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "weight": 7,
                            "preference": {
                                "matchExpressions": [
                                    {"key": "fast", "operator": "Exists"}
                                ]
                            },
                        }
                    ],
                },
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "labelSelector": {"matchLabels": {"app": "web"}},
                            "topologyKey": "zone",
                        }
                    ]
                },
                "podAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "weight": 5,
                            "podAffinityTerm": {
                                "labelSelector": {
                                    "matchLabels": {"app": "cache"}
                                },
                                "topologyKey": "kubernetes.io/hostname",
                            },
                        }
                    ]
                },
            },
            "topologySpreadConstraints": [
                {
                    "maxSkew": 2,
                    "topologyKey": "zone",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"app": "web"}},
                },
                {
                    "maxSkew": 1,
                    "topologyKey": "zone",
                    "whenUnsatisfiable": "ScheduleAnyway",
                    "labelSelector": {"matchLabels": {"app": "web"}},
                },
            ],
        },
        "status": {"phase": "Pending"},
    }
    pod = pod_from_api(obj)
    assert pod.name == "web-0" and pod.namespace == "prod"
    assert pod.labels["scv/priority"] == "3"
    assert pod.annotations["diskIO"] == "10"
    # cpu -> millicores, memory -> bytes
    assert pod.containers[0].requests == {"cpu": 500.0, "memory": 2 * 2**30}
    assert pod.containers[1].requests == {"cpu": 1000.0}
    assert pod.init_containers[0].requests == {"memory": 4 * 2**30}
    assert pod.overhead == {"cpu": 100.0}
    assert pod.tolerations[0].operator == "Exists"
    # nodeSelector AND first nodeSelectorTerm
    ops = {(e.key, e.operator) for e in pod.node_affinity}
    assert ops == {("disk", "In"), ("zone", "In")}
    assert pod.preferred_node_affinity[0].weight == 7
    terms = {(t.topology_key, t.anti, t.preferred) for t in pod.pod_affinity}
    assert ("zone", True, False) in terms
    assert ("kubernetes.io/hostname", False, True) in terms
    # both whenUnsatisfiable modes convert: DoNotSchedule hard,
    # ScheduleAnyway soft
    assert len(pod.topology_spread) == 2
    hard = [sc for sc in pod.topology_spread if not sc.soft]
    soft = [sc for sc in pod.topology_spread if sc.soft]
    assert len(hard) == 1 and hard[0].max_skew == 2
    assert len(soft) == 1 and soft[0].max_skew == 1
    assert pod.host_ports == [8080]
    assert pod.node_name is None and pod.target_node is None


def test_pod_from_api_or_of_ands_node_affinity():
    """ALL nodeSelectorTerms are kept as OR groups (upstream semantics),
    nodeSelector is ANDed into every group, and an empty term becomes the
    matches-nothing encoding."""
    obj = {
        "metadata": {"name": "multi-term"},
        "spec": {
            "nodeSelector": {"disk": "ssd"},
            "containers": [{}],
            "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {
                                "matchExpressions": [
                                    {"key": "zone", "operator": "In",
                                     "values": ["a"]},
                                    {"key": "arch", "operator": "Exists"},
                                ]
                            },
                            {
                                "matchExpressions": [
                                    {"key": "zone", "operator": "In",
                                     "values": ["b"]},
                                ]
                            },
                            {},  # empty term: matches nothing
                        ]
                    }
                }
            },
        },
    }
    pod = pod_from_api(obj)
    by_term: dict[int, list] = {}
    for e in pod.node_affinity:
        by_term.setdefault(e.term, []).append(e)
    assert sorted(by_term) == [0, 1, 2]
    # every group carries the nodeSelector conjunct
    for t, exprs in by_term.items():
        assert any(
            e.key == "disk" and e.operator == "In" and e.values == ["ssd"]
            for e in exprs
        ), t
    assert {(e.key, e.operator) for e in by_term[0]} == {
        ("zone", "In"), ("arch", "Exists"), ("disk", "In")
    }
    assert any(e.key == "zone" and e.values == ["b"] for e in by_term[1])
    # the empty term's placeholder: In with no values, satisfiable nowhere
    assert any(
        e.operator == "In" and e.values == [] for e in by_term[2]
    )


def test_pod_from_api_affinity_namespace_scope():
    """PodAffinityTerm namespace scope converts per upstream: default =
    the pod's own namespace; explicit `namespaces` honored; the `{}`
    namespaceSelector selects ALL namespaces (exactly, per upstream)."""
    obj = {
        "metadata": {"name": "scoped", "namespace": "prod"},
        "spec": {
            "containers": [{}],
            "affinity": {"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "a"}},
                     "topologyKey": "zone"},
                    {"labelSelector": {"matchLabels": {"app": "b"}},
                     "namespaces": ["x", "y"], "topologyKey": "zone"},
                    {"labelSelector": {"matchLabels": {"app": "c"}},
                     "namespaceSelector": {}, "topologyKey": "zone"},
                ],
            }},
        },
    }
    pod = pod_from_api(obj)
    by_app = {t.match_labels["app"]: t.namespaces for t in pod.pod_affinity}
    assert by_app["a"] == ["prod"]
    assert by_app["b"] == ["x", "y"]
    assert by_app["c"] is None  # all namespaces

    # spread selectors scope to the pod's own namespace
    obj2 = {
        "metadata": {"name": "sp", "namespace": "prod"},
        "spec": {
            "containers": [{}],
            "topologySpreadConstraints": [{
                "maxSkew": 1, "topologyKey": "zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": "w"}},
            }],
        },
    }
    assert pod_from_api(obj2).topology_spread[0].namespaces == ["prod"]


def test_namespace_selector_resolution():
    """A NON-empty namespaceSelector captures the label selector at
    conversion and resolves exactly against a namespace set: matched
    namespaces UNION any explicit `namespaces` entries (upstream
    k8s >= 1.21 semantics); with no namespace data it degrades to the
    ALL-namespaces approximation."""
    from kubernetes_scheduler_tpu.kube.convert import (
        resolve_namespace_selectors,
    )

    obj = {
        "metadata": {"name": "sel", "namespace": "prod"},
        "spec": {
            "containers": [{}],
            "affinity": {"podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "db"}},
                     "namespaceSelector": {"matchLabels": {"team": "be"}},
                     "namespaces": ["extra"], "topologyKey": "zone"},
                ],
            }},
        },
    }
    pod = pod_from_api(obj)
    term = pod.pod_affinity[0]
    assert term.namespace_selector == ({"team": "be"}, [])
    assert term.namespaces == ["extra"]  # unresolved: explicit only

    nss = {"a": {"team": "be"}, "b": {"team": "web"}, "c": {"team": "be"}}
    resolved = resolve_namespace_selectors(pod, nss)
    assert resolved.pod_affinity[0].namespaces == ["a", "c", "extra"]
    # selector matches nothing and no explicit list -> empty scope
    obj["spec"]["affinity"]["podAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"
    ][0].pop("namespaces")
    none = resolve_namespace_selectors(pod_from_api(obj), {"b": {"team": "web"}})
    assert none.pod_affinity[0].namespaces == []
    # no namespace data: ALL-namespaces approximation (logged)
    degraded = resolve_namespace_selectors(pod, None)
    assert degraded.pod_affinity[0].namespaces is None


def test_pod_from_api_preferred_term_groups():
    """Multi-expression preferred terms convert with shared group ids:
    the weight is granted once per fully-matching entry."""
    obj = {
        "metadata": {"name": "pref"},
        "spec": {
            "containers": [{}],
            "affinity": {"nodeAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": 7, "preference": {"matchExpressions": [
                        {"key": "a", "operator": "Exists"},
                        {"key": "b", "operator": "Exists"},
                    ]}},
                    {"weight": 3, "preference": {"matchExpressions": [
                        {"key": "c", "operator": "Exists"},
                    ]}},
                ]
            }},
        },
    }
    pod = pod_from_api(obj)
    by_term = {}
    for w in pod.preferred_node_affinity:
        by_term.setdefault(w.term, []).append((w.expr.key, w.weight))
    assert by_term == {0: [("a", 7), ("b", 7)], 1: [("c", 3)]}


def test_pod_from_api_spec_priority_wins():
    """spec.priority (PriorityClass admission) outranks the reference's
    scv/priority label; absent spec falls back to the label."""
    from kubernetes_scheduler_tpu.host.queue import pod_priority

    both = pod_from_api({
        "metadata": {"name": "b", "labels": {"scv/priority": "3"}},
        "spec": {"priority": 1000000, "containers": [{}]},
    })
    assert both.priority == 1000000 and pod_priority(both) == 1000000
    label_only = pod_from_api({
        "metadata": {"name": "l", "labels": {"scv/priority": "3"}},
        "spec": {"containers": [{}]},
    })
    assert label_only.priority is None and pod_priority(label_only) == 3
    neither = pod_from_api({"metadata": {"name": "n"},
                            "spec": {"containers": [{}]}})
    assert pod_priority(neither) == 0


def test_node_from_api_cordoned():
    """spec.unschedulable (kubectl cordon) converts to the well-known
    unschedulable taint, so cordoned nodes filter like upstream's
    NodeUnschedulable plugin — and a toleration for it still admits."""
    from kubernetes_scheduler_tpu.kube.convert import node_from_api

    node = node_from_api({
        "metadata": {"name": "cordoned"},
        "spec": {"unschedulable": True},
        "status": {"allocatable": {"cpu": "4"}},
    })
    assert any(
        t.key == "node.kubernetes.io/unschedulable"
        and t.effect == "NoSchedule"
        for t in node.taints
    )
    # already-tainted node (the taint-nodes controller beat us): no dupe
    node2 = node_from_api({
        "metadata": {"name": "c2"},
        "spec": {
            "unschedulable": True,
            "taints": [{"key": "node.kubernetes.io/unschedulable",
                        "effect": "NoSchedule"}],
        },
        "status": {},
    })
    assert (
        sum(t.key == "node.kubernetes.io/unschedulable" for t in node2.taints)
        == 1
    )
    plain = node_from_api({"metadata": {"name": "open"}, "spec": {},
                           "status": {}})
    assert not plain.taints


def test_pod_from_api_match_fields():
    """matchFields convert as ordinary expressions keyed metadata.name,
    joining the term's matchExpressions conjunct."""
    obj = {
        "metadata": {"name": "fields"},
        "spec": {
            "containers": [{}],
            "affinity": {"nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{
                        "matchExpressions": [
                            {"key": "zone", "operator": "In", "values": ["a"]}
                        ],
                        "matchFields": [
                            {"key": "metadata.name", "operator": "NotIn",
                             "values": ["cordoned-node"]}
                        ],
                    }]
                }
            }},
        },
    }
    pod = pod_from_api(obj)
    got = {(e.key, e.operator, tuple(e.values)) for e in pod.node_affinity}
    assert got == {
        ("zone", "In", ("a",)),
        ("metadata.name", "NotIn", ("cordoned-node",)),
    }
    assert {e.term for e in pod.node_affinity} == {0}


def test_pod_from_api_pinned_and_running():
    pending = pod_from_api(
        {
            "metadata": {"name": "p"},
            "spec": {"nodeName": "n3", "containers": []},
            "status": {"phase": "Pending"},
        }
    )
    assert pending.target_node == "n3"  # upstream NodeName filter input
    running = pod_from_api(
        {
            "metadata": {"name": "r"},
            "spec": {"nodeName": "n3", "containers": []},
            "status": {"phase": "Running"},
        }
    )
    assert running.target_node is None and running.node_name == "n3"


def test_node_from_api():
    node = node_from_api(
        {
            "metadata": {
                "name": "n0",
                "labels": {"zone": "a"},
                "annotations": {
                    "scv/cards": '[{"clock": 1500, "free_memory": 8000, '
                    '"total_memory": 16000, "health": "Healthy"}]'
                },
            },
            "spec": {
                "taints": [{"key": "gpu", "value": "yes", "effect": "NoSchedule"}]
            },
            "status": {
                "allocatable": {
                    "cpu": "7500m",
                    "memory": "30Gi",
                    "pods": "110",
                    "nvidia.com/gpu": "2",
                }
            },
        }
    )
    assert node.allocatable["cpu"] == 7500.0
    assert node.allocatable["memory"] == 30 * 2**30
    assert node.allocatable["nvidia.com/gpu"] == 2.0
    assert node.taints[0].key == "gpu"
    assert node.cards[0].clock == 1500 and node.cards[0].health == "Healthy"


# ---- client + source against the fake API server ------------------------


def test_list_nodes_running_pending(fake):
    fake.add_node(make_node_obj("n0"))
    fake.add_node(make_node_obj("n1", taints=[{"key": "x", "effect": "NoSchedule"}]))
    fake.add_pod(make_pod_obj("running-1", node_name="n0"))
    fake.add_pod(make_pod_obj("pending-1"))
    fake.add_pod(make_pod_obj("other-sched", scheduler_name="default-scheduler"))
    src = KubeClusterSource(client_for(fake), scheduler_name="yoda-tpu")
    assert [n.name for n in src.list_nodes()] == ["n0", "n1"]
    assert [p.name for p in src.list_running_pods()] == ["running-1"]
    assert [p.name for p in src.list_pending_pods()] == ["pending-1"]
    # watch yields the same pending set (bounded ADDED stream)
    assert [p.name for p in src.watch_pending(timeout_seconds=5)] == ["pending-1"]


def test_bearer_token_enforced():
    srv = FakeKube(token="sekret").start()
    try:
        with pytest.raises(KubeApiError) as ei:
            KubeClient(KubeConfig(base_url=srv.url)).get("/api/v1/nodes")
        assert ei.value.status == 401
        ok = KubeClient(KubeConfig(base_url=srv.url, token="sekret"))
        assert ok.get("/api/v1/nodes") == {"items": []}
    finally:
        srv.stop()


def test_binder_posts_binding_and_conflicts(fake):
    fake.add_pod(make_pod_obj("p0"))
    client = client_for(fake)
    binder = KubeBinder(client)
    pod = pod_from_api(fake.pods["default/p0"])
    binder.bind(pod, "n5")
    assert fake.bindings == [("default/p0", "n5")]
    assert fake.pods["default/p0"]["spec"]["nodeName"] == "n5"
    # double bind -> 409 surfaces as KubeApiError
    with pytest.raises(KubeApiError) as ei:
        binder.bind(pod, "n6")
    assert ei.value.status == 409


def test_pdb_conversion_and_listing(fake):
    from kubernetes_scheduler_tpu.kube import KubeClusterSource, pdb_from_api

    obj = {
        "metadata": {"name": "db-pdb", "namespace": "prod"},
        "spec": {
            "minAvailable": "50%",
            "selector": {"matchLabels": {"app": "db"}},
        },
        "status": {"disruptionsAllowed": 1},
    }
    pdb = pdb_from_api(obj)
    assert pdb.name == "db-pdb" and pdb.namespace == "prod"
    assert pdb.match_labels == {"app": "db"}
    assert pdb.allowed(4) == 1  # status wins over the 50% spec math

    fake.pdbs.append(obj)
    client = client_for(fake)
    source = KubeClusterSource(client, scheduler_name="yoda-tpu")
    pdbs = source.list_pdbs()
    assert len(pdbs) == 1 and pdbs[0].name == "db-pdb"


def test_evictor_deletes_with_uid_precondition(fake):
    from kubernetes_scheduler_tpu.kube import KubeEvictor

    fake.add_pod(make_pod_obj("victim", uid="uid-1"))
    client = client_for(fake)
    ev = KubeEvictor(client)
    victim = pod_from_api(fake.pods["default/victim"])
    preemptor = pod_from_api(make_pod_obj("urgent"))

    # stale UID: the name was recreated since the snapshot -> no delete
    stale = pod_from_api(make_pod_obj("victim", uid="uid-OLD"))
    ev.evict(stale, preemptor=preemptor)
    assert "default/victim" in fake.pods and not fake.deleted

    ev.evict(victim, preemptor=preemptor)
    assert fake.deleted == ["default/victim"]
    assert "default/victim" not in fake.pods
    assert ev.evicted == ["uid-1"]

    # already gone: 404 swallowed
    ev.evict(victim, preemptor=preemptor)
    assert fake.deleted == ["default/victim"]


def _ns_selector_spec(team: str, anti: bool = False) -> dict:
    kind = "podAntiAffinity" if anti else "podAffinity"
    return {"affinity": {kind: {
        "requiredDuringSchedulingIgnoredDuringExecution": [{
            "labelSelector": {"matchLabels": {"app": "db"}},
            "namespaceSelector": {"matchLabels": {"team": team}},
            "topologyKey": "kubernetes.io/hostname",
        }],
    }}}


def test_namespace_selector_exact_e2e(fake):
    """Exact namespaceSelector end-to-end: terms resolve against the
    live namespace set, so affinity admits only selector-matched
    namespaces and anti-affinity is not over-constrained by pods in
    unmatched ones (round-4 verdict: the ALL-namespaces approximation
    over-admitted the first and wrongly blocked the second)."""
    fake.add_namespace("default")
    fake.add_namespace("ns-a", {"team": "backend"})
    fake.add_namespace("ns-b", {"team": "web"})
    fake.add_node(make_node_obj("n0"))
    # anchor: a running db pod in ns-a (team=backend) on the only node
    fake.add_pod(make_pod_obj(
        "anchor", namespace="ns-a", node_name="n0", labels={"app": "db"}
    ))
    fake.add_pod(make_pod_obj(
        "wants-backend", extra_spec=_ns_selector_spec("backend")
    ))
    fake.add_pod(make_pod_obj(
        "wants-web", extra_spec=_ns_selector_spec("web")
    ))
    fake.add_pod(make_pod_obj(
        "avoids-web", extra_spec=_ns_selector_spec("web", anti=True)
    ))
    fake.add_pod(make_pod_obj(
        "avoids-backend", extra_spec=_ns_selector_spec("backend", anti=True)
    ))
    client = client_for(fake)
    src = KubeClusterSource(client, scheduler_name="yoda-tpu")
    sched = Scheduler(
        SchedulerConfig(batch_window=64, min_device_work=0),
        advisor=StaticAdvisor({"n0": NodeUtil(cpu_pct=10, disk_io=3)}),
        binder=KubeBinder(client),
        list_nodes=src.list_nodes,
        list_running_pods=src.list_running_pods,
    )
    for p in src.list_pending_pods():
        sched.submit(p)
    sched.run_cycle()
    bound = {k.split("/")[1] for k, _ in fake.bindings}
    # affinity: the anchor's namespace matches team=backend -> binds;
    # team=web selects only the db-less ns-b -> unschedulable
    assert "wants-backend" in bound
    assert "wants-web" not in bound
    # anti-affinity: the anchor is OUTSIDE team=web's scope -> n0 open;
    # inside team=backend's scope -> blocked
    assert "avoids-web" in bound
    assert "avoids-backend" not in bound


def test_namespace_selector_degrades_without_namespace_data(fake):
    """With the namespace list unavailable (404/RBAC), selectors fall
    back to the logged ALL-namespaces approximation — over-admitting
    affinity rather than silently matching nothing."""
    assert fake.namespaces is None  # route disabled
    fake.add_node(make_node_obj("n0"))
    fake.add_pod(make_pod_obj(
        "anchor", namespace="ns-a", node_name="n0", labels={"app": "db"}
    ))
    fake.add_pod(make_pod_obj(
        "wants-web", extra_spec=_ns_selector_spec("web")
    ))
    client = client_for(fake)
    src = KubeClusterSource(client, scheduler_name="yoda-tpu")
    sched = Scheduler(
        SchedulerConfig(batch_window=64, min_device_work=0),
        advisor=StaticAdvisor({"n0": NodeUtil(cpu_pct=10, disk_io=3)}),
        binder=KubeBinder(client),
        list_nodes=src.list_nodes,
        list_running_pods=src.list_running_pods,
    )
    for p in src.list_pending_pods():
        sched.submit(p)
    sched.run_cycle()
    assert {k.split("/")[1] for k, _ in fake.bindings} == {"wants-web"}


def test_kube_loop_watch_cycle_bind_e2e(fake):
    """The VERDICT-prescribed e2e: fake API server driving
    watch -> cycle -> bind. Nodes and pending pods live only on the
    server; the scheduler sees them through KubeClusterSource and the
    placements land back on the server through KubeBinder."""
    for i in range(4):
        fake.add_node(make_node_obj(f"n{i}"))
    fake.add_pod(make_pod_obj("running-0", node_name="n0", cpu="2"))
    for i in range(6):
        fake.add_pod(
            make_pod_obj(
                f"job-{i}", cpu="250m", labels={"scv/priority": str(i % 3)},
                annotations={"diskIO": "5"},
            )
        )
    client = client_for(fake)
    src = KubeClusterSource(client, scheduler_name="yoda-tpu")
    binder = KubeBinder(client)
    utils = {f"n{i}": NodeUtil(cpu_pct=10 * i, disk_io=3 * i) for i in range(4)}
    sched = Scheduler(
        SchedulerConfig(batch_window=64, min_device_work=0),
        advisor=StaticAdvisor(utils),
        binder=binder,
        list_nodes=src.list_nodes,
        list_running_pods=src.list_running_pods,
    )
    cycles = run_kube_loop(
        sched, src,
        max_cycles=4, idle_sleep=0.01, watch_timeout=5,
        stop=lambda: len(fake.bindings) >= 6,
    )
    assert cycles >= 1
    assert sorted(k for k, _ in fake.bindings) == [
        f"default/job-{i}" for i in range(6)
    ]
    for _, node in fake.bindings:
        assert node in {f"n{i}" for i in range(4)}
    # server state reflects every placement; nothing is pending anymore
    assert [p.name for p in src.list_pending_pods()] == []


def test_sigterm_releases_lease(fake, tmp_path, capsys, monkeypatch):
    """Kubernetes stops pods with SIGTERM: the serve loop must release
    the leader Lease on the way out (an unreleased lease stalls standby
    failover for the whole lease duration). Simulated by raising the
    CLI's SIGTERM translation (SystemExit) from inside the loop."""
    import json as _json

    import kubernetes_scheduler_tpu.cli as cli
    import kubernetes_scheduler_tpu.kube.source as kube_source

    fake.add_node(make_node_obj("n0"))
    fake.prom["n0"] = {"cpu_pct": 10.0, "disk_io": 3.0}
    host = fake.url.removeprefix("http://")
    cfg_file = tmp_path / "cfg.json"
    cfg_file.write_text(
        _json.dumps({"batch_window": 8, "min_device_work": 0,
                     "advisor": {"prometheus_host": host}})
    )

    def boom(*a, **kw):
        raise SystemExit(0)  # what cli._terminate raises on SIGTERM

    monkeypatch.setattr(kube_source, "run_kube_loop", boom)
    rc = cli.main([
        "scheduler", "--source", "kube", "--kube-server", fake.url,
        "--config", str(cfg_file), "--watch-timeout", "5",
        "--lease-kube",
    ])
    assert rc == 0  # clean exit: totals printed, no traceback
    # the finally block released the lease: the fake server's Lease
    # object exists and carries an EMPTY holderIdentity
    lease = next(iter(fake.leases.values()), None)
    assert lease is not None
    holder = ((lease.get("spec") or {}).get("holderIdentity")) or ""
    assert holder == ""


def test_kube_preemption_e2e(fake):
    """Live-path preemption: a high-priority pod that fits nowhere
    evicts a lower-priority victim THROUGH the API server (KubeEvictor
    DELETE), the eviction becomes visible via the cluster source, and
    the preemptor binds on a later cycle — while a PDB-protected victim
    is never touched."""
    fake.add_node(make_node_obj("n0", cpu="1"))
    fake.add_node(make_node_obj("n1", cpu="1"))
    victim = make_pod_obj(
        "victim", node_name="n0", cpu="900m", uid="v-1",
        labels={"scv/priority": "1"},
    )
    guarded = make_pod_obj(
        "guarded", node_name="n1", cpu="900m", uid="g-1",
        labels={"scv/priority": "0", "app": "db"},
    )
    fake.add_pod(victim)
    fake.add_pod(guarded)
    fake.pdbs.append({
        "metadata": {"name": "db-pdb"},
        "spec": {"maxUnavailable": 0,
                 "selector": {"matchLabels": {"app": "db"}}},
    })
    fake.add_pod(make_pod_obj(
        "urgent", cpu="800m", labels={"scv/priority": "9"},
        annotations={"diskIO": "3"},
    ))
    client = client_for(fake)
    src = KubeClusterSource(client, scheduler_name="yoda-tpu")
    utils = {"n0": NodeUtil(cpu_pct=10, disk_io=3),
             "n1": NodeUtil(cpu_pct=20, disk_io=5)}
    sched = Scheduler(
        SchedulerConfig(batch_window=8, min_device_work=0,
                        adaptive_dispatch=False),
        advisor=StaticAdvisor(utils),
        binder=KubeBinder(client),
        evictor=KubeEvictor(client),
        list_nodes=src.list_nodes,
        list_running_pods=src.list_running_pods,
        list_pdbs=src.list_pdbs,
    )
    for p in src.list_pending_pods():
        sched.submit(p)
    m1 = sched.run_cycle()
    # urgent fits nowhere; the unprotected prio-1 victim is DELETEd on
    # the server, the PDB-guarded prio-0 pod is not
    assert m1.pods_unschedulable == 1 and m1.pods_preempted == 1
    assert fake.deleted == ["default/victim"]
    assert "default/guarded" in fake.pods

    # the DELETE is immediately visible through the source (no grace
    # period on the fake server): the requeued preemptor binds on n0
    sched.queue._clock = lambda: 1e9
    m2 = sched.run_cycle()
    assert m2.pods_bound == 1
    assert ("default/urgent", "n0") in fake.bindings


# ---- Lease backend ------------------------------------------------------


def test_kube_lease_cas_and_elector(fake):
    client = client_for(fake)
    a = KubeLease(client, name="sched", namespace="kube-system")
    b = KubeLease(client, name="sched", namespace="kube-system")
    now = time.time()
    rec_a = LeaseRecord(holder="A", acquired_at=now, renewed_at=now, duration=5)
    assert a.read() is None
    assert a.try_claim(rec_a, None)
    got = b.read()
    assert got.holder == "A" and abs(got.renewed_at - now) < 0.01
    # stale CAS: B claims with previous=None while A holds -> refused
    rec_b = LeaseRecord(holder="B", acquired_at=now, renewed_at=now, duration=5)
    assert not b.try_claim(rec_b, None)
    # A renews against its own previous
    rec_a2 = LeaseRecord(
        holder="A", acquired_at=now, renewed_at=now + 1, duration=5
    )
    assert a.try_claim(rec_a2, got)
    # B steals with the correct previous (as after expiry)
    cur = b.read()
    assert b.try_claim(
        LeaseRecord(holder="B", acquired_at=now + 2, renewed_at=now + 2, duration=5),
        cur,
    )
    assert a.read().holder == "B"
    b.clear("B")
    assert a.read() is None


def test_kube_lease_leader_election_failover(fake):
    """Two replicas on the cluster Lease: standby acquires only after the
    active holder's lease expires — client-go failover semantics on the
    coordination.k8s.io backend."""
    client = client_for(fake)
    active = LeaderElector(
        KubeLease(client, name="ha"), identity="active",
        lease_duration=0.5, retry_period=0.05,
    )
    standby = LeaderElector(
        KubeLease(client, name="ha"), identity="standby",
        lease_duration=0.5, retry_period=0.05,
    )
    assert active.acquire_blocking(timeout=2)
    assert not standby.acquire_blocking(timeout=0.2)
    # active dies without releasing: stop renewals, keep the lease record
    active._stop.set()
    assert standby.acquire_blocking(timeout=5)
    assert standby.is_leader()
    standby.release()


def test_cli_source_kube_one_shot(fake, capsys, tmp_path):
    """`scheduler --source kube` end-to-end: flags -> KubeClient ->
    watch -> cycle -> Binding POSTs -> one-shot idle exit. The fake
    server doubles as the Prometheus endpoint, so the live
    PrometheusAdvisor path is exercised too."""
    import json as _json

    from kubernetes_scheduler_tpu.cli import main

    for i in range(3):
        fake.add_node(make_node_obj(f"n{i}"))
        fake.prom[f"n{i}"] = {"cpu_pct": 10.0 * i, "disk_io": 4.0 * i}
    for i in range(4):
        fake.add_pod(make_pod_obj(f"w-{i}", cpu="200m", annotations={"diskIO": "5"}))
    host = fake.url.removeprefix("http://")
    cfg_file = tmp_path / "cfg.json"
    cfg_file.write_text(
        _json.dumps({"batch_window": 64, "min_device_work": 0,
                     "advisor": {"prometheus_host": host}})
    )
    rc = main(
        [
            "scheduler",
            "--source", "kube",
            "--kube-server", fake.url,
            "--config", str(cfg_file),
            "--watch-timeout", "5",
        ]
    )
    assert rc == 0
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["pods_bound"] == 4 and out["pods_unschedulable"] == 0
    assert sorted(k for k, _ in fake.bindings) == [
        f"default/w-{i}" for i in range(4)
    ]


def test_bind_race_does_not_kill_cycle(fake):
    """Routine lifecycle races on bind (pod deleted -> 404, bound by a
    racer -> 409) must drop the pod and keep the cycle alive; transient
    errors requeue with backoff. A binder exception previously escaped
    run_cycle and killed the serve-forever loop."""
    for i in range(3):
        fake.add_node(make_node_obj(f"n{i}"))
    for name in ("ok-0", "gone-1", "ok-2"):
        fake.add_pod(make_pod_obj(name, cpu="200m", annotations={"diskIO": "2"}))
    client = client_for(fake)
    src = KubeClusterSource(client)
    sched = Scheduler(
        SchedulerConfig(batch_window=64, min_device_work=0),
        advisor=StaticAdvisor(
            {f"n{i}": NodeUtil(cpu_pct=10 * i, disk_io=i) for i in range(3)}
        ),
        binder=KubeBinder(client),
        list_nodes=src.list_nodes,
        list_running_pods=src.list_running_pods,
    )
    for pod in src.list_pending_pods():
        sched.submit(pod)
    # user deletes one pod between queue admission and the bind POST
    del fake.pods["default/gone-1"]
    m = sched.run_cycle()
    assert m.pods_bound == 2 and m.pods_dropped == 1
    assert m.pods_unschedulable == 0  # a race is churn, not a failure
    assert sorted(k for k, _ in fake.bindings) == [
        "default/ok-0", "default/ok-2"
    ]
    assert len(sched.queue) == 0  # 404 drops; no eternal rebind loop


def test_informer_cache_sync_and_assume(fake):
    """InformerCache serves nodes/assigned pods from local state, applies
    relist reconciliation, and `assume` makes a just-bound pod visible to
    the very next cycle (capacity cannot be double-sold while the watch
    echo is in flight)."""
    from kubernetes_scheduler_tpu.kube.source import InformerCache

    fake.add_node(make_node_obj("n0"))
    fake.add_pod(make_pod_obj("sys", node_name="n0", cpu="1"))
    cache = InformerCache(client_for(fake), watch_timeout=2).start()
    try:
        assert cache.wait_synced(timeout=10)
        assert [n.name for n in cache.nodes()] == ["n0"]
        assert [p.name for p in cache.running_pods()] == ["sys"]
        # bind through a cache-aware binder: immediately visible
        fake.add_pod(make_pod_obj("w0", cpu="200m"))
        binder = KubeBinder(client_for(fake), cache=cache)
        pod = pod_from_api(fake.pods["default/w0"])
        binder.bind(pod, "n0")
        names = {p.name for p in cache.running_pods()}
        assert "w0" in names  # assumed before any watch echo
        # relist reconciliation: server-side delete eventually drops it
        del fake.pods["default/w0"]
        deadline = time.time() + 10
        while "w0" in {p.name for p in cache.running_pods()}:
            assert time.time() < deadline, "relist never dropped deleted pod"
            time.sleep(0.05)
    finally:
        cache.stop()


def test_volume_topology_zonal_pv_constrains_pod(fake):
    """A pod whose PVC is Bound to a zonal PV may only land in the PV's
    zone (upstream VolumeZone via the embedded scheduler,
    /root/reference/go.mod:13): the source folds the PV's topology into
    the pod's node affinity, and the engine binds only in-zone."""
    from kubernetes_scheduler_tpu.host import Scheduler, StaticAdvisor
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    fake.pvs.append({
        "metadata": {
            "name": "pv-za",
            "labels": {"topology.kubernetes.io/zone": "za"},
        },
        "spec": {},
    })
    fake.pvcs.append({
        "metadata": {"name": "data", "namespace": "default"},
        "spec": {"volumeName": "pv-za"},
    })
    fake.add_pod({
        "metadata": {"name": "zonal"},
        "spec": {
            "schedulerName": "yoda-tpu",
            "containers": [{"resources": {"requests": {"cpu": "100m"}}}],
            "volumes": [{"persistentVolumeClaim": {"claimName": "data"}}],
        },
        "status": {"phase": "Pending"},
    })
    src = KubeClusterSource(client_for(fake), scheduler_name="yoda-tpu")
    pods = src.list_pending_pods()
    assert len(pods) == 1
    pod = pods[0]
    assert pod.volume_claims == ["data"]
    assert any(
        e.key == "topology.kubernetes.io/zone" and e.values == ["za"]
        for e in pod.node_affinity
    ), pod.node_affinity

    from kubernetes_scheduler_tpu.host.types import Node

    nodes = [
        Node(name="in-zone", labels={"topology.kubernetes.io/zone": "za"},
             allocatable={"cpu": 8000.0, "memory": 2**33, "pods": 100}),
        Node(name="out-zone", labels={"topology.kubernetes.io/zone": "zb"},
             allocatable={"cpu": 8000.0, "memory": 2**33, "pods": 100}),
    ]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    sched = Scheduler(
        SchedulerConfig(batch_window=8, min_device_work=0,
                        adaptive_dispatch=False),
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    sched.submit(pod)
    m = sched.run_cycle()
    assert m.pods_bound == 1
    assert sched.binder.bindings[0].node_name == "in-zone"


def test_volume_topology_unbound_wffc_and_cross_product(fake):
    """An unbound claim (WaitForFirstConsumer) contributes no constraint;
    a local PV's OR terms conjoin with the pod's own OR terms via the
    cross product."""
    from kubernetes_scheduler_tpu.host.types import MatchExpression, Pod
    from kubernetes_scheduler_tpu.kube.convert import pv_from_api
    from kubernetes_scheduler_tpu.kube.volumes import fold_volume_terms

    # unbound claim through the live source: no constraint added
    fake.pvcs.append({
        "metadata": {"name": "wffc", "namespace": "default"},
        "spec": {},
    })
    fake.add_pod({
        "metadata": {"name": "waiter"},
        "spec": {
            "schedulerName": "yoda-tpu",
            "containers": [{}],
            "volumes": [{"persistentVolumeClaim": {"claimName": "wffc"}}],
        },
        "status": {"phase": "Pending"},
    })
    src = KubeClusterSource(client_for(fake), scheduler_name="yoda-tpu")
    (pod,) = src.list_pending_pods()
    assert pod.node_affinity == []

    # cross product: pod (zone a OR zone b) AND pv (host h1 OR host h2)
    pv = pv_from_api({
        "metadata": {"name": "local-pv"},
        "spec": {"nodeAffinity": {"required": {"nodeSelectorTerms": [
            {"matchExpressions": [
                {"key": "kubernetes.io/hostname", "operator": "In",
                 "values": ["h1"]}]},
            {"matchExpressions": [
                {"key": "kubernetes.io/hostname", "operator": "In",
                 "values": ["h2"]}]},
        ]}}},
    })
    base = Pod(name="p", node_affinity=[
        MatchExpression(key="zone", operator="In", values=["a"], term=0),
        MatchExpression(key="zone", operator="In", values=["b"], term=1),
    ])
    folded = fold_volume_terms(base, [pv.terms])
    groups: dict[int, set] = {}
    for e in folded.node_affinity:
        groups.setdefault(e.term, set()).add((e.key, tuple(e.values)))
    assert len(groups) == 4  # 2 pod terms x 2 pv terms
    assert {("zone", ("a",)), ("kubernetes.io/hostname", ("h1",))} in [
        set(g) for g in groups.values()
    ]
    assert {("zone", ("b",)), ("kubernetes.io/hostname", ("h2",))} in [
        set(g) for g in groups.values()
    ]


def test_informer_cache_serves_pdbs(fake):
    """PDBs ride the informer like nodes/pods: list_pdbs with a cache
    attached reads the watch-fed store — no per-preemption-pass LIST —
    and new budgets appear without a TTL wait."""
    from kubernetes_scheduler_tpu.kube.source import InformerCache

    def pdb_obj(name):
        return {
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"selector": {"matchLabels": {"app": name}},
                     "minAvailable": 1},
            "status": {"disruptionsAllowed": 1},
        }

    fake.pdbs.append(pdb_obj("db"))
    cache = InformerCache(client_for(fake), watch_timeout=2).start()
    try:
        assert cache.wait_synced(timeout=10)
        assert [b.name for b in cache.pdbs()] == ["db"]
        source = KubeClusterSource(client_for(fake), cache=cache)
        got = source.list_pdbs()
        assert [b.name for b in got] == ["db"]
        assert got[0].disruptions_allowed == 1
        # a budget created later reaches the cache via relist/watch
        fake.pdbs.append(pdb_obj("web"))
        deadline = time.time() + 10
        while len(cache.pdbs()) < 2:
            assert time.time() < deadline, "new PDB never reached the cache"
            time.sleep(0.05)
        assert {b.name for b in source.list_pdbs()} == {"db", "web"}
    finally:
        cache.stop()


def test_informer_serves_volumes_and_fold_uses_them(fake):
    """PVCs/PVs ride the informer: the volume fold reads the watch-fed
    stores (no LIST on the pending-pod path), and a PVC that binds later
    reaches the fold without a TTL wait."""
    from kubernetes_scheduler_tpu.kube.source import InformerCache

    fake.pvs.append({
        "metadata": {"name": "pv-za",
                     "labels": {"topology.kubernetes.io/zone": "za"}},
        "spec": {},
    })
    fake.pvcs.append({
        "metadata": {"name": "data", "namespace": "default"},
        "spec": {"volumeName": "pv-za"},
    })
    fake.add_pod({
        "metadata": {"name": "zonal"},
        "spec": {"schedulerName": "yoda-tpu", "containers": [{}],
                 "volumes": [{"persistentVolumeClaim": {"claimName": "data"}}]},
        "status": {"phase": "Pending"},
    })
    cache = InformerCache(client_for(fake), watch_timeout=2).start()
    try:
        assert cache.wait_synced(timeout=10)
        assert "default/data" in cache.pvc_map()
        assert "pv-za" in cache.pv_map()
        src = KubeClusterSource(
            client_for(fake), scheduler_name="yoda-tpu", cache=cache
        )
        assert src.volumes.cache is cache
        (pod,) = src.list_pending_pods()
        assert any(
            e.key == "topology.kubernetes.io/zone" and e.values == ["za"]
            for e in pod.node_affinity
        ), pod.node_affinity
    finally:
        cache.stop()


def test_volume_restrictions_rwop_exclusive(fake):
    """VolumeRestrictions (ReadWriteOncePod): exclusivity enforced per
    CYCLE in the scheduler — two pods pending together cannot both take
    the claim (the race an admission-time check loses), a running holder
    blocks it, and a released claim admits the waiter."""
    from kubernetes_scheduler_tpu.host import Scheduler, StaticAdvisor
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil
    from kubernetes_scheduler_tpu.host.types import Node
    from kubernetes_scheduler_tpu.kube.source import InformerCache
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    fake.pvcs.append({
        "metadata": {"name": "exclusive", "namespace": "default"},
        "spec": {"volumeName": "pv-x", "accessModes": ["ReadWriteOncePod"]},
    })
    fake.pvs.append({"metadata": {"name": "pv-x"}, "spec": {}})
    for name in ("rival-a", "rival-b"):
        fake.add_pod({
            "metadata": {"name": name},
            "spec": {"schedulerName": "yoda-tpu",
                     "containers": [{"resources": {"requests": {"cpu": "100m"}}}],
                     "volumes": [{"persistentVolumeClaim": {"claimName": "exclusive"}}]},
            "status": {"phase": "Pending"},
        })
    cache = InformerCache(client_for(fake), watch_timeout=2).start()
    try:
        assert cache.wait_synced(timeout=10)
        src = KubeClusterSource(
            client_for(fake), scheduler_name="yoda-tpu", cache=cache
        )
        pods = src.list_pending_pods()
        assert all(p.exclusive_claims == ["default/exclusive"] for p in pods)

        nodes = [Node(name=f"n{i}", allocatable={"cpu": 8000.0, "memory": 2**33,
                                                 "pods": 100}) for i in range(2)]
        utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
        running: list = []
        sched = Scheduler(
            SchedulerConfig(batch_window=8, min_device_work=0,
                            adaptive_dispatch=False),
            advisor=StaticAdvisor(utils),
            list_nodes=lambda: nodes,
            list_running_pods=lambda: running,
        )
        for p in pods:
            sched.submit(p)
        m = sched.run_cycle()
        # exactly ONE rival binds; the other waits
        assert m.pods_bound == 1 and m.pods_unschedulable == 1

        # the winner is now running and HOLDS the claim: the loser stays
        # pending even with free nodes
        winner = sched.binder.bindings[0].pod
        running.append(winner)
        sched.queue._clock = lambda: 1e9  # clear backoff
        m2 = sched.run_cycle()
        assert m2.pods_bound == 0 and m2.pods_unschedulable == 1

        # holder released: the waiter binds (the mirror owns running
        # state once seeded, so the release is an informer event too)
        running.clear()
        sched.mirror.apply_pod_event("DELETED", winner)
        sched.queue._clock = lambda: 2e9
        m3 = sched.run_cycle()
        assert m3.pods_bound == 1
    finally:
        cache.stop()


def test_informer_pdb_403_does_not_block_sync(fake, monkeypatch):
    """An RBAC gap on the OPTIONAL PDB resource (403) must not hang
    wait_synced or spam error backoff — the scheduler starts with an
    empty budget set (review finding r4)."""
    from kubernetes_scheduler_tpu.kube.client import KubeApiError
    from kubernetes_scheduler_tpu.kube.source import InformerCache

    fake.add_node(make_node_obj("n0"))
    client = client_for(fake)
    real = client.list_with_rv

    def forbidden(path, params=None):
        if "poddisruptionbudgets" in path:
            raise KubeApiError(403, "GET", path, "forbidden")
        return real(path, params)

    monkeypatch.setattr(client, "list_with_rv", forbidden)
    cache = InformerCache(client, watch_timeout=2).start()
    try:
        assert cache.wait_synced(timeout=10)
        assert cache.pdbs() == []
        assert [n.name for n in cache.nodes()] == ["n0"]
    finally:
        cache.stop()


def test_cli_kube_uses_informer_cache(fake, capsys, tmp_path):
    """The CLI kube path schedules from the informer cache (running pod
    on the server consumes capacity seen by the cycle)."""
    import json as _json

    from kubernetes_scheduler_tpu.cli import main

    fake.add_node(make_node_obj("only", cpu="1"))
    fake.prom["only"] = {"cpu_pct": 10.0, "disk_io": 1.0}
    fake.add_pod(make_pod_obj("hog", node_name="only", cpu="900m"))
    fake.add_pod(make_pod_obj("wants", cpu="500m", annotations={"diskIO": "1"}))
    host = fake.url.removeprefix("http://")
    cfg_file = tmp_path / "cfg.json"
    cfg_file.write_text(
        _json.dumps({"batch_window": 8, "min_device_work": 0,
                     "max_backoff_seconds": 0.2, "initial_backoff_seconds": 0.1,
                     "advisor": {"prometheus_host": host}})
    )
    # --max-cycles 3: the pod is unschedulable by design, so without a
    # cycle cap the loop would retry it for the full default 1000 cycles
    # (~0.25s of backoff each — the 258s this test used to take)
    rc = main(
        ["scheduler", "--source", "kube", "--kube-server", fake.url,
         "--config", str(cfg_file), "--watch-timeout", "2",
         "--max-cycles", "3"]
    )
    assert rc == 0
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # the hog (seen only via the informer) fills the node: wants cannot fit
    assert out["pods_bound"] == 0 and fake.bindings == []


def test_token_file_rotation(tmp_path):
    """File-backed bearer tokens are re-read after rotation (projected
    service-account tokens rotate ~hourly; a stale one 401s forever)."""
    srv = FakeKube(token="tok-v2").start()
    try:
        tf = tmp_path / "token"
        tf.write_text("tok-v1")
        client = KubeClient(KubeConfig(base_url=srv.url, token_path=str(tf)))
        with pytest.raises(KubeApiError):
            client.get("/api/v1/nodes")
        tf.write_text("tok-v2")
        client._token_cache = None  # expire the 60s cache (test shortcut)
        assert client.get("/api/v1/nodes") == {"items": []}
    finally:
        srv.stop()


def test_stale_pod_cannot_bind_recreated_name(fake):
    """Delete-and-recreate under the same name: the stale queued Pod's
    UID-preconditioned bind must 409 (never placing the successor, which
    may have a wildly different spec), and the recreation — a new UID —
    must be schedulable as itself."""
    fake.add_node(make_node_obj("n0"))
    fake.add_pod(make_pod_obj("web", cpu="100m", uid="uid-old"))
    client = client_for(fake)
    src = KubeClusterSource(client)
    binder = KubeBinder(client)
    stale = pod_from_api(fake.pods["default/web"])
    # user deletes and recreates the name with a different spec/UID
    del fake.pods["default/web"]
    fake.add_pod(make_pod_obj("web", cpu="30", uid="uid-new"))
    with pytest.raises(KubeApiError) as ei:
        binder.bind(stale, "n0")
    assert ei.value.status == 409
    assert fake.bindings == []           # successor untouched
    fresh = pod_from_api(fake.pods["default/web"])
    binder.bind(fresh, "n0")             # the recreation binds as itself
    assert fake.bindings == [("default/web", "n0")]
    # scheduling identities differ, so the feeder would resubmit it
    from kubernetes_scheduler_tpu.kube.source import pod_key
    assert pod_key(stale) != pod_key(fresh)


def test_owner_reference_and_controller_replicas(fake):
    """pod_from_api captures the controller ownerReference; the informer
    watches apps/v1 workloads so the PDB percentage math can resolve
    expected replica counts."""
    from kubernetes_scheduler_tpu.kube.source import InformerCache

    obj = make_pod_obj("web-abc", node_name="n0")
    obj["metadata"]["ownerReferences"] = [
        {"kind": "ReplicaSet", "name": "web-rs", "controller": True},
        {"kind": "Thing", "name": "x"},  # non-controller ignored
    ]
    pod = pod_from_api(obj)
    assert pod.owner == ("ReplicaSet", "web-rs")
    assert pod_from_api(make_pod_obj("solo")).owner is None

    fake.add_replicaset("web-rs", 10)
    cache = InformerCache(client_for(fake), watch_timeout=1.0).start()
    try:
        assert cache.wait_synced(timeout=30)
        assert cache.controller_replicas("ReplicaSet", "default", "web-rs") == 10
        assert cache.controller_replicas("ReplicaSet", "default", "nope") is None
        # statefulsets route disabled (404): optional resource degrades
        assert cache.controller_replicas("StatefulSet", "default", "x") is None
    finally:
        cache.stop()


def test_wffc_selected_node_handoff_e2e(fake):
    """VolumeBinding's ACTIVE half: binding a pod with an unbound
    WaitForFirstConsumer claim PATCHes volume.kubernetes.io/selected-node
    onto the PVC BEFORE the Binding POST, so the external provisioner
    creates the volume in the chosen node's topology (upstream
    VolumeBinding PreBind via /root/reference/go.mod:13). Bound and
    Immediate-class claims are left alone."""
    from kubernetes_scheduler_tpu.host import Scheduler, StaticAdvisor
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil
    from kubernetes_scheduler_tpu.host.types import Node
    from kubernetes_scheduler_tpu.kube.volumes import VolumeTopology

    fake.add_storageclass("fast-wffc", "WaitForFirstConsumer")
    fake.add_storageclass("std", "Immediate")
    fake.add_node(make_node_obj("n0"))
    fake.pvcs.append({
        "metadata": {"name": "scratch", "namespace": "default"},
        "spec": {"storageClassName": "fast-wffc"},   # unbound WFFC
    })
    fake.pvcs.append({
        "metadata": {"name": "plain", "namespace": "default"},
        "spec": {"storageClassName": "std"},         # unbound Immediate
    })
    fake.add_pod({
        "metadata": {"name": "wants-scratch"},
        "spec": {
            "schedulerName": "yoda-tpu",
            "containers": [{"resources": {"requests": {"cpu": "100m"}}}],
            "volumes": [
                {"persistentVolumeClaim": {"claimName": "scratch"}},
                {"persistentVolumeClaim": {"claimName": "plain"}},
            ],
        },
        "status": {"phase": "Pending"},
    })
    client = client_for(fake)
    src = KubeClusterSource(client, scheduler_name="yoda-tpu")
    binder = KubeBinder(client, volumes=src.volumes)
    nodes = [Node(name="n0",
                  allocatable={"cpu": 8000.0, "memory": 2**33, "pods": 100})]
    sched = Scheduler(
        SchedulerConfig(batch_window=8, min_device_work=0,
                        adaptive_dispatch=False),
        advisor=StaticAdvisor({"n0": NodeUtil(cpu_pct=10, disk_io=5)}),
        binder=binder,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    for p in src.list_pending_pods():
        sched.submit(p)
    m = sched.run_cycle()
    assert m.pods_bound == 1 and fake.bindings == [
        ("default/wants-scratch", "n0")
    ]
    # only the WFFC claim was annotated, with the chosen node
    assert [k for k, _ in fake.pvc_patches] == ["default/scratch"]
    ann = (fake.pvcs[-2]["metadata"].get("annotations") or {})
    assert ann.get("volume.kubernetes.io/selected-node") == "n0"
    assert "annotations" not in fake.pvcs[-1].get("metadata", {})


def test_csi_attach_limits_cap_placement(fake):
    """NodeVolumeLimits: a node at its attachable-volumes-csi-* limit
    filters out — the running pod's bound CSI volume consumes the one
    attach unit, so the pending pod's CSI claim forces it elsewhere."""
    from kubernetes_scheduler_tpu.host import Scheduler, StaticAdvisor
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil

    for name in ("full", "open"):
        obj = make_node_obj(name)
        obj["status"]["allocatable"]["attachable-volumes-csi-ebs.x"] = "1"
        fake.add_node(obj)
    for pv, claim in (("pv-a", "vol-a"), ("pv-b", "vol-b")):
        fake.pvs.append({
            "metadata": {"name": pv},
            "spec": {"csi": {"driver": "ebs.x"}},
        })
        fake.pvcs.append({
            "metadata": {"name": claim, "namespace": "default"},
            "spec": {"volumeName": pv},
        })
    fake.add_pod({
        "metadata": {"name": "holder"},
        "spec": {
            "schedulerName": "yoda-tpu", "nodeName": "full",
            "containers": [{"resources": {"requests": {"cpu": "100m"}}}],
            "volumes": [{"persistentVolumeClaim": {"claimName": "vol-a"}}],
        },
        "status": {"phase": "Running"},
    })
    fake.add_pod({
        "metadata": {"name": "wants-vol"},
        "spec": {
            "schedulerName": "yoda-tpu",
            "containers": [{"resources": {"requests": {"cpu": "100m"}}}],
            "volumes": [{"persistentVolumeClaim": {"claimName": "vol-b"}}],
        },
        "status": {"phase": "Pending"},
    })
    client = client_for(fake)
    src = KubeClusterSource(client, scheduler_name="yoda-tpu")
    # make "full" the score-preferred node so the test fails loud if the
    # attach column is ignored
    utils = {"full": NodeUtil(cpu_pct=5, disk_io=1),
             "open": NodeUtil(cpu_pct=80, disk_io=40)}
    sched = Scheduler(
        SchedulerConfig(batch_window=8, min_device_work=0,
                        adaptive_dispatch=False),
        advisor=StaticAdvisor(utils),
        binder=KubeBinder(client, volumes=src.volumes),
        list_nodes=src.list_nodes,
        list_running_pods=src.list_running_pods,
    )
    pending = src.list_pending_pods()
    assert pending[0].attach_demands == {"attachable-volumes-csi-ebs.x": 1.0}
    running = src.list_running_pods()
    holder = next(p for p in running if p.name == "holder")
    assert holder.attach_demands == {"attachable-volumes-csi-ebs.x": 1.0}
    for p in pending:
        sched.submit(p)
    m = sched.run_cycle()
    assert m.pods_bound == 1
    assert fake.bindings == [("default/wants-vol", "open")]


def test_deep_backlog_live_e2e(fake):
    """Deep-queue cycle against the live API path: one run_cycle pops
    max_windows_per_cycle windows and schedules them in ONE engine
    dispatch (capacity + window-internal anti-affinity carried on
    device), with every bind landing on the server through KubeBinder's
    per-pod POSTs. Pins the deep-backlog configuration
    (examples/scheduler-config-deep-backlog.json) to the kube surface,
    not just the simulated host loop."""
    for i in range(3):
        fake.add_node(make_node_obj(f"n{i}", cpu="64"))
    anti = {"affinity": {"podAntiAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [{
            "labelSelector": {"matchLabels": {"app": "db"}},
            "topologyKey": "kubernetes.io/hostname",
        }],
    }}}
    # 3 mutually anti-affine db pods FIRST (FIFO pop order puts all
    # three inside cycle 1's single deep dispatch), then 30 plain pods
    for i in range(3):
        fake.add_pod(make_pod_obj(
            f"db-{i}", cpu="100m", labels={"app": "db"}, extra_spec=anti
        ))
    for i in range(30):
        fake.add_pod(make_pod_obj(f"plain-{i}", cpu="100m"))
    client = client_for(fake)
    src = KubeClusterSource(client, scheduler_name="yoda-tpu")
    utils = {f"n{i}": NodeUtil(cpu_pct=10 + i, disk_io=3) for i in range(3)}
    sched = Scheduler(
        SchedulerConfig(
            batch_window=8, max_windows_per_cycle=4, min_device_work=0
        ),
        advisor=StaticAdvisor(utils),
        binder=KubeBinder(client),
        list_nodes=src.list_nodes,
        list_running_pods=src.list_running_pods,
    )
    for p in src.list_pending_pods():
        sched.submit(p)
    m1 = sched.run_cycle()
    assert m1.pods_in == 32  # 4 windows x 8 popped in ONE cycle
    m2 = sched.run_cycle()
    assert m1.pods_bound + m2.pods_bound == 33
    bound = {k.split("/")[1]: v for k, v in fake.bindings}
    assert len(bound) == 33
    # the three db pods are mutually anti-affine: three distinct nodes,
    # enforced WITHIN the single deep dispatch
    db_nodes = {bound[f"db-{i}"] for i in range(3)}
    assert len(db_nodes) == 3, db_nodes
