"""Trend gate (trace/trend.py): the soak-length leak detector.

Everything here is engine/jax-free and synthetic: gate_series rows over
hand-built series, build_trend over hand-written span files,
journal_trend over hand-framed journals — so the gate's decision
boundary (slope direction x absolute floor x relative threshold x
monotonicity) is pinned point by point, and the CLI exit-code contract
(0 clean / 1 regression / 2 unusable input) is pinned in-process."""

import json
import os

import pytest

from kubernetes_scheduler_tpu import cli
from kubernetes_scheduler_tpu.trace.recorder import (
    JournalWriter,
    encode_record,
)
from kubernetes_scheduler_tpu.trace.trend import (
    TrendError,
    build_trend,
    gate_series,
    journal_trend,
    perturb_trend,
    trend_over_reports,
)


# ---- gate_series: the decision boundary --------------------------------


def test_gate_flags_monotone_growth():
    row = gate_series("s", [1.0, 1.4, 1.9, 2.3, 3.0])
    assert row["regression"] is True
    assert row["monotone_frac"] == 1.0
    assert row["rise_pct"] == 200.0


def test_gate_ignores_flat_and_falling_series():
    assert gate_series("s", [2.0, 2.0, 2.0, 2.0])["regression"] is False
    assert gate_series("s", [3.0, 2.0, 1.5, 1.0])["regression"] is False


def test_gate_rejects_jagged_rise():
    # big end-to-end rise, but noise-shaped: half the deltas fight the
    # slope
    row = gate_series("s", [1.0, 3.0, 1.2, 3.2, 2.9])
    assert row["monotone_frac"] < 0.6
    assert row["regression"] is False


def test_gate_absolute_floor_gates_sub_tick_jitter():
    # 300% relative rise, 0.03 absolute — under the 0.05 default floor
    small = [0.01, 0.02, 0.03, 0.04]
    assert gate_series("s", small)["regression"] is False
    assert gate_series("s", small, min_abs=0.005)["regression"] is True


def test_gate_relative_threshold_gates_big_bases():
    # +10 ms on a 100 ms base: clears any floor, not the 25% threshold
    row = gate_series("s", [100.0, 103.0, 107.0, 110.0])
    assert row["regression"] is False
    assert gate_series(
        "s", [100.0, 103.0, 107.0, 110.0], threshold_pct=5.0
    )["regression"] is True


def test_gate_down_direction_flags_decay():
    # delta hit-rate style: monotone decay trips the "down" gate
    row = gate_series(
        "hit", [0.9, 0.8, 0.6, 0.45], direction="down", min_abs=0.05
    )
    assert row["regression"] is True
    assert gate_series(
        "hit", [0.9, 0.91, 0.9, 0.89], direction="down", min_abs=0.05
    )["regression"] is False


def test_gate_too_few_points_never_regresses():
    row = gate_series("s", [1.0, 9.0])
    assert row["regression"] is False
    assert "too few points" in row["reason"]


# ---- trend_over_reports: N snapshots in time order ---------------------


def _report(engine_ms: float, *, p99_ms: float | None = None) -> dict:
    p99 = engine_ms if p99_ms is None else p99_ms
    return {
        "cycles": 10,
        "cycle_ms": {"count": 10, "p50_ms": engine_ms + 1, "p99_ms": p99 + 1},
        "stages": {
            "engine_step": {"count": 10, "p50_ms": engine_ms, "p99_ms": p99}
        },
    }


def test_trend_over_reports_flags_ramp_and_passes_flat():
    flat = trend_over_reports([_report(2.0) for _ in range(5)])
    assert flat["clean"] is True
    ramp = trend_over_reports([_report(2.0 + 0.8 * i) for i in range(5)])
    assert "engine_step.p50_ms" in ramp["regressions"]
    assert "cycle.p50_ms" in ramp["regressions"]
    assert ramp["clean"] is False


def test_trend_p99_floor_is_ten_x():
    # identical 0.1 -> 0.3 ramp on both metrics: 0.2 rise clears the
    # 0.05 p50 floor but not the 0.5 p99 floor (p99 is max-like at
    # window sample counts — tail jitter must not fail a soak)
    reports = [_report(0.1 + 0.05 * i) for i in range(5)]
    out = trend_over_reports(reports)
    assert "engine_step.p50_ms" in out["regressions"]
    assert "engine_step.p99_ms" not in out["regressions"]


def test_trend_skips_stages_missing_from_some_snapshots():
    reports = [_report(2.0 + 0.8 * i) for i in range(5)]
    reports[2]["stages"]["ghost"] = {"count": 4, "p50_ms": 1, "p99_ms": 2}
    out = trend_over_reports(reports)
    assert not any(r["series"].startswith("ghost") for r in out["rows"])


def test_trend_needs_three_snapshots():
    with pytest.raises(TrendError, match=">= 3 report snapshots"):
        trend_over_reports([_report(1.0), _report(2.0)])


# ---- build_trend / perturb_trend: one span source, windowed ------------


def _write_spans(path: str, durs_us: list[float]) -> None:
    """One span file in the recorder's crash-tolerant trailing-comma
    format: per cycle an engine_step span plus its owning cycle span,
    1ms apart in start time."""
    os.makedirs(path, exist_ok=True)
    events = []
    for i, dur in enumerate(durs_us):
        ts = 1000.0 * i
        args = {"trace_id": i}
        events.append(
            {"ph": "X", "name": "engine_step", "ts": ts, "dur": dur,
             "args": args}
        )
        events.append(
            {"ph": "X", "name": "cycle", "ts": ts, "dur": dur + 100.0,
             "args": args}
        )
    with open(
        os.path.join(path, "spans-00000000.trace.json"), "w",
        encoding="utf-8",
    ) as f:
        f.write("[\n")
        for ev in events:
            f.write(json.dumps(ev, separators=(",", ":")) + ",\n")


def test_build_trend_clean_on_steady_state(tmp_path):
    src = str(tmp_path / "spans")
    # deterministic sub-floor jitter around 1ms
    _write_spans(src, [1000.0 + (i * 37 % 13) for i in range(96)])
    out = build_trend(src)
    assert out["clean"] is True
    assert out["warmup_windows_dropped"] == 1


def test_build_trend_catches_seeded_leak(tmp_path):
    src, dst = str(tmp_path / "spans"), str(tmp_path / "leaky")
    _write_spans(src, [1000.0 + (i * 37 % 13) for i in range(96)])
    touched = perturb_trend(src, dst, stage="engine_step", factor=3.0)
    assert touched == 96
    out = build_trend(dst)
    assert "engine_step.p50_ms" in out["regressions"]
    # the owning cycle stretched by the same added time: the leak is
    # visible end-to-end, not only in the stage that leaks
    assert "cycle.p50_ms" in out["regressions"]


def test_build_trend_warmup_unmasks_drift_behind_compile(tmp_path):
    # a slow compile-dominated first window opens the run; behind it,
    # genuine +67% drift. without the warmup drop the first window's
    # fall swamps the rise and the leak sails through; with it the
    # drift is caught.
    src = str(tmp_path / "spans")
    _write_spans(
        src,
        [60000.0] * 12 + [1000.0 + 8.0 * i for i in range(84)],
    )
    masked = build_trend(src, warmup=0)
    assert masked["warmup_windows_dropped"] == 0
    assert "engine_step.p50_ms" not in masked["regressions"]
    caught = build_trend(src, warmup=1)
    assert caught["warmup_windows_dropped"] == 1
    assert "engine_step.p50_ms" in caught["regressions"]


def test_build_trend_single_instant_errors(tmp_path):
    src = str(tmp_path / "spans")
    os.makedirs(src)
    with open(
        os.path.join(src, "spans-00000000.trace.json"), "w",
        encoding="utf-8",
    ) as f:
        f.write("[\n")
        for _ in range(8):
            f.write(
                json.dumps(
                    {"ph": "X", "name": "cycle", "ts": 5.0, "dur": 1.0}
                ) + ",\n"
            )
    with pytest.raises(TrendError, match="single instant"):
        build_trend(src)


# ---- journal_trend: leak signals from per-cycle metrics ----------------


def _write_journal(
    path: str,
    n: int = 60,
    *,
    cycle_s=lambda i: 0.002,
    pods_in=lambda i: 8,
    delta=lambda i: (9, 1),
) -> None:
    w = JournalWriter(path)
    for i in range(n):
        du, fu = delta(i)
        payload = encode_record(
            {
                "seq": i,
                "path": "device",
                "metrics": {
                    "cycle_seconds": cycle_s(i),
                    "pods_in": pods_in(i),
                    "delta_uploads": du,
                    "full_uploads": fu,
                },
            }
        )
        w.append(payload, rotate=w.needs_rotation(len(payload)))
    w.close()


def test_journal_trend_clean_on_steady_journal(tmp_path):
    path = str(tmp_path / "journal")
    _write_journal(path)
    out = journal_trend(path)
    assert out["clean"] is True
    assert out["records"] == 60
    assert {r["series"] for r in out["rows"]} == {
        "cycle_p99_ms", "queue_depth_mean", "state_bytes_mean",
        "delta_hit_ratio",
    }


def test_journal_trend_flags_latency_creep(tmp_path):
    path = str(tmp_path / "journal")
    _write_journal(path, cycle_s=lambda i: 0.002 + 0.0001 * i)
    out = journal_trend(path)
    assert "cycle_p99_ms" in out["regressions"]


def test_journal_trend_flags_queue_runaway(tmp_path):
    path = str(tmp_path / "journal")
    _write_journal(path, pods_in=lambda i: 8 + i)
    out = journal_trend(path)
    assert "queue_depth_mean" in out["regressions"]


def test_journal_trend_flags_delta_hit_decay(tmp_path):
    path = str(tmp_path / "journal")
    # early cycles nearly all deltas, late cycles nearly all fulls
    _write_journal(path, delta=lambda i: (max(10 - i // 6, 0), 1 + i // 6))
    out = journal_trend(path)
    assert "delta_hit_ratio" in out["regressions"]


def test_journal_trend_too_short_errors(tmp_path):
    path = str(tmp_path / "journal")
    _write_journal(path, n=5)
    with pytest.raises(TrendError, match="too short"):
        journal_trend(path)


# ---- exit-code contract (0 clean / 1 regression / 2 error) -------------


def test_trace_trend_exit_codes(tmp_path, capsys):
    clean = str(tmp_path / "clean")
    _write_journal(clean)
    assert cli.main(["trace", "trend", clean]) == 0
    leaky = str(tmp_path / "leaky")
    _write_journal(leaky, cycle_s=lambda i: 0.002 + 0.0001 * i)
    assert cli.main(["trace", "trend", leaky]) == 1
    short = str(tmp_path / "short")
    _write_journal(short, n=4)
    assert cli.main(["trace", "trend", short]) == 2
    assert "too short" in capsys.readouterr().out


def test_spans_report_trend_exit_codes(tmp_path, capsys):
    clean = str(tmp_path / "spans")
    _write_spans(clean, [1000.0 + (i * 37 % 13) for i in range(96)])
    assert cli.main(["spans", "report", "--trend", clean]) == 0
    leaky = str(tmp_path / "leaky")
    perturb_trend(clean, leaky, factor=3.0)
    assert cli.main(["spans", "report", "--trend", leaky]) == 1
    assert (
        cli.main(["spans", "report", "--trend", str(tmp_path / "absent")])
        == 2
    )
    out = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert "engine_step.p50_ms" in out[1]["regressions"]
    assert "error" in out[2]


def test_spans_diff_trend_exit_codes(tmp_path, capsys):
    # three snapshots of one soak, saved as `spans report` JSONs, fed
    # to `spans diff --trend` oldest-first
    from kubernetes_scheduler_tpu.trace.analyze import build_report

    dirs = []
    for i, scale in enumerate((1.0, 1.5, 2.2)):
        d = str(tmp_path / f"win{i}")
        _write_spans(d, [1000.0 * scale] * 24)
        rp = tmp_path / f"report{i}.json"
        rp.write_text(json.dumps(build_report(d)))
        dirs.append(str(rp))
    assert cli.main(["spans", "diff", "--trend", *dirs]) == 1
    flat = []
    for i in range(3):
        d = str(tmp_path / f"flat{i}")
        _write_spans(d, [1000.0] * 24)
        rp = tmp_path / f"flat-report{i}.json"
        rp.write_text(json.dumps(build_report(d)))
        flat.append(str(rp))
    assert cli.main(["spans", "diff", "--trend", *flat]) == 0
    # pairwise mode refuses extra sources: N-way compare IS --trend
    assert cli.main(["spans", "diff", *dirs]) == 2
    assert "need --trend" in capsys.readouterr().out
