"""The fused Pallas kernel must match the unfused op composition exactly.

Runs through the Pallas interpreter on CPU (conftest forces the cpu
backend); on TPU the same kernel compiles via Mosaic with identical
semantics.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_scheduler_tpu.ops import (
    balanced_cpu_diskio,
    resource_fit,
    utilization_stats,
)
from kubernetes_scheduler_tpu.ops.assign import NEG
from kubernetes_scheduler_tpu.ops.pallas_fused import fused_masked_score

RNG = np.random.default_rng(7)


def make_problem(p, n, r=3):
    alloc = RNG.uniform(10, 100, (n, r)).astype(np.float32)
    reqd = (alloc * RNG.uniform(0, 1, (n, r))).astype(np.float32)
    disk_io = RNG.uniform(0, 50, n).astype(np.float32)
    cpu = RNG.uniform(0, 100, n).astype(np.float32)
    pod_req = RNG.uniform(0, 40, (p, r)).astype(np.float32)
    # exercise the unrequested-resource bypass
    pod_req[RNG.uniform(size=(p, r)) < 0.3] = 0.0
    r_cpu = pod_req[:, 0] * 10
    r_io = RNG.uniform(0, 30, p).astype(np.float32)
    r_io[RNG.uniform(size=p) < 0.25] = 0.0  # missing diskIO annotation
    return alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io


def reference_masked(alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io,
                     node_mask, pod_mask):
    return np.asarray(
        _reference_masked_jit(
            jnp.asarray(alloc), jnp.asarray(reqd), jnp.asarray(disk_io),
            jnp.asarray(cpu), jnp.asarray(pod_req), jnp.asarray(r_cpu),
            jnp.asarray(r_io), jnp.asarray(node_mask), jnp.asarray(pod_mask),
        )
    )


@jax.jit
def _reference_masked_jit(alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io,
                          node_mask, pod_mask):
    # jitted like the engine's unfused path: eager op-by-op dispatch
    # rounds float contractions differently from compiled XLA, and the
    # parity the engine pins is between the two COMPILED paths
    stats = utilization_stats(disk_io, cpu, node_mask)
    score = balanced_cpu_diskio(stats, r_cpu, r_io)
    fits = resource_fit(alloc, reqd, pod_req, node_mask)
    fits = fits & pod_mask[:, None]
    return jnp.where(fits, score, NEG)


@pytest.mark.parametrize("p,n", [(4, 16), (17, 130), (64, 300)])
def test_fused_matches_composition(p, n):
    alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io = make_problem(p, n)
    node_mask = np.ones(n, bool)
    node_mask[-max(1, n // 7):] = False
    pod_mask = np.ones(p, bool)
    pod_mask[-1] = False
    stats = utilization_stats(
        jnp.asarray(disk_io), jnp.asarray(cpu), jnp.asarray(node_mask)
    )
    got = np.asarray(
        fused_masked_score(
            stats.u, stats.v, jnp.asarray(node_mask),
            jnp.asarray(alloc), jnp.asarray(reqd),
            jnp.asarray(r_cpu), jnp.asarray(r_io),
            jnp.asarray(pod_req), jnp.asarray(pod_mask),
            tile_p=8, tile_n=128,
        )
    )
    want = reference_masked(
        alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io, node_mask, pod_mask
    )
    feas_got = got > NEG * 0.5
    feas_want = want > NEG * 0.5
    np.testing.assert_array_equal(feas_got, feas_want)
    np.testing.assert_allclose(
        got[feas_want], want[feas_want], rtol=1e-5, atol=1e-5
    )
    assert (got[~feas_want] == NEG).all()


def test_fused_padding_is_masked():
    p, n = 5, 37
    alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io = make_problem(p, n)
    got = np.asarray(
        fused_masked_score(
            jnp.asarray(disk_io / 50.0), jnp.asarray(cpu / 100.0),
            jnp.ones(n, bool),
            jnp.asarray(alloc), jnp.asarray(reqd),
            jnp.asarray(r_cpu), jnp.asarray(r_io),
            jnp.asarray(pod_req), jnp.ones(p, bool),
            tile_p=8, tile_n=128,
        )
    )
    assert got.shape == (p, n)


@pytest.mark.parametrize("features", [{}, {"constraints": True}, {"gpu": True}])
@pytest.mark.parametrize("assigner", ["greedy", "auction"])
def test_fused_engine_decisions_match_unfused(features, assigner):
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(96, seed=3, **features)
    pods = gen_pods(24, seed=4, **features)
    base = schedule_batch(
        snap, pods, assigner=assigner, normalizer="none", fused=False
    )
    got = schedule_batch(
        snap, pods, assigner=assigner, normalizer="none", fused=True
    )
    np.testing.assert_array_equal(
        np.asarray(got.feasible), np.asarray(base.feasible)
    )
    np.testing.assert_array_equal(
        np.asarray(got.node_idx), np.asarray(base.node_idx)
    )


def test_fused_windows_match_unfused():
    from kubernetes_scheduler_tpu.engine import schedule_windows, stack_windows
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(64, seed=5)
    pods = stack_windows(gen_pods(32, seed=6), 8)
    base = schedule_windows(snap, pods, fused=False)
    got = schedule_windows(snap, pods, fused=True)
    np.testing.assert_array_equal(
        np.asarray(got.node_idx), np.asarray(base.node_idx)
    )
    assert int(got.n_assigned) == int(base.n_assigned)


def test_fused_windows_layout_carry_bitwise():
    """The layout-carrying windows scan (resident multi-window cycles:
    retained node_ft/alloc_t reused every window, only reqd_t rebuilt
    from the capacity carry via prep_requested) must be BITWISE the
    re-prep path — node_idx AND free_after — and reject a layout
    without fused=True."""
    import jax

    from kubernetes_scheduler_tpu.engine import (
        build_fused_layout,
        schedule_windows,
        stack_windows,
    )
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(64, seed=5)
    pods = stack_windows(gen_pods(32, seed=6), 8)
    base = schedule_windows(snap, pods, fused=True)
    layout = build_fused_layout(jax.device_put(snap))
    got = schedule_windows(snap, pods, fused=True, layout=layout)
    np.testing.assert_array_equal(
        np.asarray(got.node_idx), np.asarray(base.node_idx)
    )
    np.testing.assert_array_equal(
        np.asarray(got.free_after), np.asarray(base.free_after)
    )
    assert int(got.n_assigned) == int(base.n_assigned)
    with pytest.raises(ValueError, match="layout requires fused"):
        schedule_windows(snap, pods, fused=False, layout=layout)


# tile-boundary property sweep (the shapes that break tiled kernels:
# exactly at and one off the TILE multiples, with the small tiles the
# interpreter can afford), crossed with the resource-axis widths the
# unrolled fit loop sees in production. On a TPU backend the same cases
# compile through Mosaic (interpret=None auto-selects the native path).
_TILE_P, _TILE_N = 8, 128
_BOUNDARY_SHAPES = [
    (_TILE_P, _TILE_N),                  # exactly one tile
    (_TILE_P - 1, _TILE_N - 1),          # one under
    (_TILE_P + 1, _TILE_N + 1),          # one over
    (2 * _TILE_P, 2 * _TILE_N),          # exact multiple
    (2 * _TILE_P + 1, _TILE_N),          # ragged pod axis only
    (_TILE_P, 2 * _TILE_N - 1),          # ragged node axis only
]


@pytest.mark.parametrize("p,n", _BOUNDARY_SHAPES)
@pytest.mark.parametrize("n_res", [1, 4, 8])
def test_fused_tile_boundaries_bitwise(p, n, n_res):
    """Tile-boundary parity with the unfused reference: the feasibility
    pattern, the NEG sentinels, and the per-row DECISION (argmax over
    feasible cells — what the assigners consume) are bitwise equal;
    feasible-cell values agree to float-contraction tolerance (XLA is
    free to FMA-contract `alpha*v - beta*u` differently per graph, so
    exact value identity between two compiled graphs is not a
    guarantee either path makes)."""
    alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io = make_problem(
        p, n, r=n_res
    )
    node_mask = np.ones(n, bool)
    node_mask[- max(1, n // 5):] = False
    pod_mask = np.ones(p, bool)
    pod_mask[-1] = False
    stats = utilization_stats(
        jnp.asarray(disk_io), jnp.asarray(cpu), jnp.asarray(node_mask)
    )
    got = np.asarray(
        fused_masked_score(
            stats.u, stats.v, jnp.asarray(node_mask),
            jnp.asarray(alloc), jnp.asarray(reqd),
            jnp.asarray(r_cpu), jnp.asarray(r_io),
            jnp.asarray(pod_req), jnp.asarray(pod_mask),
            tile_p=_TILE_P, tile_n=_TILE_N,
        )
    )
    want = reference_masked(
        alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io, node_mask, pod_mask
    )
    feas_got = got > NEG * 0.5
    feas_want = want > NEG * 0.5
    np.testing.assert_array_equal(feas_got, feas_want)
    assert (got[~feas_want] == NEG).all()
    np.testing.assert_allclose(
        got[feas_want], want[feas_want], rtol=1e-5, atol=1e-5
    )
    np.testing.assert_array_equal(
        np.argmax(got, axis=1), np.argmax(want, axis=1)
    )


@pytest.mark.parametrize("which", ["rows", "cols", "both"])
def test_fused_all_masked(which):
    """Fully-masked pod rows / node columns return exactly NEG
    everywhere (the all-padding degenerate tiles)."""
    p, n = 9, 130
    alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io = make_problem(p, n)
    node_mask = np.zeros(n, bool) if which in ("cols", "both") else np.ones(n, bool)
    pod_mask = np.zeros(p, bool) if which in ("rows", "both") else np.ones(p, bool)
    stats = utilization_stats(
        jnp.asarray(disk_io), jnp.asarray(cpu), jnp.asarray(node_mask)
    )
    got = np.asarray(
        fused_masked_score(
            stats.u, stats.v, jnp.asarray(node_mask),
            jnp.asarray(alloc), jnp.asarray(reqd),
            jnp.asarray(r_cpu), jnp.asarray(r_io),
            jnp.asarray(pod_req), jnp.asarray(pod_mask),
            tile_p=_TILE_P, tile_n=_TILE_N,
        )
    )
    assert got.shape == (p, n)
    assert (got == NEG).all()


@pytest.mark.parametrize("normalizer", ["none", "min_max"])
def test_fused_folded_constraints_match_unfused(normalizer):
    """The megakernel's folded families — count-based (anti)affinity,
    reverse avoiders, topology spread, spec.nodeName pinning — against
    the unfused composition: include_pod_affinity engaged via
    affinity_aware=False, bitwise decisions and feasibility."""
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(96, seed=21, constraints=True)
    pods = gen_pods(32, seed=22, constraints=True)
    # pin a few pods to nodes (incl. one out-of-range = never fits) so
    # the kernel's global-column target fold is exercised
    tgt = np.asarray(pods.target_node).copy()
    tgt[0], tgt[1], tgt[2] = 5, 95, 200
    pods = pods._replace(target_node=jnp.asarray(tgt))
    for assigner in ("greedy", "auction"):
        base = schedule_batch(
            snap, pods, assigner=assigner, normalizer=normalizer,
            fused=False, affinity_aware=False,
        )
        got = schedule_batch(
            snap, pods, assigner=assigner, normalizer=normalizer,
            fused=True, affinity_aware=False,
        )
        np.testing.assert_array_equal(
            np.asarray(got.feasible), np.asarray(base.feasible)
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_idx), np.asarray(base.node_idx)
        )


def test_fused_wide_selector_axis_falls_back():
    """A selector axis past MAX_FUSED_SELECTORS routes the count-based
    families through the outside composition — decisions unchanged."""
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.ops.pallas_fused import MAX_FUSED_SELECTORS
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(64, seed=31, constraints=True)
    pods = gen_pods(16, seed=32, constraints=True)
    s_wide = MAX_FUSED_SELECTORS * 2
    n = np.asarray(snap.domain_counts).shape[0]
    dc = np.zeros((n, s_wide), np.float32)
    dc[:, : np.asarray(snap.domain_counts).shape[1]] = np.asarray(
        snap.domain_counts
    )
    dom = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, s_wide))
    zeros = np.zeros_like(dc)
    snap = snap._replace(
        domain_counts=jnp.asarray(dc), domain_id=jnp.asarray(dom),
        avoid_counts=jnp.asarray(zeros), pref_attract=jnp.asarray(zeros),
        pref_avoid=jnp.asarray(zeros),
    )
    base = schedule_batch(
        snap, pods, normalizer="none", fused=False, affinity_aware=False
    )
    got = schedule_batch(
        snap, pods, normalizer="none", fused=True, affinity_aware=False
    )
    np.testing.assert_array_equal(
        np.asarray(got.node_idx), np.asarray(base.node_idx)
    )


def test_resident_layout_matches_repad():
    """FusedLayout delta-folding vs per-call re-pad: a resident engine
    serving fused cycles off delta-updated kernel-layout buffers makes
    bitwise the same decisions as full re-uploads re-deriving the prep
    (PARITY round 12, resident-layout <-> re-pad identity)."""
    import jax

    from kubernetes_scheduler_tpu.engine import (
        LocalEngine,
        build_fused_layout,
        schedule_batch,
    )
    from kubernetes_scheduler_tpu.host.snapshot import snapshot_delta
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    # host-shaped inputs (numpy leaves, like SnapshotBuilder emits):
    # the resident path device_puts its own PRIVATE copy, which the
    # delta apply then donates — device-array inputs would alias it
    snap0 = gen_cluster(64, seed=41)
    snap0 = type(snap0)(*[np.asarray(a) for a in snap0])
    pods = gen_pods(16, seed=42)
    kw = dict(normalizer="none", fused=True)

    eng = LocalEngine()
    res0 = eng.schedule_resident(snap0, pods, epoch=1, **kw)
    assert eng._resident.layout is not None  # fused cycle built it

    # a second cycle's snapshot: utilization + requested rows moved
    d_io = np.asarray(snap0.disk_io).copy()
    d_io[:5] += 3.0
    req = np.asarray(snap0.requested).copy()
    req[7] += 1.5
    snap1 = snap0._replace(disk_io=d_io, requested=req)
    delta = snapshot_delta(snap0, snap1)
    assert delta is not None
    res1 = eng.schedule_resident(snap1, pods, delta=delta, epoch=2, **kw)
    assert eng.resident_used_delta

    # reference: fresh full uploads, layout re-derived from scratch
    ref0 = schedule_batch(jax.device_put(snap0), pods, **kw)
    ref1 = schedule_batch(jax.device_put(snap1), pods, **kw)
    np.testing.assert_array_equal(
        np.asarray(res0.node_idx), np.asarray(ref0.node_idx)
    )
    np.testing.assert_array_equal(
        np.asarray(res1.node_idx), np.asarray(ref1.node_idx)
    )
    # and the delta-folded layout buffers ARE the from-scratch prep
    fresh = build_fused_layout(jax.device_put(snap1))
    for a, b in zip(eng._resident.layout, fresh):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_auction_bid_kernel_bitwise():
    """fused_auction_bid vs the XLA round head: bitwise-identical
    assignments under capacity contention, priorities, and ties."""
    from kubernetes_scheduler_tpu.ops.assign import auction_assign

    rng = np.random.default_rng(3)
    for p, n, r in ((17, 130, 3), (64, 256, 5), (8, 128, 1)):
        scores = rng.uniform(0, 10, (p, n)).astype(np.float32)
        # inject exact ties so first-max semantics are actually exercised
        scores[:, n // 2] = scores[:, n // 3]
        feasible = rng.uniform(size=(p, n)) < 0.7
        feasible[-1] = False  # an all-infeasible pod
        req = rng.uniform(0, 4, (p, r)).astype(np.float32)
        req[rng.uniform(size=(p, r)) < 0.3] = 0.0
        free = rng.uniform(1, 6, (n, r)).astype(np.float32)
        prio = rng.integers(0, 3, p).astype(np.int32)
        mask = np.ones(p, bool)
        kw = dict(rounds=64, price_frac=1.0)
        base = auction_assign(
            jnp.asarray(scores), jnp.asarray(feasible), jnp.asarray(req),
            jnp.asarray(free), jnp.asarray(prio), jnp.asarray(mask),
            bid_kernel=False, **kw,
        )
        got = auction_assign(
            jnp.asarray(scores), jnp.asarray(feasible), jnp.asarray(req),
            jnp.asarray(free), jnp.asarray(prio), jnp.asarray(mask),
            bid_kernel=True, **kw,
        )
        np.testing.assert_array_equal(
            np.asarray(got.node_idx), np.asarray(base.node_idx)
        )
        np.testing.assert_array_equal(
            np.asarray(got.free_after), np.asarray(base.free_after)
        )


def test_greedy_scan_kernel_bitwise():
    """fused_greedy_scan vs the XLA lax.scan body: bitwise-identical
    node_idx AND free_after under capacity contention, priority order,
    exact ties, zero requests, masked pods, and tile-boundary shapes."""
    from kubernetes_scheduler_tpu.ops.assign import greedy_assign

    rng = np.random.default_rng(5)
    shapes = (
        (17, 130, 3),    # ragged both axes
        (64, 256, 5),    # aligned
        (128, 128, 1),   # exact single tiles
        (129, 127, 7),   # +-1 off the tile
        (3, 8, 2),       # tiny
    )
    for p, n, r in shapes:
        scores = rng.uniform(0, 10, (p, n)).astype(np.float32)
        # exact ties exercise first-max argmax semantics
        scores[:, n // 2] = scores[:, n // 3]
        scores[p // 2] = scores[p // 3]
        feasible = rng.uniform(size=(p, n)) < 0.7
        feasible[-1] = False  # an all-infeasible pod
        req = rng.uniform(0, 4, (p, r)).astype(np.float32)
        req[rng.uniform(size=(p, r)) < 0.3] = 0.0
        free = rng.uniform(1, 6, (n, r)).astype(np.float32)
        prio = rng.integers(-3, 3, p).astype(np.int32)
        mask = rng.uniform(size=p) < 0.9
        args = (
            jnp.asarray(scores), jnp.asarray(feasible), jnp.asarray(req),
            jnp.asarray(free), jnp.asarray(prio), jnp.asarray(mask),
        )
        base = greedy_assign(*args, greedy_kernel=False)
        got = greedy_assign(*args, greedy_kernel=True)
        np.testing.assert_array_equal(
            np.asarray(got.node_idx), np.asarray(base.node_idx)
        )
        np.testing.assert_array_equal(
            np.asarray(got.free_after), np.asarray(base.free_after)
        )
        assert int(got.n_assigned) == int(base.n_assigned)


def test_greedy_scan_kernel_capacity_sequencing():
    """The scan's defining property through the kernel: one-slot nodes
    admit exactly one pod, in priority order, capacity decremented
    between steps."""
    from kubernetes_scheduler_tpu.ops.assign import greedy_assign

    p, n = 6, 4
    scores = jnp.tile(jnp.asarray([4.0, 3.0, 2.0, 1.0]), (p, 1))
    feasible = jnp.ones((p, n), bool)
    req = jnp.ones((p, 1), jnp.float32)
    free = jnp.ones((n, 1), jnp.float32)  # one pod per node, 4 slots
    prio = jnp.asarray([0, 5, 3, 1, 2, 4], jnp.int32)
    mask = jnp.ones(p, bool)
    base = greedy_assign(
        scores, feasible, req, free, prio, mask, greedy_kernel=False
    )
    got = greedy_assign(
        scores, feasible, req, free, prio, mask, greedy_kernel=True
    )
    np.testing.assert_array_equal(
        np.asarray(got.node_idx), np.asarray(base.node_idx)
    )
    # two pods (the lowest-priority ones) must be unassigned
    assert int(got.n_assigned) == 4
    np.testing.assert_array_equal(
        np.asarray(got.free_after), np.zeros((n, 1), np.float32)
    )


def test_fused_rejects_incompatible_options():
    from kubernetes_scheduler_tpu.engine import (
        check_fused_contract,
        schedule_batch,
    )
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(8, seed=0)
    pods = gen_pods(2, seed=1)
    # softmax stays outside the fused contract (its exp/sum statistics
    # would fold the NEG sentinels); min_max is admitted on the dense
    # surface via the kernel epilogue (test_fused_min_max_matches_unfused)
    with pytest.raises(ValueError, match="normalizer"):
        schedule_batch(snap, pods, normalizer="softmax", fused=True)
    with pytest.raises(ValueError, match="fused kernel"):
        schedule_batch(
            snap, pods, policy="free_capacity", normalizer="none", fused=True
        )
    # the sharded factories keep the strict contract: their min-max
    # bounds are global pmax/pmin reductions the shard-local kernel
    # epilogue cannot see (engine.check_fused_contract min_max_ok)
    with pytest.raises(ValueError, match="normalizer"):
        check_fused_contract("balanced_cpu_diskio", "min_max")
    check_fused_contract("balanced_cpu_diskio", "min_max", min_max_ok=True)


def test_fused_min_max_matches_unfused():
    """normalizer="min_max" through the kernel epilogue: decisions AND
    feasible-cell score values bitwise equal to the unfused
    normalize-then-mask composition, on both assigners."""
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(96, seed=11, constraints=True)
    pods = gen_pods(24, seed=12, constraints=True)
    for assigner in ("greedy", "auction"):
        for soft in (False, True):
            base = schedule_batch(
                snap, pods, assigner=assigner, normalizer="min_max",
                fused=False, soft=soft,
            )
            got = schedule_batch(
                snap, pods, assigner=assigner, normalizer="min_max",
                fused=True, soft=soft,
            )
            np.testing.assert_array_equal(
                np.asarray(got.feasible), np.asarray(base.feasible)
            )
            np.testing.assert_array_equal(
                np.asarray(got.node_idx), np.asarray(base.node_idx)
            )
            feas = np.asarray(base.feasible)
            np.testing.assert_array_equal(
                np.asarray(got.scores)[feas], np.asarray(base.scores)[feas]
            )
