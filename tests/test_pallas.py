"""The fused Pallas kernel must match the unfused op composition exactly.

Runs through the Pallas interpreter on CPU (conftest forces the cpu
backend); on TPU the same kernel compiles via Mosaic with identical
semantics.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_scheduler_tpu.ops import (
    balanced_cpu_diskio,
    resource_fit,
    utilization_stats,
)
from kubernetes_scheduler_tpu.ops.assign import NEG
from kubernetes_scheduler_tpu.ops.pallas_fused import fused_masked_score

RNG = np.random.default_rng(7)


def make_problem(p, n, r=3):
    alloc = RNG.uniform(10, 100, (n, r)).astype(np.float32)
    reqd = (alloc * RNG.uniform(0, 1, (n, r))).astype(np.float32)
    disk_io = RNG.uniform(0, 50, n).astype(np.float32)
    cpu = RNG.uniform(0, 100, n).astype(np.float32)
    pod_req = RNG.uniform(0, 40, (p, r)).astype(np.float32)
    # exercise the unrequested-resource bypass
    pod_req[RNG.uniform(size=(p, r)) < 0.3] = 0.0
    r_cpu = pod_req[:, 0] * 10
    r_io = RNG.uniform(0, 30, p).astype(np.float32)
    r_io[RNG.uniform(size=p) < 0.25] = 0.0  # missing diskIO annotation
    return alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io


def reference_masked(alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io,
                     node_mask, pod_mask):
    stats = utilization_stats(
        jnp.asarray(disk_io), jnp.asarray(cpu), jnp.asarray(node_mask)
    )
    score = balanced_cpu_diskio(stats, jnp.asarray(r_cpu), jnp.asarray(r_io))
    fits = resource_fit(
        jnp.asarray(alloc), jnp.asarray(reqd), jnp.asarray(pod_req),
        jnp.asarray(node_mask),
    )
    fits = fits & jnp.asarray(pod_mask)[:, None]
    return np.asarray(jnp.where(fits, score, NEG))


@pytest.mark.parametrize("p,n", [(4, 16), (17, 130), (64, 300)])
def test_fused_matches_composition(p, n):
    alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io = make_problem(p, n)
    node_mask = np.ones(n, bool)
    node_mask[-max(1, n // 7):] = False
    pod_mask = np.ones(p, bool)
    pod_mask[-1] = False
    stats = utilization_stats(
        jnp.asarray(disk_io), jnp.asarray(cpu), jnp.asarray(node_mask)
    )
    got = np.asarray(
        fused_masked_score(
            stats.u, stats.v, jnp.asarray(node_mask),
            jnp.asarray(alloc), jnp.asarray(reqd),
            jnp.asarray(r_cpu), jnp.asarray(r_io),
            jnp.asarray(pod_req), jnp.asarray(pod_mask),
            tile_p=8, tile_n=128,
        )
    )
    want = reference_masked(
        alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io, node_mask, pod_mask
    )
    feas_got = got > NEG * 0.5
    feas_want = want > NEG * 0.5
    np.testing.assert_array_equal(feas_got, feas_want)
    np.testing.assert_allclose(
        got[feas_want], want[feas_want], rtol=1e-5, atol=1e-5
    )
    assert (got[~feas_want] == NEG).all()


def test_fused_padding_is_masked():
    p, n = 5, 37
    alloc, reqd, disk_io, cpu, pod_req, r_cpu, r_io = make_problem(p, n)
    got = np.asarray(
        fused_masked_score(
            jnp.asarray(disk_io / 50.0), jnp.asarray(cpu / 100.0),
            jnp.ones(n, bool),
            jnp.asarray(alloc), jnp.asarray(reqd),
            jnp.asarray(r_cpu), jnp.asarray(r_io),
            jnp.asarray(pod_req), jnp.ones(p, bool),
            tile_p=8, tile_n=128,
        )
    )
    assert got.shape == (p, n)


@pytest.mark.parametrize("features", [{}, {"constraints": True}, {"gpu": True}])
@pytest.mark.parametrize("assigner", ["greedy", "auction"])
def test_fused_engine_decisions_match_unfused(features, assigner):
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(96, seed=3, **features)
    pods = gen_pods(24, seed=4, **features)
    base = schedule_batch(
        snap, pods, assigner=assigner, normalizer="none", fused=False
    )
    got = schedule_batch(
        snap, pods, assigner=assigner, normalizer="none", fused=True
    )
    np.testing.assert_array_equal(
        np.asarray(got.feasible), np.asarray(base.feasible)
    )
    np.testing.assert_array_equal(
        np.asarray(got.node_idx), np.asarray(base.node_idx)
    )


def test_fused_windows_match_unfused():
    from kubernetes_scheduler_tpu.engine import schedule_windows, stack_windows
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(64, seed=5)
    pods = stack_windows(gen_pods(32, seed=6), 8)
    base = schedule_windows(snap, pods, fused=False)
    got = schedule_windows(snap, pods, fused=True)
    np.testing.assert_array_equal(
        np.asarray(got.node_idx), np.asarray(base.node_idx)
    )
    assert int(got.n_assigned) == int(base.n_assigned)


def test_fused_rejects_incompatible_options():
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(8, seed=0)
    pods = gen_pods(2, seed=1)
    with pytest.raises(ValueError, match="normalizer"):
        schedule_batch(snap, pods, normalizer="min_max", fused=True)
    with pytest.raises(ValueError, match="fused kernel"):
        schedule_batch(
            snap, pods, policy="free_capacity", normalizer="none", fused=True
        )
