"""Learned-scorer model family: shapes, training convergence, sharded step."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_scheduler_tpu.engine import compute_scores
from kubernetes_scheduler_tpu.models import (
    HEURISTIC_POLICIES,
    NodeScorer,
    get_policy,
    init_train_state,
    make_features,
    train_step,
)
from kubernetes_scheduler_tpu.models.learned import NODE_FEATURES, POD_FEATURES
from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods
import functools


def test_policy_registry():
    assert set(HEURISTIC_POLICIES) == {
        "balanced_cpu_diskio", "balanced_diskio", "free_capacity", "card",
        "least_allocated", "balanced_allocation", "image_locality",
        "learned",
    }
    assert get_policy("balanced_cpu_diskio").live_in_reference
    # every engine-schedulable registry entry is a real engine policy
    from kubernetes_scheduler_tpu.engine import POLICIES

    assert {
        n for n, p in HEURISTIC_POLICIES.items() if p.engine_schedulable
    } == set(POLICIES)
    with pytest.raises(ValueError):
        get_policy("nope")


def test_features_and_forward_shapes():
    snap = gen_cluster(32, seed=0)
    pods = gen_pods(8, seed=1)
    pod_x, node_x = make_features(snap, pods)
    assert pod_x.shape == (8, POD_FEATURES)
    assert node_x.shape == (32, NODE_FEATURES)
    state, model, _ = init_train_state(jax.random.key(0))
    scores = model.apply(state.params, pod_x, node_x)
    assert scores.shape == (8, 32)
    assert scores.dtype == jnp.float32
    assert np.isfinite(np.asarray(scores)).all()


def test_training_reduces_imitation_loss():
    snap = gen_cluster(48, seed=2)
    pods = gen_pods(16, seed=3)
    pod_x, node_x = make_features(snap, pods)
    teacher = compute_scores(snap, pods, "balanced_cpu_diskio")
    state, model, tx = init_train_state(jax.random.key(1), learning_rate=3e-3)
    step = jax.jit(functools.partial(train_step, model=model, tx=tx))
    losses = []
    for _ in range(30):
        state, loss = step(
            state, pod_x=pod_x, node_x=node_x, teacher_scores=teacher,
            node_mask=snap.node_mask, pod_mask=pods.pod_mask,
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    assert int(state.step) == 30


def test_sharded_train_step_matches_single_device():
    """GSPMD dp x node sharding produces the same loss as unsharded."""
    snap = gen_cluster(32, seed=4)
    pods = gen_pods(8, seed=5)
    pod_x, node_x = make_features(snap, pods)
    teacher = compute_scores(snap, pods, "balanced_cpu_diskio")
    state, model, tx = init_train_state(jax.random.key(2))
    step = jax.jit(functools.partial(train_step, model=model, tx=tx))
    _, loss_single = step(
        state, pod_x=pod_x, node_x=node_x, teacher_scores=teacher,
        node_mask=snap.node_mask, pod_mask=pods.pod_mask,
    )

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "node"))
    s = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    with mesh:
        _, loss_sharded = step(
            jax.device_put(state, s(P())),
            pod_x=jax.device_put(pod_x, s(P("dp", None))),
            node_x=jax.device_put(node_x, s(P("node", None))),
            teacher_scores=jax.device_put(teacher, s(P("dp", "node"))),
            node_mask=jax.device_put(snap.node_mask, s(P("node"))),
            pod_mask=jax.device_put(pods.pod_mask, s(P("dp"))),
        )
    np.testing.assert_allclose(
        float(loss_sharded), float(loss_single), rtol=2e-2
    )


def test_graft_entry_contract():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert int(out.n_assigned) > 0
    g.dryrun_multichip(8)


def test_checkpoint_roundtrip(tmp_path):
    from kubernetes_scheduler_tpu.models.learned import (
        restore_checkpoint,
        save_checkpoint,
    )

    state, model, tx = init_train_state(jax.random.key(0))
    snap = gen_cluster(16, seed=0)
    pods = gen_pods(4, seed=1)
    pod_x, node_x = make_features(snap, pods)
    teacher = compute_scores(snap, pods, "balanced_cpu_diskio")
    state, _ = jax.jit(functools.partial(train_step, model=model, tx=tx))(
        state, pod_x=pod_x, node_x=node_x, teacher_scores=teacher,
        node_mask=snap.node_mask, pod_mask=pods.pod_mask,
    )
    save_checkpoint(str(tmp_path / "ckpt"), state)

    fresh, model2, _ = init_train_state(jax.random.key(1))
    restored = restore_checkpoint(str(tmp_path / "ckpt"), fresh)
    assert int(restored.step) == 1
    jax.tree_util.tree_map(
        np.testing.assert_allclose, restored.params, state.params
    )
    # restored params drive the model identically
    np.testing.assert_allclose(
        np.asarray(model2.apply(restored.params, pod_x, node_x)),
        np.asarray(model.apply(state.params, pod_x, node_x)),
    )
