"""Scenario harness (sim/scenarios): registry, determinism, event
mechanics, and replay pinning — including the journal -> live-sidecar
round trip (`trace replay --engine`)."""

import numpy as np
import pytest

from kubernetes_scheduler_tpu.sim import scenarios
from kubernetes_scheduler_tpu.sim.scenarios import (
    SCENARIOS,
    SimClock,
    run_scenario,
    scenario_config,
)
from kubernetes_scheduler_tpu.trace.replay import replay_journal


def test_registry_names_match_and_describe():
    assert set(SCENARIOS) == {
        "diurnal", "burst", "node-flap", "zone-failure",
        "anti-affinity-pack", "gang-mix",
        # soak composition (trend-gate + shadow-tailer substrate)
        "soak",
        # chaos programs (sim/faults.py): deterministic fault injection
        "advisor-outage", "sidecar-crash-restart", "rpc-flap",
        "disk-full-journal", "mirror-corruption", "compound-storm",
        # replica fleet (host/replica.py): partitioned-queue conflict storm
        "replica-conflict-storm",
    }
    for name, cls in SCENARIOS.items():
        assert cls.name == name
        assert cls.description
        assert cls.ticks > 0
    # the scenario-smoke gate needs at least two cheap programs
    assert sum(1 for c in SCENARIOS.values() if c.smoke) >= 2
    # every chaos program declares a non-empty fault plan
    for cls in SCENARIOS.values():
        if cls.chaos:
            assert cls(n_nodes=8).fault_plan().windows
        else:
            assert cls(n_nodes=8).fault_plan() is None


def test_unknown_scenario_rejected():
    with pytest.raises(ValueError, match="unknown scenario"):
        scenarios.run("no-such-program")


def test_sim_clock_advances_deterministically():
    clk = SimClock()
    assert clk() == 0.0
    clk.advance()
    clk.advance(2.5)
    assert clk() == 3.5


def _bind_set(tmp_path, name, seed, sub):
    journal = str(tmp_path / f"{name}-{sub}")
    summary = scenarios.run(
        name, n_nodes=24, seed=seed, trace_path=journal
    )
    from kubernetes_scheduler_tpu.trace.recorder import read_journal

    bindings = []
    for rec in read_journal(journal):
        bindings.extend(tuple(b) for b in rec.get("bindings") or ())
    return summary, bindings


@pytest.mark.parametrize("name", ["burst", "gang-mix"])
def test_scenario_same_seed_same_journal(tmp_path, name):
    s1, b1 = _bind_set(tmp_path, name, 7, "a")
    s2, b2 = _bind_set(tmp_path, name, 7, "b")
    assert b1 == b2 and b1
    for key in ("pods_submitted", "pods_bound", "cycles"):
        assert s1[key] == s2[key]
    # a different seed produces different traffic (not vacuous pinning)
    s3, b3 = _bind_set(tmp_path, name, 8, "c")
    assert b3 != b1


def test_zone_failure_mass_reschedules(tmp_path):
    summary = scenarios.run("zone-failure", n_nodes=24, seed=0)
    assert summary["node_failures"] >= 24 // 4 - 1
    assert summary["pods_resubmitted"] > 0
    assert summary["node_restores"] == summary["node_failures"]
    assert summary["fallback_cycles"] == 0


def test_node_flap_flushes_resident_state():
    cfg = scenario_config({"resident_state": True, "pipeline_depth": 1})
    summary = scenarios.run("node-flap", n_nodes=24, seed=0, config=cfg)
    assert summary["node_failures"] > 0 and summary["node_restores"] > 0
    # every flap breaks the delta chain: full uploads beyond the first
    assert summary["full_uploads"] > 1
    assert summary["delta_uploads"] > 0
    assert summary["fallback_cycles"] == 0


def test_anti_affinity_pack_leaves_deterministic_remainder():
    s1 = scenarios.run("anti-affinity-pack", n_nodes=16, seed=0)
    s2 = scenarios.run("anti-affinity-pack", n_nodes=16, seed=0)
    # each wave carries two more members than zones: a structural,
    # seed-stable unschedulable remainder
    assert s1["pods_unschedulable"] > 0
    assert s1["pods_unschedulable"] == s2["pods_unschedulable"]
    assert s1["pods_bound"] == s2["pods_bound"] > 0


def test_gang_mix_exercises_the_gang_machinery():
    summary = scenarios.run("gang-mix", n_nodes=24, seed=1)
    assert summary["gangs_admitted"] > 0
    assert summary["gangs_deferred"] > 0  # stragglers + the oversize gang
    assert summary["fallback_cycles"] == 0


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_replay_pins_e2e(tmp_path, name):
    """The acceptance gate: every shipped scenario's journal replays
    with zero binding diffs — chaos programs included (fault injection
    is deterministic on the virtual clock, and a chaos run must ALSO
    end fully recovered: top rungs, breakers closed)."""
    journal = str(tmp_path / name)
    summary = run_scenario(
        SCENARIOS[name](n_nodes=16), seed=0, trace_path=journal
    )
    assert summary["pods_bound"] > 0
    if SCENARIOS[name].chaos:
        assert summary["recovered"], summary
        # degradation is bounded: faulted cycles never dominate
        assert summary["degraded_cycles"] <= summary["cycles"] // 2
    else:
        assert summary["fallback_cycles"] == 0
    report = replay_journal(journal)
    assert report.replayed > 0
    assert report.binding_diffs == 0, report.to_dict()


def test_scenario_journal_replays_through_live_sidecar(tmp_path):
    """Scenario journal -> `trace replay --engine` round trip against a
    live sidecar: the recorded decisions reproduce across the bridge
    (gang tensors ride the wire; the sidecar masks on its side)."""
    pytest.importorskip("grpc")
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server

    journal = str(tmp_path / "gang-mix-journal")
    summary = scenarios.run(
        "gang-mix", n_nodes=16, seed=0, trace_path=journal
    )
    assert summary["pods_bound"] > 0
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        report = replay_journal(journal, engine=client)
        assert report.replayed > 0
        assert report.binding_diffs == 0, report.to_dict()
    finally:
        client.close()
        server.stop(grace=None)
