"""Soft (preferred) constraints: score terms, never masks.

Covers the three upstream preferred families (NodeAffinity preferred
terms, InterPodAffinity preferred terms, TaintToleration's
PreferNoSchedule scoring) at the kernel, engine, and host-loop levels.
"""

import numpy as np
import jax.numpy as jnp

from kubernetes_scheduler_tpu.engine import (
    make_pod_batch,
    make_snapshot,
    schedule_batch,
)
from kubernetes_scheduler_tpu.ops.constraints import (
    NO_SCHEDULE,
    OP_EXISTS,
    OP_IN,
    PREFER_NO_SCHEDULE,
    TOL_EQUAL,
    node_affinity_preference,
    pod_affinity_preference,
    prefer_no_schedule_penalty,
)


def test_prefer_no_schedule_penalty_counts():
    # node 0: one PreferNoSchedule taint; node 1: one NoSchedule (hard,
    # not counted); node 2: two PreferNoSchedule
    taints = np.zeros((3, 2, 3), np.int32)
    mask = np.zeros((3, 2), bool)
    taints[0, 0] = (5, 1, PREFER_NO_SCHEDULE); mask[0, 0] = True
    taints[1, 0] = (5, 1, NO_SCHEDULE); mask[1, 0] = True
    taints[2, 0] = (5, 1, PREFER_NO_SCHEDULE); mask[2, 0] = True
    taints[2, 1] = (6, 0, PREFER_NO_SCHEDULE); mask[2, 1] = True
    # pod 0: no tolerations; pod 1 tolerates key 5 value 1
    tols = np.zeros((2, 1, 4), np.int32)
    tol_mask = np.zeros((2, 1), bool)
    tols[1, 0] = (5, 1, TOL_EQUAL, 0); tol_mask[1, 0] = True
    pen = np.asarray(prefer_no_schedule_penalty(
        jnp.asarray(taints), jnp.asarray(mask),
        jnp.asarray(tols), jnp.asarray(tol_mask),
    ))
    np.testing.assert_array_equal(pen, [[1, 0, 2], [0, 0, 1]])


def test_node_affinity_preference_weights():
    # nodes: 0 has (k=3, v=7); 1 has (k=3, v=8); 2 has nothing
    labels = np.zeros((3, 1, 2), np.int32)
    lmask = np.zeros((3, 1), bool)
    labels[0, 0] = (3, 7); lmask[0, 0] = True
    labels[1, 0] = (3, 8); lmask[1, 0] = True
    # pod prefers k=3 in {7} with weight 10
    key = np.full((1, 1), 3, np.int32)
    op = np.full((1, 1), OP_IN, np.int32)
    vals = np.full((1, 1, 1), 7, np.int32)
    got = np.asarray(node_affinity_preference(
        jnp.asarray(labels), jnp.asarray(lmask),
        jnp.asarray(key), jnp.asarray(op), jnp.asarray(vals),
        jnp.ones((1, 1, 1), bool), jnp.ones((1, 1), bool),
        jnp.full((1, 1), 10.0),
    ))
    np.testing.assert_array_equal(got, [[10.0, 0.0, 0.0]])


def test_node_affinity_preference_term_grouping():
    """Upstream weighted-term semantics: a preferred term's weight is
    granted ONCE iff EVERY expression in the term matches — never per
    matching expression."""
    # nodes: 0 has (k=3,v=7) and (k=4,v=1); 1 has only (k=3,v=7); 2 none
    labels = np.zeros((3, 2, 2), np.int32)
    lmask = np.zeros((3, 2), bool)
    labels[0, 0] = (3, 7); labels[0, 1] = (4, 1); lmask[0] = True
    labels[1, 0] = (3, 7); lmask[1, 0] = True
    # one preferred term, weight 10, two ANDed expressions:
    # k3 in {7} AND k4 exists
    key = np.asarray([[3, 4]], np.int32)
    op = np.asarray([[OP_IN, OP_EXISTS]], np.int32)
    vals = np.asarray([[[7], [0]]], np.int32)
    vmask = np.asarray([[[True], [False]]])
    term = np.zeros((1, 2), np.int32)  # both in group 0
    got = np.asarray(node_affinity_preference(
        jnp.asarray(labels), jnp.asarray(lmask),
        jnp.asarray(key), jnp.asarray(op), jnp.asarray(vals),
        jnp.asarray(vmask), jnp.ones((1, 2), bool),
        jnp.full((1, 2), 10.0), jnp.asarray(term),
    ))
    # node 0 satisfies BOTH -> 10 once (not 20); node 1 only one -> 0
    np.testing.assert_array_equal(got, [[10.0, 0.0, 0.0]])

    # same expressions as separate terms: weights add per satisfied term
    term2 = np.asarray([[0, 1]], np.int32)
    got2 = np.asarray(node_affinity_preference(
        jnp.asarray(labels), jnp.asarray(lmask),
        jnp.asarray(key), jnp.asarray(op), jnp.asarray(vals),
        jnp.asarray(vmask), jnp.ones((1, 2), bool),
        jnp.full((1, 2), 10.0), jnp.asarray(term2),
    ))
    np.testing.assert_array_equal(got2, [[20.0, 10.0, 0.0]])


def test_pod_affinity_preference_signs():
    counts = jnp.asarray([[2.0, 0.0], [0.0, 1.0]])  # [n=2, S=2]
    got = np.asarray(pod_affinity_preference(
        counts,
        jnp.asarray([[0]]), jnp.asarray([[5.0]]),      # prefer near sel 0, w=5
        jnp.asarray([[1]]), jnp.asarray([[3.0]]),      # prefer away from sel 1, w=3
    ))
    # node 0: sel0 present (+5), sel1 absent (0) => 5; node 1: -3
    np.testing.assert_array_equal(got, [[5.0, -3.0]])
    # out-of-range / padded ids contribute nothing (never unschedulable)
    got2 = np.asarray(pod_affinity_preference(
        counts, jnp.asarray([[7]]), jnp.asarray([[5.0]]),
        jnp.asarray([[-1]]), jnp.asarray([[3.0]]),
    ))
    np.testing.assert_array_equal(got2, [[0.0, 0.0]])


def _uniform_snapshot(n, labels=None, lmask=None, taints=None, tmask=None):
    return make_snapshot(
        allocatable=np.full((n, 3), 100.0, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.full(n, 10.0), cpu_pct=np.full(n, 20.0),
        mem_pct=np.zeros(n),
        node_labels=labels, node_label_mask=lmask,
        taints=taints, taint_mask=tmask,
    )


def test_engine_soft_breaks_tie_toward_preferred_node():
    n = 4
    labels = np.zeros((n, 1, 2), np.int32)
    lmask = np.zeros((n, 1), bool)
    labels[2, 0] = (9, 4); lmask[2, 0] = True  # only node 2 has the label
    snap = _uniform_snapshot(n, labels=labels, lmask=lmask)
    pods = make_pod_batch(
        request=np.full((1, 3), 1.0, np.float32),
        pna_key=np.full((1, 1), 9, np.int32),
        pna_op=np.full((1, 1), OP_IN, np.int32),
        pna_vals=np.full((1, 1, 1), 4, np.int32),
        pna_weight=np.full((1, 1), 5.0, np.float32),
    )
    off = schedule_batch(snap, pods, soft=False)
    on = schedule_batch(snap, pods, soft=True)
    assert int(off.node_idx[0]) == 0  # uniform scores: first argmax
    assert int(on.node_idx[0]) == 2   # preference breaks the tie


def test_engine_soft_avoids_prefer_no_schedule_taint():
    n = 3
    taints = np.zeros((n, 1, 3), np.int32)
    tmask = np.zeros((n, 1), bool)
    taints[0, 0] = (1, 1, PREFER_NO_SCHEDULE); tmask[0, 0] = True
    snap = _uniform_snapshot(n, taints=taints, tmask=tmask)
    pods = make_pod_batch(request=np.full((1, 3), 1.0, np.float32))
    on = schedule_batch(snap, pods, soft=True)
    assert int(on.node_idx[0]) != 0  # steered off the soft-tainted node
    off = schedule_batch(snap, pods, soft=False)
    assert int(off.node_idx[0]) == 0  # hard path ignores PreferNoSchedule


def test_host_loop_preferred_terms_end_to_end():
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.host.types import (
        Container, MatchExpression, Node, Pod, PodAffinityTerm,
        WeightedExpression,
    )
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes = [
        Node(name=f"n{i}", allocatable={"cpu": 8000.0, "memory": 32 * 2**30,
                                        "pods": 110},
             labels={"disk": "ssd"} if i == 2 else {})
        for i in range(4)
    ]
    running = [Pod(name="db", labels={"app": "db"}, node_name="n3")]

    class A:
        def fetch(self):
            return {nd.name: NodeUtil(cpu_pct=10.0, disk_io=5.0) for nd in nodes}

    cfg = SchedulerConfig(min_device_work=0)
    cfg.feature_gates.native_host = False
    sched = Scheduler(cfg, advisor=A(), list_nodes=lambda: nodes,
                      list_running_pods=lambda: running)
    # prefers ssd nodes AND proximity to the db pod; ssd weight dominates
    sched.submit(Pod(
        name="web",
        containers=[Container(requests={"cpu": 100.0})],
        preferred_node_affinity=[
            WeightedExpression(MatchExpression("disk", "In", ["ssd"]), weight=50)
        ],
        pod_affinity=[PodAffinityTerm(match_labels={"app": "db"},
                                      preferred=True, weight=10)],
    ))
    m = sched.run_cycle()
    assert m.pods_bound == 1 and not m.used_fallback
    assert sched.binder.bindings[0].node_name == "n2"


def test_running_pods_preferred_terms_score_symmetrically():
    """Upstream InterPodAffinity also scores EXISTING pods' preferred terms
    against the incoming pod: a running pod with a preferred anti term
    pushes matching incomers away; a preferred affinity term pulls them."""
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.host.types import (
        Container, Node, Pod, PodAffinityTerm,
    )
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes = [
        Node(name=f"n{i}", allocatable={"cpu": 8000.0, "memory": 32 * 2**30,
                                        "pods": 110})
        for i in range(3)
    ]
    running = [
        # latency-sensitive pod on n0 prefers web pods keep away
        Pod(name="solo", node_name="n0",
            pod_affinity=[PodAffinityTerm(match_labels={"app": "web"},
                                          anti=True, preferred=True, weight=40)]),
        # cache pod on n2 prefers web pods nearby
        Pod(name="cache", node_name="n2",
            pod_affinity=[PodAffinityTerm(match_labels={"app": "web"},
                                          preferred=True, weight=20)]),
    ]

    class A:
        def fetch(self):
            return {nd.name: NodeUtil(cpu_pct=10.0, disk_io=5.0) for nd in nodes}

    cfg = SchedulerConfig(min_device_work=0)
    cfg.feature_gates.native_host = False
    s = Scheduler(cfg, advisor=A(), list_nodes=lambda: nodes,
                  list_running_pods=lambda: running)
    s.submit(Pod(name="w", labels={"app": "web"},
                 containers=[Container(requests={"cpu": 100.0})]))
    m = s.run_cycle()
    assert m.pods_bound == 1 and not m.used_fallback
    assert s.binder.bindings[0].node_name == "n2"  # pulled to cache, pushed off solo
