"""parallel/engine.py's dedicated tier-1 surface.

Fast units pin the sharded factories' argument contracts (mesh-axis
validation, assigner/knob clashes — errors that otherwise surface as
shard_map tracebacks mid-dispatch) and the resident delta ROUTING
(host.snapshot.shard_snapshot_delta: owner-shard emission, shard-local
coordinates, empty shards shipping nothing, the stacked per-shard
apply bitwise the dense fold). The slow-marked e2es run in a
SUBPROCESS on an 8-device host-platform mesh (the multichip dryrun
recipe: `XLA_FLAGS=--xla_force_host_platform_device_count=8` forced in
the child's environment, independent of the parent harness) asserting
sharded<->dense bitwise `node_idx` parity for the greedy, auction, and
whole-backlog windows programs — and, for the ShardedEngine, across
full/delta/flush-on-churn RESIDENT cycles against LocalEngine."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- fast units: the factory argument contracts ---------------------------


def test_mesh_specs_reject_unknown_axis():
    from kubernetes_scheduler_tpu.parallel import (
        make_mesh,
        make_sharded_schedule_fn,
    )

    with pytest.raises(ValueError, match="lacks axes"):
        make_sharded_schedule_fn(make_mesh(8), node_axes="bogus")


def test_unknown_assigner_and_normalizer_rejected():
    from kubernetes_scheduler_tpu.parallel import (
        make_mesh,
        make_sharded_schedule_fn,
    )

    with pytest.raises(ValueError, match="unknown assigner"):
        make_sharded_schedule_fn(make_mesh(8), assigner="bogus")


def test_score_plugins_clash_with_other_scorers():
    # name deliberately avoids conftest's slow-pattern substrings
    # ("fused" would silently deselect this sub-second unit from tier-1)
    from kubernetes_scheduler_tpu.parallel import (
        make_mesh,
        make_sharded_schedule_fn,
    )

    mesh = make_mesh(8)
    plugins = (("balanced_cpu_diskio", 1.0),)
    with pytest.raises(ValueError, match="score_plugins"):
        make_sharded_schedule_fn(
            mesh, score_plugins=plugins, score_fn=lambda s, p: None
        )
    with pytest.raises(ValueError, match="score_plugins"):
        make_sharded_schedule_fn(mesh, score_plugins=plugins, fused=True)


def test_knob_wrapper_clamps_rounds_to_int32():
    """A wire int64 rounds value means 'run to convergence' — the
    wrapper must clamp instead of letting OverflowError surface as a
    gRPC INTERNAL."""
    from kubernetes_scheduler_tpu.parallel.engine import _with_auction_knobs

    seen = {}

    def fake_jfn(snapshot, pods, rounds, price_frac):
        seen["rounds"] = int(rounds)
        seen["price_frac"] = float(price_frac)
        return None

    call = _with_auction_knobs(fake_jfn, 1024, 1.0)
    call(None, None, auction_rounds=2**40, auction_price_frac=0.5)
    assert seen["rounds"] == 2**31 - 1
    assert seen["price_frac"] == 0.5


# ---- delta routing units (fast: names avoid the slow patterns) ------------


def _routing_delta(n=64, r=3, s=2, touch=()):
    """A SnapshotDelta whose REAL changed rows are exactly `touch`
    (global indices), padded with the dense sentinel n like
    host.snapshot.snapshot_delta emits."""
    from kubernetes_scheduler_tpu.engine import SnapshotDelta
    from kubernetes_scheduler_tpu.host.snapshot import _rows_padded

    touch = np.asarray(sorted(touch), np.int32)
    rows = _rows_padded(touch, n)
    req_vals = np.zeros((len(rows), r), np.float32)
    req_vals[: len(touch)] = np.arange(
        len(touch) * r, dtype=np.float32
    ).reshape(len(touch), r) + 1.0
    util_vals = np.zeros((len(rows), 5), np.float32)
    util_vals[: len(touch)] = 0.5
    dom_vals = np.zeros((len(rows), s, 4), np.float32)
    return SnapshotDelta(
        req_rows=rows,
        req_vals=req_vals,
        util_rows=rows.copy(),
        util_vals=util_vals,
        dom_rows=_rows_padded(np.asarray([], np.int32), n),
        dom_vals=np.zeros((8, s, 4), np.float32),
        node_mask=np.ones(n, bool),
    )


def test_delta_routing_owner_shards_only():
    """Rows in shards {0, 3, 7} of an 8-shard mesh produce exactly
    those per-shard deltas — empty shards ship nothing — with rows in
    shard-local coordinates and values carried verbatim."""
    from kubernetes_scheduler_tpu.host.snapshot import shard_snapshot_delta

    n, d = 64, 8  # n_local = 8
    touch = (1, 7, 3 * 8 + 2, 7 * 8 + 5)  # shards 0, 0, 3, 7
    delta = _routing_delta(n=n, touch=touch)
    routed = shard_snapshot_delta(delta, d)
    assert sorted(routed) == [0, 3, 7]
    sh0 = routed[0]
    assert sorted(sh0.req_rows[sh0.req_rows < 8].tolist()) == [1, 7]
    sh3 = routed[3]
    assert sh3.req_rows[sh3.req_rows < 8].tolist() == [2]
    # values ride with their rows: shard 3's single row carries the
    # third touched row's payload
    got = sh3.req_vals[list(sh3.req_rows).index(2)]
    want = delta.req_vals[list(delta.req_rows).index(3 * 8 + 2)]
    assert np.array_equal(got, want)
    sh7 = routed[7]
    assert sh7.req_rows[sh7.req_rows < 8].tolist() == [5]
    # pad sentinel is the SHARD's axis length, and each shard's mask is
    # its local slice
    for i, sh in routed.items():
        assert (sh.req_rows[sh.req_rows >= 8] == 8).all()
        assert sh.node_mask.shape == (8,)


def test_delta_routing_mask_change_emits_rowless_shard():
    """A shard whose node-mask slice changed must emit even with no
    changed rows (its retained mask would otherwise go stale)."""
    from kubernetes_scheduler_tpu.host.snapshot import shard_snapshot_delta

    delta = _routing_delta(n=64, touch=(1,))  # rows only in shard 0
    prev = np.ones(64, bool)
    prev[5 * 8 + 3] = False  # shard 5's retained mask differs
    routed = shard_snapshot_delta(delta, 8, prev_node_mask=prev)
    assert sorted(routed) == [0, 5]
    # shard 5 ships only sentinels + its (current) mask slice
    sh5 = routed[5]
    assert (sh5.req_rows == 8).all() and (sh5.util_rows == 8).all()
    assert sh5.node_mask.all()
    # without the prev mask, shard 5 ships nothing
    assert sorted(shard_snapshot_delta(delta, 8)) == [0]


def test_delta_routing_rejects_indivisible_axis():
    from kubernetes_scheduler_tpu.host.snapshot import shard_snapshot_delta

    with pytest.raises(ValueError, match="does not divide"):
        shard_snapshot_delta(_routing_delta(n=64), 7)


def test_stacked_shard_apply_matches_dense_fold():
    """The routed-and-stacked per-shard fold must be BITWISE the dense
    apply_snapshot_delta on the same snapshot/delta (the appliers share
    one body — this pins the routing/stacking around it)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubernetes_scheduler_tpu import engine
    from kubernetes_scheduler_tpu.host.snapshot import shard_snapshot_delta
    from kubernetes_scheduler_tpu.parallel import (
        make_mesh,
        make_sharded_apply_delta_fn,
        stack_shard_deltas,
    )
    from kubernetes_scheduler_tpu.parallel.mesh import NODE_AXIS

    rng = np.random.default_rng(11)
    n, r, s, d = 64, 3, 2, 8
    snap = engine.make_snapshot(
        allocatable=rng.uniform(1000, 4000, (n, r)).astype(np.float32),
        requested=rng.uniform(0, 900, (n, r)).astype(np.float32),
        disk_io=rng.uniform(0, 50, n).astype(np.float32),
        cpu_pct=rng.uniform(0, 100, n).astype(np.float32),
        mem_pct=rng.uniform(0, 100, n).astype(np.float32),
        domain_counts=np.zeros((n, s), np.float32),
    )
    snap = type(snap)(*[np.asarray(a) for a in snap])
    delta = _routing_delta(n=n, r=r, s=s, touch=(0, 9, 30, 63))
    dense = engine.apply_snapshot_delta(snap, delta)
    mesh = make_mesh(d)
    node = NamedSharding(mesh, P(NODE_AXIS))
    snap_dev = jax.device_put(snap, type(snap)(*[node] * len(snap)))
    routed = shard_snapshot_delta(delta, d)
    stacked = stack_shard_deltas(delta, routed, d)
    got = make_sharded_apply_delta_fn(mesh)(snap_dev, stacked)
    for name, a, b in zip(type(snap)._fields, got, dense):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


# ---- the subprocess e2e (slow-marked by name) -----------------------------

_E2E_SCRIPT = """
import json

import numpy as np
import jax

from kubernetes_scheduler_tpu import engine
from kubernetes_scheduler_tpu.parallel import make_mesh, make_sharded_schedule_fn
from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn

rng = np.random.default_rng(7)
n, p, r = 64, 24, 3
snapshot = engine.make_snapshot(
    allocatable=rng.integers(4000, 16000, (n, r)).astype(np.float32),
    requested=rng.integers(0, 4000, (n, r)).astype(np.float32),
    disk_io=rng.uniform(0, 50, n),
    cpu_pct=rng.uniform(0, 100, n),
    mem_pct=rng.uniform(0, 100, n),
)
pods = engine.make_pod_batch(
    request=rng.integers(100, 3000, (p, r)).astype(np.float32),
    r_io=rng.uniform(0, 40, p),
    priority=rng.integers(0, 10, p),
)
mesh = make_mesh(8)
out = {"devices": jax.device_count()}
for name in ("greedy", "auction"):
    dense = engine.schedule_batch(snapshot, pods, assigner=name)
    sharded = make_sharded_schedule_fn(mesh, assigner=name)(snapshot, pods)
    out[name] = {
        "parity": np.asarray(sharded.node_idx).tolist()
        == np.asarray(dense.node_idx).tolist(),
        "n_assigned": int(sharded.n_assigned),
    }
windows = engine.stack_windows(pods, 8)
# the established pairing (tests/test_engine.py): the sharded windows
# scan ALWAYS evaluates (anti)affinity dynamically against live counts
# and normalizes with global bounds, which corresponds to the dense
# scan's affinity_aware=True + normalizer="none" configuration
dense_w = engine.schedule_windows(
    snapshot, windows, assigner="greedy", affinity_aware=True,
    normalizer="none",
)
sharded_w = make_sharded_windows_fn(mesh, normalizer="min_max")(
    snapshot, windows
)
out["windows"] = {
    "parity": np.asarray(sharded_w.node_idx).tolist()
    == np.asarray(dense_w.node_idx).tolist(),
    "n_assigned": int(sharded_w.n_assigned),
}
print(json.dumps(out))
"""


_RESIDENT_E2E_SCRIPT = """
import json

import numpy as np

from kubernetes_scheduler_tpu import engine
from kubernetes_scheduler_tpu.host.snapshot import snapshot_delta
from kubernetes_scheduler_tpu.parallel import ShardedEngine

rng = np.random.default_rng(5)
n, p, r = 64, 24, 3
# the static block the delta protocol keys on: a churn step BUMPS this
# (allocatable edits are never delta-expressible -> flush to full) and
# later cycles diff against the bumped value
cur = {"alloc": rng.integers(4000, 16000, (n, r)).astype(np.float32)}


def mksnap(seed):
    g = np.random.default_rng(seed)
    s = engine.make_snapshot(
        allocatable=cur["alloc"],
        requested=g.integers(0, 4000, (n, r)).astype(np.float32),
        disk_io=g.uniform(0, 50, n),
        cpu_pct=g.uniform(0, 100, n),
        mem_pct=g.uniform(0, 100, n),
    )
    # numpy leaves, like the real host builder (private device buffers
    # on upload — nothing the donated folds consume can alias)
    return type(s)(*[np.asarray(x) for x in s])


pods = engine.make_pod_batch(
    request=rng.integers(100, 3000, (p, r)).astype(np.float32),
    r_io=rng.uniform(0, 40, p),
    priority=rng.integers(0, 10, p),
)
se, le = ShardedEngine(), engine.LocalEngine()
out = {"devices": se.n_shards, "cycles": []}
for kw in (
    dict(assigner="auction", normalizer="none", fused=True),
    dict(assigner="greedy", normalizer="min_max"),
):
    se.invalidate_resident()
    le.invalidate_resident()
    prev, epoch = None, 0
    plan = ["full", "delta", "delta", "churn", "delta"]
    for step in plan:
        epoch += 1
        if step == "churn":
            # static-block churn (allocatable moves): snapshot_delta
            # returns None and both engines must flush to full
            cur["alloc"] = cur["alloc"] + np.float32(1.0)
        snap = mksnap(100 + epoch)
        delta = (
            snapshot_delta(prev, snap) if prev is not None else None
        )
        if step == "churn":
            assert delta is None, "churn step still delta-expressible"
        rs = se.schedule_resident(snap, pods, delta=delta, epoch=epoch, **kw)
        rl = le.schedule_resident(snap, pods, delta=delta, epoch=epoch, **kw)
        out["cycles"].append({
            "step": step,
            "kw": kw.get("assigner"),
            "delta_sent": delta is not None,
            "used_delta": [se.resident_used_delta, le.resident_used_delta],
            "parity": np.asarray(rs.node_idx).tolist()
            == np.asarray(rl.node_idx).tolist(),
            "assigned": int(rs.n_assigned),
            "shard_bytes": list(se.shard_delta_bytes),
        })
        prev = snap

# windows-resident on the same epoch sequence, fused (the layout-carry
# scan on the dense side vs the sharded re-prep scan)
wpods = engine.stack_windows(
    engine.make_pod_batch(
        request=rng.integers(100, 3000, (32, r)).astype(np.float32),
        r_io=rng.uniform(0, 40, 32),
        priority=rng.integers(0, 10, 32),
    ),
    8,
)
snap = mksnap(999)
delta = snapshot_delta(prev, snap)
kw = dict(assigner="greedy", normalizer="none", fused=True)
ws = se.schedule_windows_resident(snap, wpods, delta=delta, epoch=epoch + 1, **kw)
wl = le.schedule_windows_resident(snap, wpods, delta=delta, epoch=epoch + 1, **kw)
out["windows"] = {
    "parity": np.asarray(ws.node_idx).tolist()
    == np.asarray(wl.node_idx).tolist(),
    "used_delta": [se.resident_used_delta, le.resident_used_delta],
    "assigned": int(ws.n_assigned),
}
print(json.dumps(out))
"""


def test_sharded_resident_parity_subprocess_e2e():
    """ShardedEngine vs LocalEngine across full/delta/flush-on-churn
    resident cycles (both assigners, fused and unfused), plus the
    windows-resident surface: node_idx must be BITWISE identical every
    cycle, the delta/full path choice must agree, and delta cycles must
    report per-shard routed bytes."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    proc = subprocess.run(
        [sys.executable, "-c", _RESIDENT_E2E_SCRIPT],
        capture_output=True, text=True, timeout=540, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["devices"] == 8, out
    assert len(out["cycles"]) == 10
    for cyc in out["cycles"]:
        assert cyc["parity"], cyc
        assert cyc["assigned"] > 0, cyc
        assert cyc["used_delta"][0] == cyc["used_delta"][1], cyc
        # the delta/full choice matches the plan: full + churn flush,
        # deltas apply
        want_delta = cyc["step"] == "delta"
        assert cyc["used_delta"][0] == want_delta, cyc
        if want_delta:
            assert sum(cyc["shard_bytes"]) > 0, cyc
    win = out["windows"]
    assert win["parity"] and win["assigned"] > 0, win
    assert win["used_delta"] == [True, True], win


def test_sharded_engine_subprocess_parity_e2e():
    """The multichip dryrun recipe as a pinned test: a fresh process
    with an 8-device host-platform topology runs the sharded engine
    end to end; node_idx parity with the dense path must be BITWISE
    for greedy, auction, and the whole-backlog windows program."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    proc = subprocess.run(
        [sys.executable, "-c", _E2E_SCRIPT],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["devices"] == 8, out
    for name in ("greedy", "auction", "windows"):
        assert out[name]["parity"], (name, out)
        assert out[name]["n_assigned"] > 0, (name, out)
