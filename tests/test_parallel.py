"""parallel/engine.py's dedicated tier-1 surface.

Fast units pin the sharded factories' argument contracts (mesh-axis
validation, assigner/knob clashes — errors that otherwise surface as
shard_map tracebacks mid-dispatch), and the slow-marked e2e runs the
sharded engine in a SUBPROCESS on an 8-device host-platform mesh (the
multichip dryrun recipe: `XLA_FLAGS=--xla_force_host_platform_device_
count=8` forced in the child's environment, independent of the parent
harness) asserting sharded<->dense bitwise `node_idx` parity for the
greedy, auction, and whole-backlog windows programs."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- fast units: the factory argument contracts ---------------------------


def test_mesh_specs_reject_unknown_axis():
    from kubernetes_scheduler_tpu.parallel import (
        make_mesh,
        make_sharded_schedule_fn,
    )

    with pytest.raises(ValueError, match="lacks axes"):
        make_sharded_schedule_fn(make_mesh(8), node_axes="bogus")


def test_unknown_assigner_and_normalizer_rejected():
    from kubernetes_scheduler_tpu.parallel import (
        make_mesh,
        make_sharded_schedule_fn,
    )

    with pytest.raises(ValueError, match="unknown assigner"):
        make_sharded_schedule_fn(make_mesh(8), assigner="bogus")


def test_score_plugins_clash_with_other_scorers():
    # name deliberately avoids conftest's slow-pattern substrings
    # ("fused" would silently deselect this sub-second unit from tier-1)
    from kubernetes_scheduler_tpu.parallel import (
        make_mesh,
        make_sharded_schedule_fn,
    )

    mesh = make_mesh(8)
    plugins = (("balanced_cpu_diskio", 1.0),)
    with pytest.raises(ValueError, match="score_plugins"):
        make_sharded_schedule_fn(
            mesh, score_plugins=plugins, score_fn=lambda s, p: None
        )
    with pytest.raises(ValueError, match="score_plugins"):
        make_sharded_schedule_fn(mesh, score_plugins=plugins, fused=True)


def test_knob_wrapper_clamps_rounds_to_int32():
    """A wire int64 rounds value means 'run to convergence' — the
    wrapper must clamp instead of letting OverflowError surface as a
    gRPC INTERNAL."""
    from kubernetes_scheduler_tpu.parallel.engine import _with_auction_knobs

    seen = {}

    def fake_jfn(snapshot, pods, rounds, price_frac):
        seen["rounds"] = int(rounds)
        seen["price_frac"] = float(price_frac)
        return None

    call = _with_auction_knobs(fake_jfn, 1024, 1.0)
    call(None, None, auction_rounds=2**40, auction_price_frac=0.5)
    assert seen["rounds"] == 2**31 - 1
    assert seen["price_frac"] == 0.5


# ---- the subprocess e2e (slow-marked by name) -----------------------------

_E2E_SCRIPT = """
import json

import numpy as np
import jax

from kubernetes_scheduler_tpu import engine
from kubernetes_scheduler_tpu.parallel import make_mesh, make_sharded_schedule_fn
from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn

rng = np.random.default_rng(7)
n, p, r = 64, 24, 3
snapshot = engine.make_snapshot(
    allocatable=rng.integers(4000, 16000, (n, r)).astype(np.float32),
    requested=rng.integers(0, 4000, (n, r)).astype(np.float32),
    disk_io=rng.uniform(0, 50, n),
    cpu_pct=rng.uniform(0, 100, n),
    mem_pct=rng.uniform(0, 100, n),
)
pods = engine.make_pod_batch(
    request=rng.integers(100, 3000, (p, r)).astype(np.float32),
    r_io=rng.uniform(0, 40, p),
    priority=rng.integers(0, 10, p),
)
mesh = make_mesh(8)
out = {"devices": jax.device_count()}
for name in ("greedy", "auction"):
    dense = engine.schedule_batch(snapshot, pods, assigner=name)
    sharded = make_sharded_schedule_fn(mesh, assigner=name)(snapshot, pods)
    out[name] = {
        "parity": np.asarray(sharded.node_idx).tolist()
        == np.asarray(dense.node_idx).tolist(),
        "n_assigned": int(sharded.n_assigned),
    }
windows = engine.stack_windows(pods, 8)
# the established pairing (tests/test_engine.py): the sharded windows
# scan ALWAYS evaluates (anti)affinity dynamically against live counts
# and normalizes with global bounds, which corresponds to the dense
# scan's affinity_aware=True + normalizer="none" configuration
dense_w = engine.schedule_windows(
    snapshot, windows, assigner="greedy", affinity_aware=True,
    normalizer="none",
)
sharded_w = make_sharded_windows_fn(mesh, normalizer="min_max")(
    snapshot, windows
)
out["windows"] = {
    "parity": np.asarray(sharded_w.node_idx).tolist()
    == np.asarray(dense_w.node_idx).tolist(),
    "n_assigned": int(sharded_w.n_assigned),
}
print(json.dumps(out))
"""


def test_sharded_engine_subprocess_parity_e2e():
    """The multichip dryrun recipe as a pinned test: a fresh process
    with an 8-device host-platform topology runs the sharded engine
    end to end; node_idx parity with the dense path must be BITWISE
    for greedy, auction, and the whole-backlog windows program."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    proc = subprocess.run(
        [sys.executable, "-c", _E2E_SCRIPT],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["devices"] == 8, out
    for name in ("greedy", "auction", "windows"):
        assert out[name]["parity"], (name, out)
        assert out[name]["n_assigned"] > 0, (name, out)
