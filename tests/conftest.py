"""Test harness: hermetic, CPU-only, 8 virtual devices.

Must run before jax initializes its backend: force the CPU platform and a
virtual 8-device topology so sharding tests (`shard_map` over a Mesh)
exercise real multi-device paths without TPU hardware.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# This image's sitecustomize registers a TPU PJRT plugin and pins
# JAX_PLATFORMS=axon before conftest runs, so the env var alone is not
# enough — override via config before any backend is touched.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, "expected 8 virtual CPU devices for sharding tests"

import pytest  # noqa: E402

# ---- fast/slow split (make test-fast vs make test) -----------------------
# The expensive families, marked in ONE place by nodeid substring: the
# 8-device sharding/windows/fused parity sweeps, learned-model training/
# checkpointing, live-sidecar bridge servers, full e2e loops, and the
# brute-force preemption oracles. `pytest -m "not slow"` keeps the
# per-kernel/unit suite under ~2 minutes on this 1-CPU image; `make test`
# still runs everything.
_SLOW_PATTERNS = (
    "sharded",
    "windows",
    "fused",
    "multihost",
    "learned",
    "distill",
    "checkpoint",
    "graft",
    "auction",
    "bruteforce",
    "e2e",
    "sidecar",
    "preempt",
    "sweep",
    "cli_",
    "kube_loop",
    "property",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = item.nodeid.rsplit("::", 1)[-1]
        if any(p in name for p in _SLOW_PATTERNS):
            item.add_marker(pytest.mark.slow)
