"""Fleet-shared device engine (host/engine_pool.SharedEnginePool).

The tentpole claims, pinned end to end through ReplicaFleet:

- PARITY (round 20): a fleet multiplexed onto ONE shared engine binds
  the same pods to the same nodes as the same fleet on private
  engines — coalescing and upload dedupe change WHERE the work runs,
  never what a cycle decides.
- Coalescing: a deterministic round-robin drain through the split-phase
  seam (run_round_split) fuses the whole round's windows into one
  device invocation; device dispatches per drain stay strictly below
  one-per-replica-window.
- Upload dedupe: churn uploads once per FLEET — the base ships full
  once, identical co-dispatched snapshots ride as zero-row dedup
  elements.
- Failure fan-out: a sidecar crash mid-coalesced-batch delivers the
  error to EVERY participant (each replica falls back and re-binds its
  own window — nothing lost, nothing double-bound) and drops the pool
  base, so the next dispatch re-syncs with a fenced FULL upload (the
  `shared-delta-fenced` invariant's load-bearing line).
- Capability state lives in the ONE inner engine: a sidecar capability
  downgrade is probed/relearned once per fleet drain, not once per
  replica.
"""

from kubernetes_scheduler_tpu.host.engine_pool import SharedEnginePool
from kubernetes_scheduler_tpu.host.queue import namespace_partition
from kubernetes_scheduler_tpu.host.replica import ReplicaFleet
from kubernetes_scheduler_tpu.host.types import Container, Pod
from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig


def mk_pod(name, ns, cpu=100.0):
    return Pod(
        name=name,
        namespace=ns,
        containers=[Container(requests={"cpu": cpu, "memory": 2**28})],
    )


def _tenant_for(residue, n):
    return next(
        ns for i in range(256)
        if namespace_partition(ns := f"tenant-{i}", n) == residue
    )


def _workload(n_replicas, pods_per, tag="w"):
    # one tenant per partition residue: every replica is guaranteed
    # traffic, so every fleet round has N windows to coalesce
    ns_names = [_tenant_for(r, n_replicas) for r in range(n_replicas)]
    return [
        mk_pod(f"{tag}{t}-{j}", ns_names[t])
        for t in range(n_replicas)
        for j in range(pods_per)
    ]


def _mk_fleet(n_replicas, nodes, advisor, running, *, shared,
              engine_factory=None, **overrides):
    cfg = dict(
        batch_window=8, normalizer="none", adaptive_dispatch=False,
        min_device_work=0, pipeline_depth=1,
        # single-window cycles: the multi-window backlog scan carries
        # state across its own windows and dispatches alone, so only
        # single-window rounds exercise cross-replica coalescing
        max_windows_per_cycle=1,
    )
    cfg.update(overrides)
    return ReplicaFleet(
        SchedulerConfig(shared_engine=shared, **cfg),
        n_replicas=n_replicas,
        advisor_factory=lambda i: advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        engine_factory=engine_factory,
    )


def _drain_rounds(fleet, *, max_rounds=64):
    """Deterministic split-phase fleet drain: every replica dispatches
    before any completes, so a shared pool coalesces each round."""
    rounds = 0
    while any(
        len(s.queue) > 0 or s._prefetched is not None
        for s in fleet.schedulers
    ):
        assert rounds < max_rounds, "fleet failed to drain"
        fleet.run_round_split()
        rounds += 1
    for s in fleet.schedulers:
        s.drain_pipeline()
    return rounds


# ---- parity: shared == private, bit for bit -------------------------------


def test_shared_engine_union_binding_parity_with_private():
    """PARITY round 20: the same 2-replica workload drained on a shared
    engine and on private engines produces the SAME pod->node map (not
    just the same bound set). The threaded drain is the real topology —
    coalescing happens on whatever timing the threads produce, and must
    be invisible in the decisions."""
    nodes, advisor = gen_host_cluster(16, seed=0)

    def drain(shared):
        running: list = []
        fleet = _mk_fleet(2, nodes, advisor, running, shared=shared)
        for pod in _workload(2, 12):
            fleet.submit(pod)
        ev = fleet.run_until_empty(max_cycles=100)
        bound = {
            (b.pod.namespace, b.pod.name): b.node_name
            for s in fleet.schedulers
            for b in s.binder.bindings
        }
        return ev, bound

    ev_s, bound_s = drain(True)
    ev_p, bound_p = drain(False)
    assert ev_s["double_binds"] == 0 == ev_p["double_binds"]
    assert ev_s["total_binds"] == ev_p["total_binds"] == 24
    assert bound_s == bound_p
    st = ev_s["shared_engine"]
    assert st["device_dispatches"] >= 1
    # upload dedupe across the fleet: ONE full base sync, every other
    # dispatch of the unchanged cluster rides as a zero-row dedup (this
    # workload never mutates nodes/running between cycles)
    assert st["uploads"]["full"] == 1
    assert st["uploads"]["dedup"] >= 1
    assert st["upload_bytes"]["full"] > 0
    assert st["upload_bytes"]["dedup"] == 0
    assert "shared_engine" not in ev_p


# ---- coalescing: one device invocation per fleet round --------------------


def test_round_split_coalesces_fleet_windows():
    """4 replicas x 2 windows each through the deterministic round
    drain: each round's 4 windows fuse into ONE device invocation, so
    the drain's device dispatches stay strictly below the 8 a private
    fleet would pay."""
    nodes, advisor = gen_host_cluster(24, seed=0)
    running: list = []
    fleet = _mk_fleet(4, nodes, advisor, running, shared=True)
    for pod in _workload(4, 16):  # batch_window=8 -> 2 windows/replica
        fleet.submit(pod)
    _drain_rounds(fleet)
    ev = fleet.evidence()
    assert ev["double_binds"] == 0
    assert ev["pods_discarded"] == 0
    assert ev["total_binds"] == 64
    st = ev["shared_engine"]
    assert st["coalesced_dispatches"] >= 1
    assert st["device_dispatches"] < 4 * 2  # fused below one-per-window
    # the fused epochs advanced monotonically with the dispatches
    assert st["epoch"] == st["device_dispatches"]
    assert st["uploads"]["full"] == 1  # one base sync for the whole fleet
    # exporter wiring: the pool's collectors ride every replica's
    # /metrics surface (view.collectors -> scheduler.prom_collectors),
    # so the ONE shared pool is visible from all N exporters
    for replica in (0, 3):
        body = "\n".join(
            line
            for collector in fleet.prom_collectors(replica)
            for line in collector.render()
        )
        assert "yoda_tpu_coalesced_dispatches_total" in body
        assert "yoda_tpu_coalesce_batch_window_count_bucket" in body
        assert 'yoda_tpu_shared_engine_uploads_total{upload="full"}' in body


# ---- failure fan-out: crash mid-coalesced-batch ---------------------------


class _CrashOnSecondFleetCall:
    """LocalEngine wrapper: the SECOND fused fleet dispatch dies after
    the round coalesced (sidecar crash mid-batch); every other call
    serves normally. Crashing on the second call — after a successful
    round established the pool's resident base — makes the post-crash
    FULL re-sync observable in the upload accounting."""

    def __init__(self):
        from kubernetes_scheduler_tpu.engine import LocalEngine

        self._inner = LocalEngine()
        self.fleet_calls = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def schedule_batch_fleet(self, *args, **kw):
        self.fleet_calls += 1
        if self.fleet_calls == 2:
            raise RuntimeError("sidecar crashed mid-coalesced-batch")
        return self._inner.schedule_batch_fleet(*args, **kw)


def test_sidecar_crash_mid_coalesced_batch_loses_nothing():
    """A crash inside a coalesced super-batch fans the failure out to
    EVERY participant: each replica's completion falls back to its own
    scalar re-schedule of its own window, so no pod is lost and nothing
    double-binds; the pool drops its base and the next dispatch re-syncs
    with a fenced full upload."""
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    inner = _CrashOnSecondFleetCall()
    fleet = _mk_fleet(
        2, nodes, advisor, running, shared=True,
        engine_factory=lambda i: inner,
    )
    for pod in _workload(2, 24):  # 3 windows per replica -> >= 3 rounds
        fleet.submit(pod)
    _drain_rounds(fleet)
    ev = fleet.evidence()
    assert inner.fleet_calls >= 3  # crashed once, then served fused again
    assert ev["total_binds"] == 48  # every pod bound exactly once
    assert ev["double_binds"] == 0
    assert ev["pods_discarded"] == 0
    # BOTH participants of the crashed super-batch fell back (the pool
    # fans the inner failure to every request it coalesced)
    assert sum(s.totals["fallback_cycles"] for s in fleet.schedulers) >= 2
    st = ev["shared_engine"]
    # round 1 synced full; the crash dropped the base (no accounting for
    # the dead dispatch); the first post-crash dispatch re-synced FULL
    # instead of shipping a delta against state the engine lost
    assert st["uploads"]["full"] >= 2


# ---- capability state: probed once per fleet ------------------------------


class _ProbedInner:
    """LocalEngine wrapper counting capability probes; flipping
    `resident` simulates a sidecar capability downgrade."""

    def __init__(self):
        from kubernetes_scheduler_tpu.engine import LocalEngine

        self._inner = LocalEngine()
        self.probes = 0
        self.resident = True
        self.batch_calls = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def supports_resident(self):
        self.probes += 1
        return self.resident

    def schedule_batch(self, *args, **kw):
        self.batch_calls += 1
        return self._inner.schedule_batch(*args, **kw)


def test_capability_downgrade_relearned_once_per_fleet():
    """Capability state lives in the ONE inner engine: a 4-replica round
    costs one capability probe, and after a downgrade the pool relearns
    it once for the whole fleet — never once per replica."""
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    inner = _ProbedInner()
    fleet = _mk_fleet(
        4, nodes, advisor, running, shared=True,
        engine_factory=lambda i: inner,
    )
    for pod in _workload(4, 8):  # exactly one window per replica
        fleet.submit(pod)
    fleet.run_round_split()
    assert inner.probes == 1  # 4 windows, ONE probe
    pool = fleet.engine_pool
    st = pool.stats()
    assert st["device_dispatches"] == 1
    assert st["coalesced_dispatches"] == 1
    # the fused round: one full base, three identical co-snapshots dedup
    assert st["uploads"] == {"full": 1, "delta": 0, "dedup": 3}

    inner.resident = False  # the sidecar downgraded mid-run
    pool.invalidate()
    for pod in _workload(4, 8, tag="x"):
        fleet.submit(pod)
    fleet.run_round_split()
    ev = fleet.evidence()
    assert ev["total_binds"] == 64
    assert ev["double_binds"] == 0
    # the downgrade was relearned by ONE probe for the whole fleet; the
    # degraded round forwarded each window plainly through the inner
    assert inner.probes == 2
    assert inner.batch_calls == 4


# ---- the fleet applier's fixed-shape scatter ------------------------------


def test_chunked_delta_apply_bitwise_matches_unchunked():
    """The fleet path scatters per-element deltas in fixed-shape chunks
    (one compiled scatter per leaf family instead of one per
    power-of-two bucket — a growing cluster otherwise recompiles every
    coalesced dispatch). Chunking must be invisible in the data: every
    leaf bitwise-equal to the unchunked apply, at chunk sizes that
    divide, straddle, and exceed the row count."""
    import numpy as np

    from kubernetes_scheduler_tpu.engine import (
        _apply_delta_rows,
        _apply_delta_rows_chunked,
    )
    from kubernetes_scheduler_tpu.host.snapshot import (
        SnapshotBuilder,
        snapshot_delta,
    )
    from kubernetes_scheduler_tpu.sim.host_gen import (
        gen_host_cluster,
        gen_host_pods,
    )

    nodes, advisor = gen_host_cluster(64, seed=0)
    util = advisor.fetch()
    pods = gen_host_pods(48, seed=3)
    names = [n.name for n in nodes]
    for j, p in enumerate(pods):
        p.node_name = names[(j * 7) % len(names)]
    base = SnapshotBuilder().build_snapshot(nodes, util, [], ephemeral=True)
    new = SnapshotBuilder().build_snapshot(
        nodes, util, pods, ephemeral=True
    )
    delta = snapshot_delta(base, new)
    assert delta is not None and len(delta.req_rows) > 0
    # device leaves, as the engine's _consts.swap hands the appliers
    import jax
    import jax.numpy as jnp

    base = jax.tree_util.tree_map(jnp.asarray, base)
    want = _apply_delta_rows(base, delta)
    for chunk in (1, 8, 13, 128):
        got = _apply_delta_rows_chunked(base, delta, chunk=chunk)
        for field in want._fields:
            assert np.array_equal(
                np.asarray(getattr(want, field)),
                np.asarray(getattr(got, field)),
            ), (chunk, field)


# ---- the view surface -----------------------------------------------------


def test_view_never_claims_resident_and_invalidate_drops_base():
    """Replica views deliberately advertise supports_resident()=False —
    residency is the POOL's job (per-replica resident sessions on one
    sidecar would fight over the base); invalidate through any view
    drops the fleet base so the next dispatch re-syncs full."""
    pool = SharedEnginePool(_ProbedInner(), coalesce_window_ms=0.0)
    v0, v1 = pool.view("r0"), pool.view("r1")
    assert v0.supports_resident() is False
    assert v0.supports_windows_resident() is False
    assert v0.healthy()
    pool._prev = {"sentinel": object()}
    v1.invalidate_resident()
    assert pool._prev is None
    v0.close()
    assert not pool._closed  # refcounted: v1 still open
    v1.close()
    assert pool._closed
