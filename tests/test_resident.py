"""Device-resident cluster state (config.resident_state): delta/full
parity and the flush paths.

The guarantee under test (PARITY.md): for the same arrival order,
resident-delta mode produces BIT-IDENTICAL bindings to full-upload mode
— the SnapshotDelta machinery is a pure transfer optimization. Deltas
ship changed rows BY VALUE, so an applied delta reproduces the full host
build bitwise; these tests pin that across metric churn, node add/
remove, preemption, engine-failure fallback, and the live sidecar
(including sidecar restart and the mid-stream capability downgrade that
must invalidate the wire field cache and the resident epoch together)."""

import numpy as np
import pytest

from kubernetes_scheduler_tpu.engine import (
    LocalEngine,
    PendingSchedule,
    apply_snapshot_delta,
    apply_snapshot_delta_np,
)
from kubernetes_scheduler_tpu.host import NodeUtil, Scheduler, StaticAdvisor
from kubernetes_scheduler_tpu.host.scheduler import RecordingEvictor
from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder, snapshot_delta
from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster, gen_host_pods
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig
from tests.test_pipeline import drain, make_cfg, make_node, make_pod


def make_sched(nodes, advisor, running, *, resident, engine=None, **kw):
    kw.setdefault("pipeline_depth", 1)
    return Scheduler(
        make_cfg(resident_state=resident, **kw),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        engine=engine,
    )


def run_workload(
    resident, *, constraints=False, n_nodes=48, n_pods=130, engine=None,
    mutate=None, depth=1, **cfg_kw,
):
    """Drain a backlog cycle by cycle; `mutate(cycle_no, nodes, advisor,
    sched)` injects deterministic churn at the same points in every run so
    resident and plain runs stay comparable (node churn plays through the
    mirror too — informer events own cluster state once it seeds)."""
    nodes, advisor = gen_host_cluster(n_nodes, seed=0, constraints=constraints)
    running: list = []
    sched = make_sched(
        nodes, advisor, running, resident=resident, engine=engine,
        pipeline_depth=depth, **cfg_kw,
    )
    for pod in gen_host_pods(n_pods, seed=1, constraints=constraints):
        sched.submit(pod)
    seen = 0
    cycle = 0
    metrics = []
    for _ in range(64):
        if len(sched.queue) == 0 and sched._prefetched is None:
            break
        metrics.append(sched.run_cycle())
        for b in sched.binder.bindings[seen:]:
            running.append(b.pod)
        seen = len(sched.binder.bindings)
        cycle += 1
        if mutate is not None:
            mutate(cycle, nodes, advisor, sched)
    binds = [(b.pod.namespace, b.pod.name, b.node_name)
             for b in sched.binder.bindings]
    return binds, metrics, sched


def test_resident_parity_bitidentical_plain():
    b0, _, _ = run_workload(False)
    b1, m1, s1 = run_workload(True)
    assert b1 == b0 and len(b0) > 0
    # the delta path actually engaged: one full upload establishes the
    # resident state, every later device cycle ships a delta
    assert s1.totals["full_uploads"] == 1
    assert s1.totals["delta_uploads"] >= 1
    assert s1.totals["delta_bytes_saved"] > 0
    assert s1.totals["fallback_cycles"] == 0


def test_resident_parity_serial_mode():
    """resident_state composes with pipeline_depth=0 too (the serial
    driver shares _dispatch_window)."""
    b0, _, _ = run_workload(False, depth=0)
    b1, _, s1 = run_workload(True, depth=0)
    assert b1 == b0 and len(b0) > 0
    assert s1.totals["delta_uploads"] >= 1


def test_resident_parity_constraint_churn():
    """Constraint workloads: binds move whole-domain rows of the [n, S]
    count tables — those ride the delta as row sets (domain_id drift
    would force a full), so the delta path engages here too."""
    b0, _, _ = run_workload(False, constraints=True)
    b1, _, s1 = run_workload(True, constraints=True)
    assert b1 == b0 and len(b0) > 0
    assert s1.totals["fallback_cycles"] == 0
    assert s1.totals["delta_uploads"] >= 1


def test_resident_parity_metric_churn():
    """Advisor series changing every cycle: changed rows ride the delta
    by value, bindings stay bit-identical, and the delta path keeps
    engaging (metric churn alone must not force full uploads)."""

    def churn(cycle, nodes, advisor, sched):
        rng = np.random.default_rng(1000 + cycle)
        for nd in nodes[:: 3]:
            advisor.utils[nd.name] = NodeUtil(
                cpu_pct=float(rng.uniform(0, 100)),
                disk_io=float(rng.uniform(0, 50)),
                mem_pct=float(rng.uniform(0, 100)),
            )

    b0, _, _ = run_workload(False, mutate=churn)
    b1, _, s1 = run_workload(True, mutate=churn)
    assert b1 == b0 and len(b0) > 0
    assert s1.totals["delta_uploads"] >= 1
    assert s1.totals["full_uploads"] == 1
    assert s1.totals["fallback_cycles"] == 0


def test_resident_parity_node_add_remove():
    """Node add (and remove) mid-drain: layout churn flushes to a full
    upload — never a stale delta — and bindings match full-upload mode
    with the same events."""

    def events(cycle, nodes, advisor, sched):
        if cycle == 1:
            late = make_node("n-late")
            nodes.append(late)
            advisor.utils["n-late"] = NodeUtil(cpu_pct=5.0)
            if sched.mirror is not None:
                sched.mirror.apply_node_event("ADDED", late)
        if cycle == 2:
            gone = nodes.pop(0)
            advisor.utils.pop(gone.name, None)
            if sched.mirror is not None:
                sched.mirror.apply_node_event("DELETED", gone)

    b0, _, _ = run_workload(False, mutate=events)
    b1, _, s1 = run_workload(True, mutate=events)
    assert b1 == b0 and len(b0) > 0
    # the node events forced fresh full uploads (bucket/static churn)
    assert s1.totals["full_uploads"] >= 2
    assert s1.totals["fallback_cycles"] == 0


class ResidentMidflightFailEngine(LocalEngine):
    """LocalEngine whose in-flight resident handle dies on force for one
    call — the remote-outage shape against the resident surface."""

    def __init__(self, fail_call: int):
        super().__init__()
        self.calls = 0
        self.fail_call = fail_call

    def schedule_resident_async(self, snapshot, pods, **kw):
        self.calls += 1
        if self.calls == self.fail_call:
            class _Dead:
                def result(self):
                    raise RuntimeError("injected mid-flight engine failure")

            return _Dead()
        return PendingSchedule(self.schedule_resident(snapshot, pods, **kw))


def test_resident_engine_failure_flushes_to_full():
    """An engine failure mid-flight falls back to scalar exactly once,
    invalidates the resident contract, and the NEXT device cycle is a
    full upload — with bindings still matching the no-resident run."""
    engine = ResidentMidflightFailEngine(fail_call=2)
    b1, m1, s1 = run_workload(True, engine=engine)
    fallbacks = [m for m in m1 if m.used_fallback]
    assert len(fallbacks) == 1
    # first cycle full, failed cycle flushed, recovery cycle full again
    assert s1.totals["full_uploads"] >= 2
    names = [b[1] for b in b1]
    assert len(names) == len(set(names))
    b0, _, _ = run_workload(False, engine=ResidentMidflightFailEngine(2))
    assert len(b1) == len(b0)
    later = m1[m1.index(fallbacks[0]) + 1:]
    assert later and not any(m.used_fallback for m in later)


def run_preemption(resident):
    nodes = [make_node("n0", cpu=2000.0), make_node("n1", cpu=2000.0)]
    advisor = StaticAdvisor({n.name: NodeUtil(cpu_pct=10.0) for n in nodes})
    running = []
    for i, node in enumerate(nodes):
        victim = make_pod(f"victim-{i}", cpu=1800.0, priority=0)
        victim.node_name = node.name
        victim.start_time = 100.0 + i
        running.append(victim)
    evictor = RecordingEvictor()
    sched = Scheduler(
        make_cfg(pipeline_depth=1, batch_window=4, resident_state=resident),
        advisor=advisor,
        evictor=evictor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )
    sched.submit(make_pod("preemptor", cpu=1800.0, priority=100))
    sched.submit(make_pod("small", cpu=100.0, priority=0))
    drain(sched, running)
    return (
        [(e.victim.name, e.preemptor.name) for e in evictor.evictions],
        sched,
    )


def test_resident_preemption_parity_and_flush():
    """Preemption selects the same victims under resident mode, and an
    eviction flushes the resident contract (the next resident dispatch
    re-uploads in full rather than trusting a pre-kill delta base)."""
    ev0, _ = run_preemption(False)
    ev1, sched = run_preemption(True)
    assert ev1 == ev0 and len(ev0) >= 1
    assert sched._resident_ok is False  # flushed after the evictions


def test_resident_backlog_windows_parity():
    """The multi-window backlog path (schedule_windows) ships deltas
    too (ROADMAP follow-up): bindings bit-identical to the no-resident
    run, with the delta path engaging after the first full upload."""
    b0, _, _ = run_workload(False, n_pods=160, max_windows_per_cycle=4)
    b1, m1, s1 = run_workload(True, n_pods=160, max_windows_per_cycle=4)
    assert b1 == b0 and len(b0) > 0
    assert s1.totals["delta_uploads"] >= 1
    assert s1.totals["full_uploads"] >= 1
    assert s1.totals["fallback_cycles"] == 0


def test_resident_backlog_flushes_on_node_churn():
    """Cross-window layout churn (node add) mid-drain flushes the
    backlog path to a full upload — never a stale delta — and bindings
    still match the no-resident run with the same events."""

    def events(cycle, nodes, advisor, sched):
        if cycle == 1:
            late = make_node("n-late")
            nodes.append(late)
            advisor.utils["n-late"] = NodeUtil(cpu_pct=5.0)
            if sched.mirror is not None:
                sched.mirror.apply_node_event("ADDED", late)

    b0, _, _ = run_workload(
        False, n_pods=160, max_windows_per_cycle=4, mutate=events
    )
    b1, _, s1 = run_workload(
        True, n_pods=160, max_windows_per_cycle=4, mutate=events
    )
    assert b1 == b0 and len(b0) > 0
    assert s1.totals["full_uploads"] >= 2
    assert s1.totals["fallback_cycles"] == 0


def test_domain_count_incremental_bitwise_and_identity():
    """The incremental domain-count build (ROADMAP follow-up: skip the
    rebuild of provably-unchanged sections): appended running pods fold
    into cached raw tables with outputs BITWISE equal to a fresh
    builder's full scan — and when nothing changed, the SAME arrays
    come back (identity), so snapshot_delta skips diffing them."""
    from kubernetes_scheduler_tpu.host.types import PodAffinityTerm

    def mk_nodes():
        nodes = []
        for i in range(12):
            nd = make_node(f"n{i}")
            nd.labels = {"topology.kubernetes.io/zone": f"z{i % 3}"}
            nodes.append(nd)
        return nodes

    def mk_pod(name, node=None, anti=False):
        pod = make_pod(name, cpu=100.0)
        pod.labels = {"app": "svc"}
        pod.pod_affinity = [
            PodAffinityTerm(
                match_labels={"app": "svc"},
                topology_key="topology.kubernetes.io/zone",
                anti=anti,
            )
        ]
        pod.node_name = node
        return pod

    nodes = mk_nodes()
    utils = {nd.name: NodeUtil(cpu_pct=10.0) for nd in nodes}
    running = [mk_pod(f"r{i}", node=f"n{i % 12}", anti=(i % 2 == 0))
               for i in range(6)]
    window = [mk_pod("w0"), mk_pod("w1", anti=True)]
    inc = SnapshotBuilder()
    s1 = inc.build_snapshot(nodes, utils, running, pending_pods=window)
    # appended suffix (the live informer's shape)
    running.append(mk_pod("r-new", node="n3"))
    s2 = inc.build_snapshot(nodes, utils, running, pending_pods=window)
    fresh = SnapshotBuilder()
    f2 = fresh.build_snapshot(nodes, utils, running, pending_pods=window)
    for name in ("domain_counts", "avoid_counts", "pref_attract",
                 "pref_avoid", "domain_id"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s2, name)), np.asarray(getattr(f2, name)),
            err_msg=name,
        )
    # no change since the last build -> identical OBJECTS (the
    # snapshot_delta identity fast path)
    s3 = inc.build_snapshot(nodes, utils, running, pending_pods=window)
    assert s3.domain_counts is s2.domain_counts
    assert s3.avoid_counts is s2.avoid_counts
    # an ephemeral build must not poison the cache
    s_eph = inc.build_snapshot(
        nodes, utils, running + [mk_pod("tmp", node="n1")], ephemeral=True,
        pending_pods=window,
    )
    assert np.asarray(s_eph.domain_counts).sum() > np.asarray(
        s3.domain_counts
    ).sum()
    s4 = inc.build_snapshot(nodes, utils, running, pending_pods=window)
    np.testing.assert_array_equal(
        np.asarray(s4.domain_counts), np.asarray(s3.domain_counts)
    )


def test_resident_backlog_over_sidecar_parity():
    """Satellite over the wire: backlog cycles ship deltas through the
    ScheduleWindows RPC when the sidecar advertises the
    windows_resident capability bit; bindings match the local
    no-resident run and the server's counters confirm deltas served."""

    def body(client, service):
        assert client.supports_windows_resident() is True
        return (
            run_workload(
                True, n_pods=160, engine=client, max_windows_per_cycle=4,
            ),
            service,
        )

    (b_remote, m_remote, s_remote), service = _with_sidecar(body)
    b_local, _, _ = run_workload(False, n_pods=160, max_windows_per_cycle=4)
    assert b_remote == b_local and len(b_local) > 0
    assert not any(m.used_fallback for m in m_remote)
    assert s_remote.totals["delta_uploads"] >= 1
    assert service.resident_deltas_served >= 1


def test_resident_backlog_sidecar_capability_downgrade():
    """A sidecar without the windows_resident bit (older build) serves
    backlog cycles as plain full ScheduleWindows — no deltas on that
    RPC, no errors, bindings unchanged."""

    def body(client, service):
        service.windows_resident_enabled = False
        assert client.supports_windows_resident() is False
        return run_workload(
            True, n_pods=160, engine=client, max_windows_per_cycle=4,
        )

    b_remote, m_remote, s_remote = _with_sidecar(body)
    b_local, _, _ = run_workload(False, n_pods=160, max_windows_per_cycle=4)
    assert b_remote == b_local and len(b_local) > 0
    assert not any(m.used_fallback for m in m_remote)
    # backlog cycles stayed full-upload (the single-window path may
    # still delta through ScheduleBatch, which remains advertised)
    assert s_remote.totals["delta_uploads"] == 0


def test_snapshot_delta_reproduces_full_build_bitwise():
    """The delta IS the full build, row-compressed: applying it (numpy
    and device paths) to the previous snapshot reproduces the next full
    build bitwise on every leaf."""
    nodes = [make_node(f"n{i}") for i in range(24)]
    utils = {n.name: NodeUtil(cpu_pct=10.0, disk_io=5.0) for n in nodes}
    running = []
    b = SnapshotBuilder()
    window = [make_pod("w0", cpu=300.0), make_pod("w1", cpu=400.0)]
    prev = b.build_snapshot(nodes, utils, running, pending_pods=window)
    for i, pod in enumerate(window):
        pod.node_name = f"n{i}"
        running.append(pod)
    utils["n2"] = NodeUtil(cpu_pct=77.0, net_up=3.0)
    new = b.build_snapshot(nodes, utils, running)
    delta = snapshot_delta(prev, new)
    assert delta is not None
    applied = apply_snapshot_delta_np(prev, delta)
    for name, a, c in zip(new._fields, applied, new):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c), err_msg=name)
    import jax

    dev = apply_snapshot_delta(jax.device_put(prev), delta)
    for name, a, c in zip(new._fields, dev, new):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c), err_msg=name)


def test_snapshot_delta_refuses_static_and_layout_churn():
    nodes = [make_node(f"n{i}") for i in range(3)]
    utils = {n.name: NodeUtil(cpu_pct=10.0) for n in nodes}
    b = SnapshotBuilder()
    prev = b.build_snapshot(nodes, utils, [])
    # node-set churn: static block rebuilt -> no delta
    nodes2 = nodes + [make_node("n3")]
    new = b.build_snapshot(nodes2, utils, [])
    if prev.requested.shape == new.requested.shape:
        assert snapshot_delta(prev, new) is None
    # shape churn (bucket growth) -> no delta
    nodes3 = [make_node(f"m{i}") for i in range(20)]
    utils3 = {n.name: NodeUtil() for n in nodes3}
    new3 = SnapshotBuilder().build_snapshot(nodes3, utils3, [])
    assert snapshot_delta(prev, new3) is None


def test_resident_default_off_never_engages():
    """The default-off path is bit-identical PR-2 behavior: no resident
    counters move and the engine never sees the resident surface."""
    b0, _, s0 = run_workload(False)
    assert s0.totals["delta_uploads"] == 0
    assert s0.totals["full_uploads"] == 0
    assert s0.totals["delta_bytes_saved"] == 0


def test_resident_counters_exported():
    from kubernetes_scheduler_tpu.host.observe import render_prometheus

    _, _, sched = run_workload(True, n_pods=40)
    window, totals = sched.metrics_snapshot()
    assert totals["delta_uploads"] > 0
    assert totals["full_uploads"] > 0
    text = render_prometheus(window, totals)
    assert "yoda_tpu_delta_uploads_total" in text
    assert "yoda_tpu_full_uploads_total" in text
    assert "yoda_tpu_delta_bytes_saved_total" in text
    # pre-totals callers (older exporters) still render
    text2 = render_prometheus(window, None)
    assert "yoda_tpu_delta_uploads_total" in text2


# ---- live sidecar variants ------------------------------------------------


def _with_sidecar(fn):
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server

    server, port, service = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    try:
        return fn(client, service)
    finally:
        client.close()
        server.stop(grace=None)


def test_resident_over_sidecar_parity():
    """The bridge path: deltas ride the wire, the sidecar applies them to
    its session-resident state, bindings match the local full-upload
    run, and the server's own counters confirm deltas were served."""

    def body(client, service):
        return run_workload(True, n_pods=96, engine=client), service

    (b_remote, m_remote, s_remote), service = _with_sidecar(body)
    b_local, _, _ = run_workload(False, n_pods=96)
    assert b_remote == b_local
    assert not any(m.used_fallback for m in m_remote)
    assert s_remote.totals["delta_uploads"] >= 1
    assert service.resident_deltas_served >= 1
    assert service.resident_fulls_served >= 1


def test_resident_sidecar_restart_transparent_full_resend():
    """Sidecar restart (session state gone) mid-stream: the delta's
    INVALID_ARGUMENT resident-epoch-mismatch triggers a transparent full
    resend inside the client — the cycle never falls back to scalar."""

    def body(client, service):
        nodes, advisor = gen_host_cluster(32, seed=0)
        running: list = []
        sched = make_sched(nodes, advisor, running, resident=True, engine=client)
        for pod in gen_host_pods(96, seed=1):
            sched.submit(pod)
        metrics = drain(sched, running)
        assert sched.totals["delta_uploads"] >= 1
        # "restart": evict every session (resident state + field caches)
        service._field_cache.clear()
        for pod in gen_host_pods(48, seed=2):
            sched.submit(pod)
        metrics += drain(sched, running)
        return sched, metrics

    sched, metrics = _with_sidecar(body)
    assert not any(m.used_fallback for m in metrics)
    # post-restart cycles recovered: at least one full resend, then deltas
    assert sched.totals["full_uploads"] >= 2
    assert sum(m.pods_bound for m in metrics) == 96 + 48


def test_resident_capability_downgrade_invalidates_together():
    """The satellite bugfix: a mid-stream capability downgrade (sidecar
    replaced by a build without field_cache/resident_state) must
    invalidate the wire field cache AND the resident capability latch
    together — the client re-probes both and degrades to plain full
    sends instead of looping on rejected deltas."""

    def body(client, service):
        nodes, advisor = gen_host_cluster(32, seed=0)
        running: list = []
        sched = make_sched(nodes, advisor, running, resident=True, engine=client)
        for pod in gen_host_pods(64, seed=1):
            sched.submit(pod)
        metrics = drain(sched, running)
        assert sched.totals["delta_uploads"] >= 1
        assert client._field_cache_ok is True and client._resident_cap is True
        # the downgrade: the same target now serves neither capability
        service.field_cache_enabled = False
        service.resident_enabled = False
        for pod in gen_host_pods(64, seed=2):
            sched.submit(pod)
        metrics += drain(sched, running)
        return sched, metrics, client

    sched, metrics, client = _with_sidecar(body)
    # both latches re-probed to the downgraded answers — never one stale
    assert client._field_cache_ok is False
    assert client._resident_cap is False
    # at most one cycle paid a fallback while the latches re-learned;
    # everything recovered and every pod bound
    assert sum(1 for m in metrics if m.used_fallback) <= 1
    assert sum(m.pods_bound for m in metrics) == 128
    assert not metrics[-1].used_fallback


# every HealthReply capability bit, read off the proto itself — a bit
# added to the schema joins this parametrization (and so gets the
# mid-stream-downgrade pin) for free, before anyone remembers to write
# a bespoke test for it
def _capability_bits():
    import os

    from kubernetes_scheduler_tpu.analysis.rules.capability_completeness import (
        health_bool_fields,
    )

    proto = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "kubernetes_scheduler_tpu", "bridge", "schedule.proto",
    )
    return sorted(health_bool_fields(proto))


@pytest.mark.parametrize("fieldname", _capability_bits())
def test_mid_stream_downgrade_relearns_every_bit(fieldname):
    """The PR-3 bug class, pinned generically for EVERY capability bit
    (test_resident/test_gang used to pin it ad hoc per bit): one probe
    resolves the whole latch set; a mid-stream downgrade (the sidecar
    behind the target now advertises the opposite) funnels through
    `_invalidate_session`, which must drop every latch WITH the wire
    field cache; the next cycles re-learn the new advertisement and
    keep binding. The protocol itself (all interleavings) is
    model-checked in analysis/model/protocols.py `client-session`; the
    per-RPC except-path wiring is the capability-completeness lint
    family. This is the live-sidecar spot check of both."""

    def body(client, service):
        from kubernetes_scheduler_tpu.bridge.client import (
            CAPABILITY_LATCHES,
        )
        from kubernetes_scheduler_tpu.bridge.server import (
            CAPABILITY_SWITCHES,
        )

        attr = CAPABILITY_LATCHES[fieldname]
        switch = CAPABILITY_SWITCHES[fieldname]
        nodes, advisor = gen_host_cluster(24, seed=0)
        running: list = []
        sched = make_sched(
            nodes, advisor, running, resident=True, engine=client,
        )
        for pod in gen_host_pods(32, seed=1):
            sched.submit(pod)
        metrics = drain(sched, running)
        before = bool(getattr(service, switch))
        # one probe resolved the WHOLE set, this bit to the server's
        # advertisement — a partially-unknown latch set is the bug
        assert getattr(client, attr) is before
        assert all(
            getattr(client, a) is not None
            for a in CAPABILITY_LATCHES.values()
        )
        # the downgrade/upgrade: same target, opposite advertisement;
        # every RPC failure path reaches _invalidate_session (pinned
        # per-surface by capability-completeness), which drops every
        # latch and the wire field cache together
        setattr(service, switch, not before)
        client._invalidate_session()
        assert all(
            getattr(client, a) is None
            for a in CAPABILITY_LATCHES.values()
        )
        assert len(client._wire_cache) == 0
        for pod in gen_host_pods(32, seed=2):
            sched.submit(pod)
        metrics += drain(sched, running)
        # the flipped advertisement is re-learned — the bit and the set
        assert getattr(client, attr) is (not before)
        assert all(
            getattr(client, a) is not None
            for a in CAPABILITY_LATCHES.values()
        )
        return metrics

    metrics = _with_sidecar(body)
    assert sum(m.pods_bound for m in metrics) == 64
    assert sum(1 for m in metrics if m.used_fallback) <= 1
    assert not metrics[-1].used_fallback
