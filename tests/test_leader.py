"""Leader election: the active/passive failover contract
(deploy/yoda-scheduler.yaml:10-17 semantics on a pluggable lease)."""

import time

from kubernetes_scheduler_tpu.host.leader import FileLease, LeaderElector, LeaseRecord


def test_file_lease_claim_and_cas(tmp_path):
    lease = FileLease(str(tmp_path / "lease"))
    assert lease.read() is None
    rec = LeaseRecord(holder="a", acquired_at=1.0, renewed_at=1.0, duration=15.0)
    assert lease.try_claim(rec, None)
    got = lease.read()
    assert got.holder == "a"
    # stale CAS (previous=None while held) must fail
    rec_b = LeaseRecord(holder="b", acquired_at=2.0, renewed_at=2.0, duration=15.0)
    assert not lease.try_claim(rec_b, None)
    # correct CAS succeeds
    assert lease.try_claim(rec_b, got)
    assert lease.read().holder == "b"
    # clear only by holder
    lease.clear("a")
    assert lease.read() is not None
    lease.clear("b")
    assert lease.read() is None


def test_elector_single_holder(tmp_path):
    path = str(tmp_path / "lease")
    a = LeaderElector(
        FileLease(path), identity="a", lease_duration=5.0, retry_period=0.05
    )
    b = LeaderElector(
        FileLease(path), identity="b", lease_duration=5.0, retry_period=0.05
    )
    assert a.acquire_blocking(timeout=2.0)
    assert a.is_leader()
    # b cannot acquire while a holds
    assert not b.acquire_blocking(timeout=0.3)
    assert not b.is_leader()
    # a releases -> b takes over
    a.release()
    assert b.acquire_blocking(timeout=2.0)
    assert b.is_leader()
    b.release()


def test_elector_steals_expired_lease(tmp_path):
    path = str(tmp_path / "lease")
    lease = FileLease(path)
    # a crashed holder: renewed long ago, short duration
    stale = LeaseRecord(
        holder="dead", acquired_at=time.time() - 60,
        renewed_at=time.time() - 60, duration=1.0,
    )
    assert lease.try_claim(stale, None)
    b = LeaderElector(lease, identity="b", lease_duration=5.0, retry_period=0.05)
    assert b.acquire_blocking(timeout=2.0)
    assert lease.read().holder == "b"
    b.release()


def test_elector_survives_backend_errors_then_recovers(tmp_path):
    """A transient lease-backend error must not kill the renew thread with
    leadership still set (silent split-brain), and leadership must only
    drop after the lease duration elapses without a successful renew."""
    import time

    from kubernetes_scheduler_tpu.host.leader import FileLease, LeaderElector

    class FlakyLease(FileLease):
        fail = False

        def try_claim(self, record, previous):
            if self.fail:
                raise ConnectionError("api server down")
            return super().try_claim(record, previous)

        def read(self):
            if self.fail:
                raise ConnectionError("api server down")
            return super().read()

    lease = FlakyLease(str(tmp_path / "lease"))
    el = LeaderElector(
        lease, identity="a", lease_duration=0.6, retry_period=0.05
    )
    assert el.acquire_blocking(timeout=2)
    # outage shorter than the lease: leadership retained
    lease.fail = True
    time.sleep(0.2)
    assert el.is_leader()
    # outage outlives the lease: leadership dropped, thread stays alive
    time.sleep(0.8)
    assert not el.is_leader()
    # backend recovers (lease expired meanwhile): re-acquired in place
    lease.fail = False
    deadline = time.time() + 3
    while not el.is_leader() and time.time() < deadline:
        time.sleep(0.05)
    assert el.is_leader()
    el.release()
