"""Feasibility-mask kernels vs. scalar predicates."""

import numpy as np
import jax.numpy as jnp

from kubernetes_scheduler_tpu.ops import card_fit, collect_max_card_values, resource_fit
from kubernetes_scheduler_tpu.ops.score import card_score
from tests import oracle

RNG = np.random.default_rng(1)

METRICS = ("bandwidth", "clock", "core", "power", "free_memory", "total_memory")


def random_cards(n_nodes, max_cards=4):
    nodes = []
    for _ in range(n_nodes):
        cards = []
        for _ in range(RNG.integers(0, max_cards + 1)):
            cards.append(
                dict(
                    bandwidth=int(RNG.integers(1, 100)),
                    clock=int(RNG.choice([1000, 1500, 2000])),
                    core=int(RNG.integers(1, 5000)),
                    power=int(RNG.integers(50, 400)),
                    free_memory=int(RNG.integers(0, 32_000)),
                    total_memory=int(RNG.integers(16_000, 48_000)),
                    healthy=bool(RNG.random() > 0.2),
                )
            )
        nodes.append(cards)
    return nodes


def pack_cards(nodes, c_max=4):
    n = len(nodes)
    cards = np.zeros((n, c_max, 6), np.float32)
    mask = np.zeros((n, c_max), bool)
    healthy = np.zeros((n, c_max), bool)
    for i, cs in enumerate(nodes):
        for j, c in enumerate(cs):
            cards[i, j] = [c[m] for m in METRICS]
            mask[i, j] = True
            healthy[i, j] = c["healthy"]
    return jnp.asarray(cards), jnp.asarray(mask), jnp.asarray(healthy)


def test_resource_fit():
    # 3 nodes x 3 resources; pod 0 fits node 0,2; pod 1 fits only node 2;
    # pod 2 requests an extended resource only node 0 exposes.
    alloc = jnp.asarray(
        [[4000, 8e9, 2], [1000, 2e9, 0], [8000, 16e9, 0]], jnp.float32
    )
    req = jnp.asarray([[1000, 1e9, 0], [900, 1e9, 0], [100, 1e9, 0]], jnp.float32)
    pods = jnp.asarray(
        [[1000, 1e9, 0], [7000, 1e9, 0], [100, 1e8, 1]], jnp.float32
    )
    mask = jnp.asarray([True, True, True])
    f = np.asarray(resource_fit(alloc, req, pods, mask))
    assert f.tolist() == [
        [True, False, True],
        [False, False, True],
        [True, False, False],  # node 2 exposes no extended resource
    ]


def test_resource_fit_unrequested_extended_bypass():
    # algorithm.go:211-215: pod requesting 0 of an extended resource is not
    # excluded by it, even when requested > allocatable on that slot.
    alloc = jnp.asarray([[1000, 1e9, 0]], jnp.float32)
    req = jnp.asarray([[0, 0, 5]], jnp.float32)  # oversubscribed extended slot
    pods = jnp.asarray([[500, 1e8, 0]], jnp.float32)
    f = np.asarray(resource_fit(alloc, req, pods, jnp.asarray([True])))
    assert f.tolist() == [[True]]


def test_card_fit_matches_oracle():
    nodes = random_cards(24)
    cards, mask, healthy = pack_cards(nodes)
    # (want_number, want_memory, want_clock); -1 = label absent,
    # 0 = label present with value "0" (or unparsable -> strToUint 0).
    demands = [
        (0, -1, -1),        # non-GPU pod: fits everywhere
        (1, 8000, -1),      # memory demand only
        (2, -1, 1500),      # clock demand only
        (1, 16000, 2000),   # both
        (3, 1, -1),         # tiny explicit memory demand
        (1, 0, -1),         # present "0" memory: needs 1 healthy card
        (1, -1, 0),         # present "0" clock: Clock == 0 never matches
    ]
    want_n = jnp.asarray([d[0] for d in demands], jnp.int32)
    want_m = jnp.asarray([d[1] for d in demands], jnp.float32)
    want_c = jnp.asarray([d[2] for d in demands], jnp.float32)
    fits, _ = card_fit(cards, mask, healthy, want_n, want_m, want_c)
    fits = np.asarray(fits)
    for p, (g, m, c) in enumerate(demands):
        for j, cs in enumerate(nodes):
            assert fits[p, j] == oracle.pod_fits_node_oracle(cs, g, m, c), (p, j)
    # the "clock label present but 0" pod must fit nowhere with cards
    assert not fits[6, [len(cs) > 0 for cs in nodes]].any()


def test_collect_and_card_score_match_oracle():
    nodes = random_cards(16)
    cards, mask, healthy = pack_cards(nodes)
    g, m, c = 1, 4000, 1500
    want_n = jnp.asarray([g], jnp.int32)
    want_m = jnp.asarray([m], jnp.float32)
    want_c = jnp.asarray([c], jnp.float32)
    node_fits, per_card = card_fit(cards, mask, healthy, want_n, want_m, want_c)

    maxima = oracle.collect_max_oracle(nodes, g, m, c)
    # Device-side maxima over fitting cards of fitting nodes:
    fits_for_collect = per_card & node_fits[:, :, None]
    got_max = np.asarray(collect_max_card_values(cards, fits_for_collect))  # [p, 6]
    want_max = [maxima[k] for k in METRICS]
    np.testing.assert_allclose(got_max[0], want_max)

    s = np.asarray(
        card_score(cards, mask, per_card, jnp.asarray(got_max, jnp.float32))
    )[0]
    for j, cs in enumerate(nodes):
        want = oracle.card_score_oracle(cs, maxima, m, c)
        np.testing.assert_allclose(s[j], want, rtol=1e-5, atol=1e-4)


def test_card_score_multi_pod_and_integer_parity():
    """card_score composed directly with collect_max_card_values for several
    pods at once (the [p, 6] maxima contract), in Go uint-arithmetic mode."""
    nodes = random_cards(10)
    cards, mask, healthy = pack_cards(nodes)
    demands = [(1, 4000, -1), (1, -1, 1500), (2, 1000, -1), (0, -1, -1)]
    want_n = jnp.asarray([d[0] for d in demands], jnp.int32)
    want_m = jnp.asarray([d[1] for d in demands], jnp.float32)
    want_c = jnp.asarray([d[2] for d in demands], jnp.float32)
    node_fits, per_card = card_fit(cards, mask, healthy, want_n, want_m, want_c)
    got_max = collect_max_card_values(cards, per_card & node_fits[:, :, None])
    s = np.asarray(
        card_score(cards, mask, per_card, got_max, integer_parity=True)
    )
    for p, (g, m, c) in enumerate(demands):
        maxima = oracle.collect_max_oracle(nodes, g, m, c)
        for j, cs in enumerate(nodes):
            want = oracle.card_score_oracle(cs, maxima, m, c, integer_parity=True)
            np.testing.assert_allclose(s[p, j], want, rtol=1e-5, atol=1e-4), (p, j)
