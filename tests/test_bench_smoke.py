"""Bench-path smoke: bench.py end-to-end at toy sizes (slow-marked).

The benchmark is the repo's round-over-round evidence artifact; nothing
else imports it, so a refactor can silently rot it between rounds. This
drives the FULL default flow — engine headline, deployed-default and
weighted-multi-scorer measurements, the host loop including the
pipelined variant — as one subprocess with tiny BENCH_* knobs (the
`make bench-smoke` invocation), and asserts every expected metric line
comes back as parseable JSON."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE_ENV = {
    "JAX_PLATFORMS": "cpu",
    # the sharded rows need a real mesh: the multichip dryrun topology
    # (conftest sets the same flag for in-process tests; the subprocess
    # gets it explicitly so `make bench-smoke` parity holds)
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "BENCH_NODES": "64",
    "BENCH_PODS": "128",
    "BENCH_WINDOW": "32",
    "BENCH_REPS": "2",
    "BENCH_BASELINE_PODS": "8",
    "BENCH_LOOP_NODES": "32",
    "BENCH_LOOP_PODS": "64",
    # smoke keeps the old 3-sample drains: the >=10-cycle sampling the
    # real bench uses for stable p50/p99 would multiply this test's
    # wall time for percentiles nobody reads at toy sizes
    "BENCH_LOOP_SAMPLES": "3",
    # compressed mesh-sharded rows (host_loop_256nodes + its tenth-
    # scale flat-bytes reference, scheduling_throughput_256nodes)
    "BENCH_SHARDED_NODES": "256",
    "BENCH_SHARDED_PODS": "96",
    "BENCH_CHURN_NODES": "8",
}


def test_bench_smoke_e2e():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        timeout=560,
        cwd=REPO,
        env={**os.environ, **SMOKE_ENV},
    )
    assert proc.returncode == 0, proc.stderr[-2000:] + proc.stdout[-500:]
    records = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]
    assert not any("diag" in r and "failed" in r["diag"] for r in records), records
    metrics = {r["metric"]: r for r in records if "metric" in r}
    for want in (
        "scheduling_throughput_64nodes",
        "scheduling_throughput_64nodes_deployed_default",
        "scheduling_throughput_64nodes_weighted_multi_scorer",
        "host_loop_32nodes",
        "host_loop_32nodes_deep16w",
        "host_loop_32nodes_pipelined",
        "host_loop_32nodes_fused",
        "host_loop_32nodes_resident",
        "host_loop_32nodes_streaming",
        "host_loop_32nodes_idle_streaming",
        "host_loop_32nodes_streaming_drift",
        "host_loop_256nodes",
        "host_loop_256nodes_streaming",
        "host_loop_25nodes_sharded_ref",
        "scheduling_throughput_256nodes",
        "host_loop_32nodes_replicas1",
        "host_loop_32nodes_replicas2",
        "host_loop_32nodes_replicas4",
        "host_loop_32nodes_replicas1_shared",
        "host_loop_32nodes_replicas4_shared",
        "host_loop_32nodes_replicas",
        "host_loop_32nodes_replay",
        "host_loop_32nodes_shadow",
        "host_loop_32nodes_telemetry",
        "host_loop_32nodes_attribution",
        "scenario_burst_32nodes",
        "scenario_gang_32nodes",
        "host_loop_32nodes_chaos",
    ):
        assert want in metrics, (want, sorted(metrics))
    for name in (
        "host_loop_32nodes",
        "host_loop_32nodes_pipelined",
        "host_loop_32nodes_resident",
    ):
        assert metrics[name]["pods_bound"] > 0, metrics[name]
        assert metrics[name]["cycle_p50_ms"] > 0, metrics[name]
    # the pipelined loop reports its observability companions
    assert "host_overlap_p50_ms" in metrics["host_loop_32nodes_pipelined"]
    assert "pipeline_flushes" in metrics["host_loop_32nodes_pipelined"]
    # the fused metric carries the in-round fused/unfused A-B so the
    # megakernel's engine delta is in-data every round (the speedup
    # itself is not asserted at smoke sizes — CPU interpreter cycles)
    fus = metrics["host_loop_32nodes_fused"]
    assert fus["pods_bound"] > 0, fus
    assert fus["unfused_pods_per_sec"] > 0, fus
    assert "fused_engine_speedup" in fus and "fused_cycle_speedup" in fus
    assert fus["fallback_cycles"] == 0, fus
    # the resident loop actually exercised the delta path and reports
    # the upload accounting the acceptance gate reads
    res = metrics["host_loop_32nodes_resident"]
    assert res["delta_uploads"] > 0, res
    assert res["fallback_cycles"] == 0, res
    assert 0.0 < res["delta_hit_rate"] <= 1.0, res
    assert res["snapshot_upload_bytes"] > 0, res
    assert res["delta_bytes_saved"] > 0, res
    # the streaming-ingestion drain: the mirror actually replaced the
    # rebuild (deltas shipped, zero verify failures, no flush storm —
    # rebuilds stay at the seed + node-churn count), and the
    # stage-replacement evidence (mirror_emit vs snapshot_build +
    # delta_derive p50s) is in-data; the >=5x ratio itself is a
    # real-size claim, not a smoke assert
    stream = metrics["host_loop_32nodes_streaming"]
    assert stream["pods_bound"] > 0, stream
    assert stream["fallback_cycles"] == 0, stream
    assert stream["delta_uploads"] > 0, stream
    assert stream["mirror_verify_failures"] == 0, stream
    assert stream["mirror_events_per_cycle"] > 0, stream
    assert stream["mirror_full_rebuilds"] <= 2, stream
    assert "streaming_stage_speedup" in stream, stream
    assert stream["baseline_pods_per_sec"] > 0, stream
    # the sub-50ms cycle gate's alarm rode the drain (breaches are
    # REPORTED — CPU smoke cycles jitter, the <50ms claim is real-size)
    assert stream["cycle_slo_ms"] == 50.0, stream
    assert stream["slo_breaches"] >= 0, stream
    # the idle-cluster row: zero events -> zero-row deltas at ~0 cost,
    # and the event trigger wakes within the watchdog budget
    idle = metrics["host_loop_32nodes_idle_streaming"]
    assert idle["idle_zero_row_deltas"] is True, idle
    assert idle["events_per_cycle"] == 0, idle
    assert idle["mirror_emit_idle_p50_ms"] >= 0, idle
    assert idle["trigger_latency_p50_ms"] < 500, idle
    # the layout-drift row: every round minted a fresh selector and
    # remapped a hostPort, yet the recurring drift classes were
    # ABSORBED in place — rebuilds across the drifting rounds are the
    # few power-of-two bucket/slot crossings, not one per round — and
    # the final bitwise verify proves the absorbed state equals a
    # rebuild's
    drift = metrics["host_loop_32nodes_streaming_drift"]
    assert drift["pods_bound"] > 0, drift
    ext = drift["mirror_incremental_extensions"]
    rounds = drift["drift_rounds"]
    assert ext.get("selector", 0) >= rounds - 4, drift
    assert ext.get("port-remap", 0) >= rounds - 4, drift
    assert drift["drift_rebuilds"] <= 4, drift
    # the slot budget was warmed: hostPort churn NEVER grew the table
    assert drift["mirror_rebuild_reasons"].get("port-churn", 0) == 0, drift
    assert drift["mirror_verify_failures"] == 0, drift
    assert drift["final_verify_ok"] is True, drift
    # the mesh-sharded resident loop: every device cycle went through
    # the 8-shard mesh, the delta path actually routed per-shard
    # payloads, and the flat-bytes evidence (per-cycle routed bytes vs
    # the tenth-scale reference) is in-data — the <=2x gate itself is
    # asserted with controlled workloads in
    # test_sharded_flat_bytes_gate_e2e
    sha = metrics["host_loop_256nodes"]
    assert sha["pods_bound"] > 0, sha
    assert sha["fallback_cycles"] == 0, sha
    assert sha["mesh_devices"] == 8, sha
    assert sha["sharded_cycles"] == sha["cycles"], sha
    assert sha["delta_uploads"] > 0, sha
    assert sha["shard_delta_bytes_per_cycle"] > 0, sha
    assert sha["ref_shard_delta_bytes_per_cycle"] > 0, sha
    assert sha["flat_bytes_ratio"] > 0, sha
    ref = metrics["host_loop_25nodes_sharded_ref"]
    assert ref["pods_bound"] > 0 and ref["fallback_cycles"] == 0, ref
    # the combined scale row: streaming ingestion feeding the 8-shard
    # mesh — mirror emits route as per-shard deltas, cross-checks clean
    comb = metrics["host_loop_256nodes_streaming"]
    assert comb["pods_bound"] > 0, comb
    assert comb["fallback_cycles"] == 0, comb
    assert comb["mesh_devices"] == 8, comb
    assert comb["sharded_cycles"] == comb["cycles"], comb
    assert comb["delta_uploads"] > 0, comb
    assert comb["shard_delta_bytes_per_cycle"] > 0, comb
    assert comb["mirror_verify_failures"] == 0, comb
    st = metrics["scheduling_throughput_256nodes"]
    assert st["mesh_devices"] == 8 and st["assigned"] > 0, st
    assert st["value"] > 0, st
    # the replicated-fleet rows: every fleet size drained its whole
    # partitioned backlog (192 = 3 measured 64-pod backlogs), the
    # 4-replica fleet split it evenly (crc32 tenant round-robin), and
    # the deterministic conflict storm resolved EVERY overlap loser —
    # conflicts counted, losers requeued then retired, zero double
    # binds, zero lost pods. The >=1.6x scaling_x_2 gate is a
    # real-size claim (fixed per-cycle overheads dominate 64-pod
    # drains), recorded in BENCH.md, not asserted here.
    for n in (1, 2, 4):
        rrow = metrics[f"host_loop_32nodes_replicas{n}"]
        assert rrow["pods_bound"] > 0, rrow
        assert rrow["double_binds"] == 0, rrow
        assert len(rrow["binds_per_replica"]) == n, rrow
    r4 = metrics["host_loop_32nodes_replicas4"]
    assert len(set(r4["binds_per_replica"].values())) == 1, r4
    rhead = metrics["host_loop_32nodes_replicas"]
    assert rhead["double_binds"] == 0, rhead
    assert rhead["pods_lost"] == 0, rhead
    assert rhead["bind_conflicts"] == rhead["storm_overlap_pods"], rhead
    assert rhead["pods_discarded"] == rhead["storm_overlap_pods"], rhead
    assert rhead["requeue_latency_count"] == rhead["bind_conflicts"], rhead
    assert rhead["requeue_latency_mean_ms"] > 0, rhead
    assert rhead["scaling_x_2"] > 0 and rhead["scaling_x_4"] > 0, rhead
    # the fleet-shared engine rows: ONE pooled resident engine serving
    # the whole fleet — nothing double-binds, uploads actually flowed
    # through the pool's dedupe accounting, and the fleet shipped fewer
    # snapshot bytes than N private engines pay for the same traffic
    for n in (1, 4):
        srow = metrics[f"host_loop_32nodes_replicas{n}_shared"]
        assert srow["pods_bound"] > 0, srow
        assert srow["double_binds"] == 0, srow
        assert sum(srow["uploads"].values()) >= 1, srow
        assert srow["upload_bytes_vs_private"] < 1.0, srow
    s4 = metrics["host_loop_32nodes_replicas4_shared"]
    # the 4-replica drain coalesced: device invocations strictly below
    # one per replica per round (the >=3.65x scaling_x_4 gate itself is
    # a real-size claim, recorded in BENCH.md, not asserted at smoke)
    assert s4["coalesced_dispatches"] > 0, s4
    assert s4["dispatches_per_round"] < 4, s4
    assert "scaling_x_4" in s4, s4
    # the shared-engine conflict storm: contention semantics intact
    # while the pool coalesces below one dispatch per replica per tick
    assert rhead["shared_storm_double_binds"] == 0, rhead
    assert rhead["shared_storm_pods_lost"] == 0, rhead
    assert rhead["shared_storm_bind_conflicts"] > 0, rhead
    assert rhead["shared_storm_dispatches_per_tick"] < 2, rhead
    # the flight-recorder metric: replay reproduced the recorded
    # bindings bitwise (the acceptance gate) on a recorded workload
    rep = metrics["host_loop_32nodes_replay"]
    assert rep["binding_diffs"] == 0, rep
    assert rep["cycles_replayed"] > 0, rep
    assert rep["pods_replayed"] > 0, rep
    assert rep["traced_pods_per_sec"] > 0, rep
    # the recorder's own wall time is reported (the <5% overhead gate's
    # evidence; not asserted at smoke sizes where cycles are ~ms)
    assert "trace_overhead_pct" in rep, rep
    assert rep["trace_bytes"] > 0, rep
    # the shadow-serving metric: an identical candidate config re-scored
    # the recorded journal with ZERO decision divergence, and the
    # keep-up evidence (re-score rate, candidate/recorded latency
    # ratio) is in-data every round
    sh = metrics["host_loop_32nodes_shadow"]
    assert sh["records_rescored"] > 0, sh
    assert sh["bindings_changed"] == 0, sh
    assert sh["divergence_ratio"] == 0.0, sh
    assert sh["shadow_pods_per_sec"] > 0, sh
    assert sh["breaker_state"] == "closed", sh
    # full-telemetry metric: spans were actually written during the
    # drain, the concurrent scraper got real responses, and the
    # vs-pipelined ratio (the <5% gate's evidence at real sizes) is
    # reported — not asserted at smoke sizes where cycles are ~ms
    tel = metrics["host_loop_32nodes_telemetry"]
    assert tel["pods_bound"] > 0, tel
    assert tel["spans_written"] > 0, tel
    assert tel["span_bytes"] > 0, tel
    assert tel["spans_dropped"] == 0, tel
    assert tel["metrics_scrapes"] > 0, tel
    assert "telemetry_overhead_pct" in tel, tel
    # the attribution metric: per-stage cycle budget over the telemetry
    # drain's own spans — the percentages (engine step, host stages,
    # "other" residual) must close at ~100% of total cycle time
    att = metrics["host_loop_32nodes_attribution"]
    assert att["cycles"] > 0 and att["cycle_p50_ms"] > 0, att
    assert "engine_step" in att["attribution_pct"], att
    assert abs(sum(att["attribution_pct"].values()) - 100.0) < 0.5, att
    assert att["stage_p50_ms"]["engine_step"] > 0, att
    # scenario-harness metrics: the burst program drained on the device
    # path; the gang mix reports the all-or-nothing admit rate
    for name in ("scenario_burst_32nodes", "scenario_gang_32nodes"):
        assert metrics[name]["pods_bound"] > 0, metrics[name]
        assert metrics[name]["fallback_cycles"] == 0, metrics[name]
    gang = metrics["scenario_gang_32nodes"]
    assert gang["gangs_admitted"] > 0, gang
    assert 0.0 < gang["gang_admit_rate"] <= 1.0, gang
    # the chaos drain (RPC-flap + solid-outage plan beside the clean
    # pipelined drain): faults actually injected, degraded cycles
    # bounded, the breaker walked its full open -> half-open -> closed
    # arc, recovery latency is in-data, and the run ENDED recovered
    chaos = metrics["host_loop_32nodes_chaos"]
    assert chaos["pods_bound"] > 0, chaos
    assert chaos["faults_injected"], chaos
    assert 0 < chaos["degraded_cycles"] < chaos["cycles"], chaos
    assert chaos["breaker_transitions"].get("open", 0) >= 1, chaos
    assert chaos["breaker_transitions"].get("closed", 0) >= 1, chaos
    assert chaos["breaker_state"] == "closed", chaos
    assert chaos["recovery_episodes"] > 0, chaos
    assert chaos["unrecovered_episodes"] == 0, chaos
    assert chaos["recovery_latency_ms_p99"] > 0, chaos
    assert chaos["recovered"] is True, chaos


def test_chaos_smoke_e2e(tmp_path):
    """The `make chaos-smoke` flow as a test: the compound-storm chaos
    program at compressed scale with --require-recovery (exit 1 unless
    every degradation-ladder rung ends at top with the breakers
    closed), its journal replay-pinned by `trace replay` (exit 1 on
    ANY binding diff) — chaos runs are as deterministic as clean
    ones."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def run(*argv):
        return subprocess.run(
            [sys.executable, "-m", "kubernetes_scheduler_tpu", *argv],
            capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
        )

    journal = str(tmp_path / "compound-storm")
    rec = run(
        "scenario", "run", "compound-storm", "--nodes", "24",
        "--require-recovery", "--trace", journal,
    )
    assert rec.returncode == 0, rec.stderr[-2000:]
    summary = json.loads(rec.stdout.splitlines()[-1])
    assert summary["pods_bound"] > 0, summary
    assert summary["recovered"] is True, summary
    assert summary["degraded_cycles"] > 0, summary
    assert summary["faults_injected"], summary
    assert summary["trace_records_dropped"] > 0, summary  # disk-full bit
    assert summary["mirror_verify_failures"] >= 1, summary
    rep = run("trace", "replay", journal)
    assert rep.returncode == 0, rep.stderr[-2000:] + rep.stdout[-500:]
    report = json.loads(rep.stdout.splitlines()[-1])
    assert report["binding_diffs"] == 0 and report["replayed"] > 0


def test_replica_smoke_e2e(tmp_path):
    """The `make replica-smoke` flow as a test: the 2-replica
    conflict-storm scenario (partition-skew traffic + overlap
    submissions racing the bind-table CAS) at compressed scale — every
    conflict must RESOLVE (loser requeued through restore_window, then
    retired; never a lost pod, never a double bind) — and then BOTH
    per-replica journals replay-pinned independently by `trace replay`
    (exit 1 on ANY binding diff): the fenced CAS is downstream of the
    replayed engine boundary, so conflict cycles replay bitwise too."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def run(*argv):
        return subprocess.run(
            [sys.executable, "-m", "kubernetes_scheduler_tpu", *argv],
            capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
        )

    journal = str(tmp_path / "replica-storm")
    rec = run(
        "scenario", "run", "replica-conflict-storm", "--nodes", "24",
        "--trace", journal,
    )
    assert rec.returncode == 0, rec.stderr[-2000:]
    summary = json.loads(rec.stdout.splitlines()[-1])
    assert summary["replicas"] == 2, summary
    assert summary["pods_bound"] == summary["pods_submitted"], summary
    assert summary["bind_conflicts"] > 0, summary
    assert summary["double_binds"] == 0, summary
    # every conflict loser was retired through drop_bound — conflicts
    # resolved, not lost
    assert summary["pods_discarded"] >= summary["bind_conflicts"], summary
    assert summary["requeue_latency_mean_s"] >= 0, summary
    assert set(summary["binds_per_replica"]) == {"r0", "r1"}, summary
    assert all(v > 0 for v in summary["binds_per_replica"].values()), summary
    for sub in summary["journals"]:
        rep = run("trace", "replay", sub)
        assert rep.returncode == 0, (
            sub, rep.stderr[-2000:] + rep.stdout[-500:]
        )
        report = json.loads(rep.stdout.splitlines()[-1])
        assert report["binding_diffs"] == 0 and report["replayed"] > 0, (
            sub, report,
        )

    # the SAME storm through the fleet-shared engine (--shared-engine):
    # contention semantics intact (conflicts happened and every loser
    # resolved, zero double binds), the pool actually coalesced, and
    # the fleet paid fewer device dispatches than scheduler cycles —
    # then both journals replay-pinned through a PRIVATE engine, so
    # shared-engine decisions are bitwise the decisions a private
    # engine makes (the `make replica-smoke` shared leg)
    journal_s = str(tmp_path / "replica-storm-shared")
    rec = run(
        "scenario", "run", "replica-conflict-storm", "--nodes", "24",
        "--shared-engine", "--trace", journal_s,
    )
    assert rec.returncode == 0, rec.stderr[-2000:]
    shared = json.loads(rec.stdout.splitlines()[-1])
    assert shared["double_binds"] == 0, shared
    assert shared["bind_conflicts"] > 0, shared
    assert shared["pods_bound"] == shared["pods_submitted"], shared
    se = shared["shared_engine"]
    assert se["coalesced_dispatches"] > 0, se
    assert se["device_dispatches"] < shared["cycles"], (se, shared["cycles"])
    for sub in shared["journals"]:
        rep = run("trace", "replay", sub)
        assert rep.returncode == 0, (
            sub, rep.stderr[-2000:] + rep.stdout[-500:]
        )
        report = json.loads(rep.stdout.splitlines()[-1])
        assert report["binding_diffs"] == 0 and report["replayed"] > 0, (
            sub, report,
        )


def test_sharded_flat_bytes_gate_e2e():
    """The flat-bytes acceptance gate at compressed scale: on a
    metric-churn workload (fixed-size rotating utilization churn), the
    mesh-sharded resident loop's per-cycle routed delta payload at 8x
    the nodes must stay within 2x the small-scale figure — per-cycle
    host->device bytes scale with the CHANGE (churned rows + window
    binds), not the cluster. Runs in-process on the harness's 8-device
    topology; the pod count stays below the small scale's node count so
    neither scale is node-capped on bind rows (the 100k-vs-10k shape)."""
    import bench

    kw = dict(
        n_pods=48, max_windows=1, pipeline_depth=1, force_device=True,
        resident=True, sharded=True, churn_nodes=16,
    )
    small = bench.loop_rate(n_nodes=64, metric_suffix="_fb_small", **kw)
    big = bench.loop_rate(n_nodes=512, metric_suffix="_fb_big", **kw)
    for row in (small, big):
        assert row["fallback_cycles"] == 0, row
        assert row["delta_uploads"] > 0, row
        assert row["mesh_devices"] == 8, row
        assert row["shard_delta_bytes_per_cycle"] > 0, row
    ratio = (
        big["shard_delta_bytes_per_cycle"]
        / small["shard_delta_bytes_per_cycle"]
    )
    assert ratio <= 2.0, (
        f"per-cycle routed delta bytes grew {ratio:.2f}x over an 8x "
        f"node-count increase — the sharded resident path lost its "
        f"flat-bytes property ({small=} {big=})"
    )


def test_perf_gate_e2e(tmp_path):
    """The `make perf-gate` flow as a test: a fresh telemetry-shaped
    drain's span directory diffed against the COMMITTED
    BENCH_SPAN_BASELINE.json with the gate's per-stage thresholds —
    a per-stage fusion regression (e.g. an interpreter-mode kernel
    sneaking onto the CPU host path) fails loudly; then the synthetic
    trip-wire check (a slowed engine_step must exit 1) proves the gate
    can actually fail."""
    spans_dir = str(tmp_path / "spans")
    env = {
        **os.environ, "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "BENCH_LOOP_NODES": "32", "BENCH_LOOP_PODS": "64",
        "BENCH_SHARDED_NODES": "64", "BENCH_CHURN_NODES": "8",
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--perf-gate-spans", spans_dir],
        capture_output=True, text=True, timeout=560, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:] + proc.stdout[-500:]
    rows = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{") and "metric" in line
    ]
    metrics = {r["metric"]: r for r in rows if "metric" in r}
    metric = metrics["host_loop_32nodes_perfgate"]
    assert metric["spans_written"] > 0, metric
    # the sharded drain contributes its stage spans to the SAME gate
    # directory (the committed baseline covers them)
    sharded = metrics["host_loop_64nodes_perfgate_sharded"]
    assert sharded["spans_written"] > 0, sharded
    assert sharded["fallback_cycles"] == 0, sharded
    # the streaming drain contributes the mirror stages (event_apply,
    # mirror_emit) to the gate directory/baseline
    streaming = metrics["host_loop_32nodes_perfgate_streaming"]
    assert streaming["spans_written"] > 0, streaming
    assert streaming["mirror_verify_failures"] == 0, streaming

    def spans_diff(base, cand):
        # the `make perf-gate` thresholds: coarse floors (>20 ms AND
        # >100-150%) so cross-machine wall-clock variance cannot trip
        # the gate while an interpret-mode-kernel-class regression does
        return subprocess.run(
            [sys.executable, "-m", "kubernetes_scheduler_tpu", "spans",
             "diff", base, cand,
             "--threshold-pct", "100", "--min-ms", "20",
             "--stage-threshold", "engine_step=150",
             "--stage-threshold", "snapshot_build=150",
             "--stage-threshold", "cycle=150"],
            capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
        )

    baseline = os.path.join(REPO, "BENCH_SPAN_BASELINE.json")
    gate = spans_diff(baseline, spans_dir)
    report = json.loads(gate.stdout.splitlines()[-1])
    assert gate.returncode == 0, report
    assert report["clean"], report
    # trip-wire: the gate must be able to FAIL — a 20x engine_step blows
    # both the 5 ms floor and the 150% stage threshold
    from kubernetes_scheduler_tpu.trace.analyze import perturb_spans

    slow = str(tmp_path / "spans-slow")
    perturb_spans(spans_dir, slow, stage="engine_step", factor=20.0)
    tripped = spans_diff(baseline, slow)
    assert tripped.returncode == 1, tripped.stdout[-800:]
    assert "engine_step" in json.loads(
        tripped.stdout.splitlines()[-1]
    )["regressions"]


def test_obs_smoke_e2e(tmp_path):
    """The `make obs-smoke` flow as a test: a sidecar with its own
    /metrics + span files, a sim-driven host run (spans + exporter on)
    against it, a scrape of BOTH exporters, and the `spans merge` join —
    non-empty and ID-joined is the acceptance shape."""
    import socket
    import time
    import urllib.request

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    cfg = tmp_path / "config.json"
    cfg.write_text(
        '{"batch_window": 64, "min_device_work": 1, '
        '"adaptive_dispatch": false, "metrics_bind_host": "127.0.0.1"}'
    )
    grpc_port, side_mport, host_mport = free_port(), free_port(), free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    sidecar = subprocess.Popen(
        [
            sys.executable, "-m", "kubernetes_scheduler_tpu", "sidecar",
            "--port", str(grpc_port), "--metrics-port", str(side_mport),
            "--metrics-host", "127.0.0.1",
            "--span-path", str(tmp_path / "sidecar-spans"),
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{side_mport}/healthz", timeout=2
                )
                break
            except Exception:
                assert sidecar.poll() is None, sidecar.stdout.read()[-2000:]
                time.sleep(0.5)
        else:
            raise AssertionError("sidecar metrics endpoint never came up")

        host = subprocess.Popen(
            [
                sys.executable, "-m", "kubernetes_scheduler_tpu",
                "scheduler", "--nodes", "48", "--pods", "192",
                "--config", str(cfg),
                "--engine", f"127.0.0.1:{grpc_port}",
                "--spans", str(tmp_path / "host-spans"),
                "--metrics-port", str(host_mport),
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        # scrape the HOST exporter while the run is live (it serves from
        # process start; the first cycle's compile leaves ample time)
        host_bodies = []
        while host.poll() is None:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{host_mport}/metrics", timeout=2
                ) as r:
                    host_bodies.append(r.read().decode())
            except Exception:
                pass
            time.sleep(0.3)
        out, err = host.communicate(timeout=60)
        assert host.returncode == 0, err[-2000:]
        summary = json.loads(out.splitlines()[-1])
        assert summary["pods_bound"] == 192
        assert summary["fallback_cycles"] == 0
        assert host_bodies, "host /metrics was never scraped successfully"
        assert any("yoda_tpu_cycles_total" in b for b in host_bodies)

        # the sidecar's own exporter serves device-step histograms
        with urllib.request.urlopen(
            f"http://127.0.0.1:{side_mport}/metrics", timeout=10
        ) as r:
            side_body = r.read().decode()
        assert "yoda_tpu_device_step_duration_seconds_bucket" in side_body
        assert "yoda_tpu_rpcs_served_total" in side_body
    finally:
        sidecar.terminate()
        try:
            sidecar.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sidecar.kill()

    # merge joins the two sides on shared trace ids (exit 1 otherwise)
    merged = str(tmp_path / "merged.trace.json")
    proc = subprocess.run(
        [
            sys.executable, "-m", "kubernetes_scheduler_tpu", "spans",
            "merge", str(tmp_path / "host-spans"),
            str(tmp_path / "sidecar-spans"), "--out", merged,
        ],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:] + proc.stdout[-500:]
    report = json.loads(proc.stdout.splitlines()[-1])
    assert report["joined_trace_ids"] > 0, report
    assert report["host_events"] > 0 and report["sidecar_events"] > 0
    trace = json.load(open(merged))
    assert trace["traceEvents"], "merged timeline is empty"

    # the analytics round trip (`make obs-smoke`'s report/diff tail):
    # report over the run's own spans, a self-diff exiting 0, and a
    # diff against a synthetically slowed copy exiting 1 — the span
    # directory IS a working perf gate for the run that just happened
    def spans_cli(*argv):
        return subprocess.run(
            [sys.executable, "-m", "kubernetes_scheduler_tpu", "spans",
             *argv],
            capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
        )

    host_spans = str(tmp_path / "host-spans")
    rep = spans_cli("report", host_spans)
    assert rep.returncode == 0, rep.stderr[-2000:]
    rep_json = json.loads(rep.stdout.splitlines()[-1])
    assert rep_json["cycles"] > 0
    assert "engine_step" in rep_json["attribution_pct"]
    assert abs(sum(rep_json["attribution_pct"].values()) - 100.0) < 0.5
    # the sidecar timeline reports too (device_step percentiles), and a
    # merged trace is a valid report source
    side_rep = spans_cli("report", merged)
    assert side_rep.returncode == 0, side_rep.stderr[-2000:]
    assert "device_step" in json.loads(
        side_rep.stdout.splitlines()[-1]
    )["stages"]
    clean = spans_cli("diff", host_spans, host_spans)
    assert clean.returncode == 0, clean.stdout[-500:]
    from kubernetes_scheduler_tpu.trace.analyze import perturb_spans

    slow = str(tmp_path / "host-spans-slow")
    perturb_spans(host_spans, slow, stage="engine_step", factor=4.0)
    dirty = spans_cli("diff", host_spans, slow)
    assert dirty.returncode == 1, dirty.stdout[-500:]
    assert "engine_step" in json.loads(
        dirty.stdout.splitlines()[-1]
    )["regressions"]


def test_scenario_smoke_e2e(tmp_path):
    """The `make scenario-smoke` flow as a test: the two fastest
    registered scenarios at small scale, each emitting a journal that
    `trace replay` (exit 1 on ANY binding diff) must reproduce — the
    replay-pinning gate every scenario ships under."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def run(*argv):
        return subprocess.run(
            [sys.executable, "-m", "kubernetes_scheduler_tpu", *argv],
            capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
        )

    for name, checks in (
        ("burst", {}),
        ("gang-mix", {"gangs_admitted": lambda v: v > 0}),
    ):
        journal = str(tmp_path / name)
        rec = run(
            "scenario", "run", name, "--nodes", "32", "--trace", journal
        )
        assert rec.returncode == 0, rec.stderr[-2000:]
        summary = json.loads(rec.stdout.splitlines()[-1])
        assert summary["pods_bound"] > 0, summary
        assert summary["fallback_cycles"] == 0, summary
        for key, ok in checks.items():
            assert ok(summary[key]), summary
        rep = run("trace", "replay", journal)
        assert rep.returncode == 0, rep.stderr[-2000:] + rep.stdout[-500:]
        report = json.loads(rep.stdout.splitlines()[-1])
        assert report["binding_diffs"] == 0 and report["replayed"] > 0


def test_trace_smoke_e2e(tmp_path):
    """The `make trace-smoke` flow as a test: record a sim-driven run
    on the device path, replay the journal (exit 1 on ANY binding
    diff), and diff the recorded vs replayed journals (exit 1 on any
    decision difference)."""
    cfg = tmp_path / "config.json"
    cfg.write_text(
        '{"batch_window": 64, "min_device_work": 1, '
        '"adaptive_dispatch": false}'
    )
    journal = str(tmp_path / "journal")
    replayed = str(tmp_path / "replayed")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def run(*argv):
        return subprocess.run(
            [sys.executable, "-m", "kubernetes_scheduler_tpu", *argv],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )

    rec = run(
        "scheduler", "--nodes", "48", "--pods", "192",
        "--config", str(cfg), "--trace", journal,
    )
    assert rec.returncode == 0, rec.stderr[-2000:]
    summary = json.loads(rec.stdout.splitlines()[-1])
    assert summary["pods_bound"] == 192 and summary["fallback_cycles"] == 0

    rep = run("trace", "replay", journal, "--out", replayed)
    assert rep.returncode == 0, rep.stderr[-2000:] + rep.stdout[-500:]
    report = json.loads(rep.stdout.splitlines()[-1])
    assert report["binding_diffs"] == 0 and report["replayed"] > 0

    dif = run("trace", "diff", journal, replayed)
    assert dif.returncode == 0, dif.stderr[-2000:] + dif.stdout[-500:]
    assert json.loads(dif.stdout.splitlines()[-1])["differences"] == 0


def test_lint_artifact_and_sarif_e2e(tmp_path):
    """The `make lint` / `make lint-sarif` CI surface: one full-repo run
    (engine contracts included) under the Makefile's wall-time budget
    writing the findings-JSON artifact, and a SARIF 2.1.0 artifact that
    passes the structural validator — the exact invocations the
    Makefile targets wire, minus the shell."""
    artifact = tmp_path / "lint.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_scheduler_tpu.analysis",
         "--budget-seconds", "300", "--json-artifact", str(artifact)],
        capture_output=True, text=True, timeout=400, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    findings = json.loads(artifact.read_text())
    assert isinstance(findings, list)
    # a green run's artifact holds ONLY waived findings, reasons intact
    assert all(f["waived"] and f["waiver_reason"] for f in findings)

    sarif_proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_scheduler_tpu.analysis",
         "--format", "sarif", "--no-contracts"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert sarif_proc.returncode == 0, sarif_proc.stderr[-2000:]
    from kubernetes_scheduler_tpu.analysis.sarif import validate_sarif

    doc = json.loads(sarif_proc.stdout)
    validate_sarif(doc)
    rule_ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"donation-aliasing", "host-transfer", "tracer-leak",
            "lockset-race", "thread-race", "determinism-taint"} <= rule_ids


def test_lint_walltime_budget_e2e():
    """The parse-once index gate: running ALL eighteen AST families over
    the full repo must cost less than 2x the sixteen-family PR-14
    baseline measured in the SAME process (the thread-model and
    determinism-taint families ride the shared index and its call graph
    instead of re-parsing/re-walking). Measured on warm imports so the
    ratio is the analyses', not the interpreter's; the absolute ceiling
    lives in the Makefile's LINT_BUDGET."""
    import time

    from kubernetes_scheduler_tpu.analysis import run_lint

    pr14_families = [
        "jit-purity", "host-sync", "lock-discipline", "wire-schema",
        "dtype-shape", "timeout-hygiene", "pallas-vmem", "metric-hygiene",
        "sim-determinism", "span-hygiene", "donation-aliasing",
        "host-transfer", "tracer-leak", "lockset-race",
        "capability-completeness", "spmd-collective",
    ]
    run_lint(rules=pr14_families)  # warm imports/caches out of the timing
    t0 = time.monotonic()
    run_lint(rules=pr14_families)
    t_base = time.monotonic() - t0
    t0 = time.monotonic()
    vs = run_lint()  # all eighteen + docs-drift
    t_all = time.monotonic() - t0
    assert [v for v in vs if not v.waived] == []
    # generous noise floor for a loaded 1-CPU box: the gate is the
    # RATIO, and an index regression (each family re-walking every
    # tree) blows straight through 2x
    assert t_all < 2.0 * t_base + 0.75, (
        f"18-family lint {t_all:.2f}s vs 16-family baseline "
        f"{t_base:.2f}s — the parse-once index contract is broken"
    )


def test_spmd_lint_e2e(tmp_path):
    """The SPMD layer's CI surface, end to end: one full-repo lint run
    with all sixteen families + the contracts layer (sharded surfaces
    traced through shard_map on the 8-device virtual mesh, the
    COLLECTIVE_BUDGET.json gate, the seeded SPMD mutant harness) under
    the existing wall-time budget, emitting a SARIF artifact that
    validates and registers the new family; every seeded SPMD mutant
    caught one by one, by the layer that owns its class; and
    budget-file staleness failing loudly (a doctored budget must fail
    the gate, and an exact copy must pass it)."""
    import shutil

    artifact = tmp_path / "spmd-lint.json"
    env = {
        **os.environ, "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_scheduler_tpu.analysis",
         "--no-models", "--budget-seconds", "300",
         "--json-artifact", str(artifact), "--format", "sarif"],
        capture_output=True, text=True, timeout=400, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    from kubernetes_scheduler_tpu.analysis.sarif import validate_sarif

    doc = json.loads(proc.stdout)
    validate_sarif(doc)
    rule_ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert "spmd-collective" in rule_ids
    findings = json.loads(artifact.read_text())
    assert all(f["waived"] for f in findings), [
        f for f in findings if not f["waived"]
    ]

    # every seeded SPMD mutant caught one by one, by its declared layer
    from kubernetes_scheduler_tpu.analysis.spmd_mutants import (
        SPMD_MUTANTS,
        check_spmd_mutants,
        run_spmd_mutant,
    )

    assert set(SPMD_MUTANTS) == {
        "dropped-psum", "wrong-axis", "replicated-double-count",
        "extra-gather-over-budget",
    }
    for name, (_, _, expect) in SPMD_MUTANTS.items():
        got = run_spmd_mutant(name)
        for layer in expect:
            assert got[layer], (name, layer)
    # the extra-gather class is AST-silent by construction: only the
    # budget gate has it — proof the budget adds teeth the AST lacks
    assert run_spmd_mutant("extra-gather-over-budget")["ast"] == []
    assert check_spmd_mutants() == []

    # budget-file staleness fails loudly: a verbatim copy passes, a
    # doctored count fails with a diff naming the drifted kind
    from kubernetes_scheduler_tpu.analysis.contracts import (
        COLLECTIVE_BUDGET_NAME,
        check_collective_budget,
        traced_surface_counts,
    )

    traced = traced_surface_counts()
    committed = os.path.join(REPO, COLLECTIVE_BUDGET_NAME)
    copy = tmp_path / "budget-copy.json"
    shutil.copy(committed, copy)
    assert check_collective_budget(str(copy), traced=traced) == []
    doc = json.load(open(committed))
    doc["surfaces"]["sharded_schedule(greedy)"]["all_gather"] += 1
    stale = tmp_path / "budget-stale.json"
    stale.write_text(json.dumps(doc))
    vs = check_collective_budget(str(stale), traced=traced)
    assert vs and any("all_gather" in v.message for v in vs), [
        v.format() for v in vs
    ]


def test_model_check_e2e(tmp_path):
    """The `make model-check` CI surface, minus the shell: one run of
    the protocol-model layer — every shipped model's bounded state
    space exhausted, every transition anchor verified against the live
    source, every seeded mutant caught — under the acceptance budget
    (<60s on CPU; in practice ~2s), writing the JSON artifact CI diffs,
    plus the SARIF rendering. Exit 3 (un-exhausted proof) and exit 1
    (violation/survived mutant) would both fail here."""
    import time

    artifact = tmp_path / "model.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_scheduler_tpu.analysis.model",
         "--budget-seconds", "60", "--json-artifact", str(artifact)],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert wall < 60.0, f"model-check took {wall:.1f}s — smoke budget blown"
    doc = json.loads(artifact.read_text())
    assert len(doc["models"]) == 6
    assert all(m["exhausted"] and not m["violations"] for m in doc["models"])
    assert doc["mutants"] and all(
        d["caught"] for d in doc["mutants"].values()
    ), doc["mutants"]
    assert doc["anchor_drift"] == []
    # every model actually explored a nontrivial space and the harness
    # names the first finding that catches each mutant
    assert all(m["states"] > 1 for m in doc["models"])
    assert all(d["first_finding"] for d in doc["mutants"].values())

    sarif_proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_scheduler_tpu.analysis.model",
         "--format", "sarif", "--no-mutants"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert sarif_proc.returncode == 0, sarif_proc.stderr[-2000:]
    from kubernetes_scheduler_tpu.analysis.sarif import validate_sarif

    validate_sarif(json.loads(sarif_proc.stdout))


def test_soak_smoke_e2e(tmp_path):
    """The `make soak-smoke` flow as a test: a baseline soak run pins
    the undisturbed journal, then a `yoda-tpu shadow` process attaches
    to a SECOND, still-being-written soak journal — following live
    rotations, serving its own /metrics — and must score every cycle
    with zero divergence under an identical candidate config while the
    primary's journal stays bitwise equal to the baseline (a tailing
    shadow perturbs nothing). The soak's span stream then drives the
    trend gate: clean exits 0, a perturb_trend-seeded leak exits 1."""
    import time
    import urllib.request

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cand = tmp_path / "candidate.json"
    cand.write_text(
        '{"batch_window": 256, "normalizer": "none", "min_device_work": 1, '
        '"adaptive_dispatch": false, "trace_file_bytes": 65536, '
        '"cycle_slo_ms": 15000.0}'
    )

    def run(*argv, check=True):
        proc = subprocess.run(
            [sys.executable, "-m", "kubernetes_scheduler_tpu", *argv],
            capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
        )
        if check:
            assert proc.returncode == 0, proc.stderr[-2000:] + proc.stdout[-500:]
        return proc

    journal_off = str(tmp_path / "journal-off")
    journal = str(tmp_path / "journal")
    spans = str(tmp_path / "spans")
    base = run(
        "scenario", "run", "soak", "--nodes", "16", "--seed", "0",
        "--trace", journal_off, "--spans", spans,
    )
    base_summary = json.loads(base.stdout.splitlines()[-1])
    assert base_summary["slo_breaches"] == 0, base_summary
    assert base_summary["fallback_cycles"] == 0, base_summary

    scenario = subprocess.Popen(
        [
            sys.executable, "-m", "kubernetes_scheduler_tpu", "scenario",
            "run", "soak", "--nodes", "16", "--seed", "0",
            "--trace", journal,
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    shadow = None
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(os.scandir(journal)) if os.path.isdir(journal) else False:
                break
            assert scenario.poll() is None, scenario.stdout.read()[-2000:]
            time.sleep(0.25)
        else:
            raise AssertionError("live soak journal never appeared")

        shadow = subprocess.Popen(
            [
                sys.executable, "-m", "kubernetes_scheduler_tpu", "shadow",
                journal, "--candidate-config", str(cand),
                "--follow", "--idle-timeout-s", "15",
                "--metrics-port", "0", "--metrics-host", "127.0.0.1",
                "--spans", str(tmp_path / "shadow-spans"),
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        # the exporter's bound port is the first stdout line
        port = json.loads(shadow.stdout.readline())["shadow_metrics_port"]
        # scrape the shadow's own exporter while it tails the live run
        body = ""
        deadline = time.time() + 120
        while time.time() < deadline and shadow.poll() is None:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=2
                ) as r:
                    body = r.read().decode()
                if "yoda_tpu_shadow_records_applied_total" in body:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        assert "yoda_tpu_shadow_records_applied_total" in body, body[:400]
        assert "yoda_tpu_shadow_cycles_total" in body, body[:400]

        sc_out, _ = scenario.communicate(timeout=240)
        assert scenario.returncode == 0, sc_out[-2000:]
        live_summary = json.loads(sc_out.splitlines()[-1])
        assert live_summary["fallback_cycles"] == 0, live_summary

        sh_out, sh_err = shadow.communicate(timeout=240)
        assert shadow.returncode == 0, sh_err[-2000:]
        summary = json.loads(sh_out.splitlines()[-1])
    finally:
        for proc in (scenario, shadow):
            if proc is not None and proc.poll() is None:
                proc.kill()

    # every tailed record scored, zero divergence under the identical
    # config, and the tail followed at least one live rotation
    assert summary["records_applied"] > 0, summary
    assert summary["cycles"].get("scored") == summary["records_applied"], summary
    assert summary["bindings_changed"] == 0, summary
    assert summary["divergence_ratio"] == 0.0, summary
    assert summary["gangs_diverged"] == 0, summary
    assert summary["breaker_state"] == "closed", summary
    assert summary["tail"]["rotations_followed"] >= 1, summary["tail"]

    # the primary never felt the shadow: bitwise-equal journals
    diff = run("trace", "diff", journal_off, journal)
    report = json.loads(diff.stdout.splitlines()[-1])
    assert report["differences"] == 0, report
    assert report["records_compared"] == summary["records_applied"], report

    # trend gate: the undisturbed soak is clean (exit 0)...
    clean = run("spans", "report", "--trend", spans, "--min-ms", "0.2")
    clean_report = json.loads(clean.stdout.splitlines()[-1])
    assert clean_report["clean"] is True, clean_report["regressions"]
    # ...and a seeded leak (engine_step ramped 1x->4x) exits 1 exactly
    from kubernetes_scheduler_tpu.trace.trend import perturb_trend

    leaky = str(tmp_path / "spans-leaky")
    perturb_trend(spans, leaky, stage="engine_step", factor=4.0)
    dirty = run(
        "spans", "report", "--trend", leaky, "--min-ms", "0.2", check=False
    )
    assert dirty.returncode == 1, dirty.stdout[-800:]
    assert "engine_step.p50_ms" in json.loads(
        dirty.stdout.splitlines()[-1]
    )["regressions"]

    # journal-level leak signals stay quiet on the clean soak
    trend = run("trace", "trend", journal)
    trend_report = json.loads(trend.stdout.splitlines()[-1])
    assert trend_report["clean"] is True, trend_report["regressions"]
