"""Bench-path smoke: bench.py end-to-end at toy sizes (slow-marked).

The benchmark is the repo's round-over-round evidence artifact; nothing
else imports it, so a refactor can silently rot it between rounds. This
drives the FULL default flow — engine headline, deployed-default and
weighted-multi-scorer measurements, the host loop including the
pipelined variant — as one subprocess with tiny BENCH_* knobs (the
`make bench-smoke` invocation), and asserts every expected metric line
comes back as parseable JSON."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_NODES": "64",
    "BENCH_PODS": "128",
    "BENCH_WINDOW": "32",
    "BENCH_REPS": "2",
    "BENCH_BASELINE_PODS": "8",
    "BENCH_LOOP_NODES": "32",
    "BENCH_LOOP_PODS": "64",
}


def test_bench_smoke_e2e():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        timeout=560,
        cwd=REPO,
        env={**os.environ, **SMOKE_ENV},
    )
    assert proc.returncode == 0, proc.stderr[-2000:] + proc.stdout[-500:]
    records = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]
    assert not any("diag" in r and "failed" in r["diag"] for r in records), records
    metrics = {r["metric"]: r for r in records if "metric" in r}
    for want in (
        "scheduling_throughput_64nodes",
        "scheduling_throughput_64nodes_deployed_default",
        "scheduling_throughput_64nodes_weighted_multi_scorer",
        "host_loop_32nodes",
        "host_loop_32nodes_deep16w",
        "host_loop_32nodes_pipelined",
        "host_loop_32nodes_resident",
        "host_loop_32nodes_replay",
    ):
        assert want in metrics, (want, sorted(metrics))
    for name in (
        "host_loop_32nodes",
        "host_loop_32nodes_pipelined",
        "host_loop_32nodes_resident",
    ):
        assert metrics[name]["pods_bound"] > 0, metrics[name]
        assert metrics[name]["cycle_p50_ms"] > 0, metrics[name]
    # the pipelined loop reports its observability companions
    assert "host_overlap_p50_ms" in metrics["host_loop_32nodes_pipelined"]
    assert "pipeline_flushes" in metrics["host_loop_32nodes_pipelined"]
    # the resident loop actually exercised the delta path and reports
    # the upload accounting the acceptance gate reads
    res = metrics["host_loop_32nodes_resident"]
    assert res["delta_uploads"] > 0, res
    assert res["fallback_cycles"] == 0, res
    assert 0.0 < res["delta_hit_rate"] <= 1.0, res
    assert res["snapshot_upload_bytes"] > 0, res
    assert res["delta_bytes_saved"] > 0, res
    # the flight-recorder metric: replay reproduced the recorded
    # bindings bitwise (the acceptance gate) on a recorded workload
    rep = metrics["host_loop_32nodes_replay"]
    assert rep["binding_diffs"] == 0, rep
    assert rep["cycles_replayed"] > 0, rep
    assert rep["pods_replayed"] > 0, rep
    assert rep["traced_pods_per_sec"] > 0, rep
    # the recorder's own wall time is reported (the <5% overhead gate's
    # evidence; not asserted at smoke sizes where cycles are ~ms)
    assert "trace_overhead_pct" in rep, rep
    assert rep["trace_bytes"] > 0, rep


def test_trace_smoke_e2e(tmp_path):
    """The `make trace-smoke` flow as a test: record a sim-driven run
    on the device path, replay the journal (exit 1 on ANY binding
    diff), and diff the recorded vs replayed journals (exit 1 on any
    decision difference)."""
    cfg = tmp_path / "config.json"
    cfg.write_text(
        '{"batch_window": 64, "min_device_work": 1, '
        '"adaptive_dispatch": false}'
    )
    journal = str(tmp_path / "journal")
    replayed = str(tmp_path / "replayed")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def run(*argv):
        return subprocess.run(
            [sys.executable, "-m", "kubernetes_scheduler_tpu", *argv],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )

    rec = run(
        "scheduler", "--nodes", "48", "--pods", "192",
        "--config", str(cfg), "--trace", journal,
    )
    assert rec.returncode == 0, rec.stderr[-2000:]
    summary = json.loads(rec.stdout.splitlines()[-1])
    assert summary["pods_bound"] == 192 and summary["fallback_cycles"] == 0

    rep = run("trace", "replay", journal, "--out", replayed)
    assert rep.returncode == 0, rep.stderr[-2000:] + rep.stdout[-500:]
    report = json.loads(rep.stdout.splitlines()[-1])
    assert report["binding_diffs"] == 0 and report["replayed"] > 0

    dif = run("trace", "diff", journal, replayed)
    assert dif.returncode == 0, dif.stderr[-2000:] + dif.stdout[-500:]
    assert json.loads(dif.stdout.splitlines()[-1])["differences"] == 0
