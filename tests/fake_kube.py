"""An httptest-style fake Kubernetes API server (stdlib http.server).

Implements just enough surface for the kube boundary tests: node/pod
lists with fieldSelector filtering, a bounded pod watch stream, the
Binding subresource POST, and coordination.k8s.io Leases with
resourceVersion compare-and-swap (409 on stale writes) — the semantics
KubeClient/KubeClusterSource/KubeBinder/KubeLease rely on.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_BIND_RE = re.compile(r"^/api/v1/namespaces/([^/]+)/pods/([^/]+)/binding$")
_POD_RE = re.compile(r"^/api/v1/namespaces/([^/]+)/pods/([^/]+)$")
_LEASE_RE = re.compile(
    r"^/apis/coordination\.k8s\.io/v1/namespaces/([^/]+)/leases(?:/([^/]+))?$"
)


class FakeKube:
    def __init__(self, *, token: str | None = None):
        self.lock = threading.Lock()
        self.nodes: list[dict] = []
        self.pods: dict[str, dict] = {}     # "ns/name" -> pod object
        self.deleted: list[str] = []        # "ns/name" DELETE log
        self.leases: dict[str, dict] = {}   # "ns/name" -> lease object
        self.pdbs: list[dict] = []          # policy/v1 PDB objects
        self.pvcs: list[dict] = []          # v1 PersistentVolumeClaims
        self.pvs: list[dict] = []           # v1 PersistentVolumes
        # v1 Namespace objects; None = no route (404, the pre-1.21 /
        # RBAC-denied regime some tests exercise)
        self.namespaces: list[dict] | None = None
        # apps/v1 workload controllers; None = route disabled (404)
        self.replicasets: list[dict] | None = None
        self.statefulsets: list[dict] | None = None
        # storage.k8s.io/v1 StorageClasses; None = route disabled (404)
        self.storageclasses: list[dict] | None = None
        self.pvc_patches: list[tuple[str, dict]] = []  # PATCH log
        self.bindings: list[tuple[str, str]] = []
        # node -> {cpu_pct, mem_pct, disk_io, net_up, net_down}: served
        # Prometheus-style from POST /api/v1/query so one fixture covers
        # both the API server and the metrics endpoint
        self.prom: dict[str, dict[str, float]] = {}
        self.token = token
        self._rv = 0
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), self._handler())
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "FakeKube":
        self.thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    @property
    def url(self) -> str:
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    # -- state helpers ---------------------------------------------------

    def add_node(self, obj: dict) -> None:
        with self.lock:
            self.nodes.append(obj)

    def add_pod(self, obj: dict) -> None:
        meta = obj.setdefault("metadata", {})
        meta.setdefault("namespace", "default")
        key = f"{meta['namespace']}/{meta['name']}"
        with self.lock:
            self.pods[key] = obj

    def add_replicaset(
        self, name: str, replicas: int, *, namespace: str = "default"
    ) -> None:
        with self.lock:
            if self.replicasets is None:
                self.replicasets = []
            self.replicasets.append({
                "metadata": {"name": name, "namespace": namespace},
                "spec": {"replicas": replicas},
            })

    def add_storageclass(self, name: str, mode: str) -> None:
        with self.lock:
            if self.storageclasses is None:
                self.storageclasses = []
            self.storageclasses.append(
                {"metadata": {"name": name}, "volumeBindingMode": mode}
            )

    def add_namespace(self, name: str, labels: dict | None = None) -> None:
        with self.lock:
            if self.namespaces is None:
                self.namespaces = []
            self.namespaces.append(
                {"metadata": {"name": name, "labels": labels or {}}}
            )

    # -- request handling ------------------------------------------------

    def _match_field_selector(self, pod: dict, selector: str) -> bool:
        spec = pod.get("spec") or {}
        for clause in filter(None, selector.split(",")):
            if "!=" in clause:
                key, val = clause.split("!=", 1)
                op = "ne"
            else:
                key, val = clause.split("=", 1)
                op = "eq"
            actual = {
                "spec.nodeName": spec.get("nodeName") or "",
                "spec.schedulerName": spec.get("schedulerName") or "",
                "status.phase": (pod.get("status") or {}).get("phase") or "",
            }.get(key, "")
            if op == "eq" and actual != val:
                return False
            if op == "ne" and actual == val:
                return False
        return True

    def _handler(self):
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, obj: dict | None = None):
                body = json.dumps(obj or {}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_raw(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def _read_body(self) -> dict:
                raw = self._read_raw()
                return json.loads(raw) if raw else {}

            def _auth_ok(self) -> bool:
                if fake.token is None:
                    return True
                return (
                    self.headers.get("Authorization")
                    == f"Bearer {fake.token}"
                )

            def do_GET(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                parsed = urllib.parse.urlparse(self.path)
                params = dict(urllib.parse.parse_qsl(parsed.query))
                path = parsed.path
                if path == "/api/v1/nodes":
                    with fake.lock:
                        return self._send(200, {"items": list(fake.nodes)})
                if path == "/apis/policy/v1/poddisruptionbudgets":
                    with fake.lock:
                        return self._send(200, {"items": list(fake.pdbs)})
                if path == "/api/v1/persistentvolumeclaims":
                    with fake.lock:
                        return self._send(200, {"items": list(fake.pvcs)})
                if path == "/api/v1/persistentvolumes":
                    with fake.lock:
                        return self._send(200, {"items": list(fake.pvs)})
                if path == "/api/v1/namespaces":
                    with fake.lock:
                        if fake.namespaces is None:
                            return self._send(
                                404, {"message": "namespaces disabled"}
                            )
                        return self._send(
                            200, {"items": list(fake.namespaces)}
                        )
                for route, store in (
                    ("/apis/apps/v1/replicasets", fake.replicasets),
                    ("/apis/apps/v1/statefulsets", fake.statefulsets),
                    ("/apis/storage.k8s.io/v1/storageclasses",
                     fake.storageclasses),
                ):
                    if path == route:
                        with fake.lock:
                            if store is None:
                                return self._send(
                                    404, {"message": "route disabled"}
                                )
                            return self._send(200, {"items": list(store)})
                m = _LEASE_RE.match(path)
                if m and m.group(2):
                    with fake.lock:
                        obj = fake.leases.get(f"{m.group(1)}/{m.group(2)}")
                    if obj is None:
                        return self._send(404, {"message": "not found"})
                    return self._send(200, obj)
                if path == "/api/v1/pods" or re.match(
                    r"^/api/v1/namespaces/[^/]+/pods$", path
                ):
                    ns = None
                    if path != "/api/v1/pods":
                        ns = path.split("/")[4]
                    sel = params.get("fieldSelector", "")
                    with fake.lock:
                        items = [
                            p
                            for key, p in fake.pods.items()
                            if (ns is None or key.startswith(ns + "/"))
                            and fake._match_field_selector(p, sel)
                        ]
                    if params.get("watch") == "true":
                        # bounded stream: one ADDED event per matching pod
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.end_headers()
                        for p in items:
                            line = json.dumps(
                                {"type": "ADDED", "object": p}
                            ).encode() + b"\n"
                            self.wfile.write(line)
                        return
                    return self._send(200, {"items": items})
                return self._send(404, {"message": f"no route {path}"})

            def do_POST(self):
                path = urllib.parse.urlparse(self.path).path
                if path == "/api/v1/query":  # Prometheus, not k8s: no auth
                    form = urllib.parse.parse_qs(
                        self._read_raw().decode("utf-8", "replace")
                    )
                    query = (form.get("query") or [""])[0]
                    series = {
                        "cpu_usage": "cpu_pct",
                        "MemTotal": "mem_pct",
                        "node_disk": "disk_io",
                        "transmit": "net_up",
                        "receive": "net_down",
                    }
                    name = next(
                        (v for k, v in series.items() if k in query), None
                    )
                    with fake.lock:
                        result = [
                            {
                                "metric": {"kubernetes_io_hostname": node},
                                "value": [0, str(vals.get(name, 0.0))],
                            }
                            for node, vals in fake.prom.items()
                        ]
                    return self._send(
                        200, {"data": {"resultType": "vector", "result": result}}
                    )
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                m = _BIND_RE.match(path)
                if m:
                    ns, name = m.group(1), m.group(2)
                    body = self._read_body()
                    target = (body.get("target") or {}).get("name")
                    want_uid = (body.get("metadata") or {}).get("uid")
                    with fake.lock:
                        pod = fake.pods.get(f"{ns}/{name}")
                        if pod is None:
                            return self._send(404, {"message": "pod not found"})
                        have_uid = (pod.get("metadata") or {}).get("uid")
                        if want_uid and have_uid and want_uid != have_uid:
                            # real API-server UID precondition: the name
                            # now belongs to a different (recreated) pod
                            return self._send(
                                409, {"message": "uid precondition failed"}
                            )
                        if (pod.get("spec") or {}).get("nodeName"):
                            return self._send(
                                409, {"message": "pod already bound"}
                            )
                        pod.setdefault("spec", {})["nodeName"] = target
                        fake.bindings.append((f"{ns}/{name}", target))
                    return self._send(201, {"status": "Success"})
                m = _LEASE_RE.match(path)
                if m and not m.group(2):
                    body = self._read_body()
                    name = (body.get("metadata") or {}).get("name")
                    key = f"{m.group(1)}/{name}"
                    with fake.lock:
                        if key in fake.leases:
                            return self._send(409, {"message": "exists"})
                        body.setdefault("metadata", {})[
                            "resourceVersion"
                        ] = fake.next_rv()
                        fake.leases[key] = body
                    return self._send(201, body)
                return self._send(404, {"message": f"no route {path}"})

            def do_PUT(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                path = urllib.parse.urlparse(self.path).path
                m = _LEASE_RE.match(path)
                if m and m.group(2):
                    key = f"{m.group(1)}/{m.group(2)}"
                    body = self._read_body()
                    sent_rv = (body.get("metadata") or {}).get("resourceVersion")
                    with fake.lock:
                        current = fake.leases.get(key)
                        if current is None:
                            return self._send(404, {"message": "not found"})
                        cur_rv = (current.get("metadata") or {}).get(
                            "resourceVersion"
                        )
                        if sent_rv != cur_rv:
                            return self._send(409, {"message": "conflict"})
                        body.setdefault("metadata", {})[
                            "resourceVersion"
                        ] = fake.next_rv()
                        fake.leases[key] = body
                    return self._send(200, body)
                return self._send(404, {"message": f"no route {path}"})

            def do_PATCH(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                path = urllib.parse.urlparse(self.path).path
                m = re.match(
                    r"^/api/v1/namespaces/([^/]+)"
                    r"/persistentvolumeclaims/([^/]+)$", path,
                )
                if m:
                    ns, name = m.group(1), m.group(2)
                    body = self._read_body()
                    with fake.lock:
                        pvc = next(
                            (
                                o for o in fake.pvcs
                                if (o.get("metadata") or {}).get("name") == name
                                and (o.get("metadata") or {}).get(
                                    "namespace", "default") == ns
                            ),
                            None,
                        )
                        if pvc is None:
                            return self._send(404, {"message": "not found"})
                        ann = pvc.setdefault("metadata", {}).setdefault(
                            "annotations", {}
                        )
                        ann.update(
                            (body.get("metadata") or {}).get("annotations")
                            or {}
                        )
                        fake.pvc_patches.append((f"{ns}/{name}", body))
                    return self._send(200, pvc)
                return self._send(404, {"message": f"no route {path}"})

            def do_DELETE(self):
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                path = urllib.parse.urlparse(self.path).path
                m = _LEASE_RE.match(path)
                if m and m.group(2):
                    key = f"{m.group(1)}/{m.group(2)}"
                    with fake.lock:
                        if fake.leases.pop(key, None) is None:
                            return self._send(404, {"message": "not found"})
                    return self._send(200, {"status": "Success"})
                m = _POD_RE.match(path)
                if m:
                    ns, name = m.group(1), m.group(2)
                    body = self._read_body()
                    want_uid = (body.get("preconditions") or {}).get("uid")
                    with fake.lock:
                        pod = fake.pods.get(f"{ns}/{name}")
                        if pod is None:
                            return self._send(404, {"message": "not found"})
                        have_uid = (pod.get("metadata") or {}).get("uid")
                        if want_uid and have_uid and want_uid != have_uid:
                            return self._send(
                                409,
                                {"message": "uid precondition failed"},
                            )
                        fake.pods.pop(f"{ns}/{name}")
                        fake.deleted.append(f"{ns}/{name}")
                    return self._send(200, {"status": "Success"})
                return self._send(404, {"message": f"no route {path}"})

        return Handler


def make_node_obj(name: str, *, cpu="8", memory="32Gi", labels=None, taints=None):
    return {
        "metadata": {"name": name, "labels": labels or {}},
        "spec": {"taints": taints or []},
        "status": {
            "allocatable": {"cpu": cpu, "memory": memory, "pods": "110"}
        },
    }


def make_pod_obj(
    name: str,
    *,
    namespace="default",
    scheduler_name="yoda-tpu",
    cpu="500m",
    memory="1Gi",
    node_name=None,
    labels=None,
    annotations=None,
    extra_spec=None,
    uid=None,
):
    spec = {
        "schedulerName": scheduler_name,
        "containers": [
            {
                "name": "main",
                "resources": {"requests": {"cpu": cpu, "memory": memory}},
            }
        ],
    }
    if node_name:
        spec["nodeName"] = node_name
    spec.update(extra_spec or {})
    return {
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": uid or f"uid-{namespace}-{name}",
            "labels": labels or {},
            "annotations": annotations or {},
        },
        "spec": spec,
        "status": {"phase": "Running" if node_name else "Pending"},
    }
