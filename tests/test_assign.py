"""Assignment kernels: greedy parity with the scalar oracle, capacity safety."""

import numpy as np
import jax.numpy as jnp

from kubernetes_scheduler_tpu.ops.assign import auction_assign, greedy_assign
from tests import oracle

RNG = np.random.default_rng(2)


def random_problem(p, n, r=3):
    scores = RNG.uniform(0, 10, (p, n)).astype(np.float32)
    feasible = RNG.random((p, n)) > 0.2
    pod_req = RNG.integers(1, 5, (p, r)).astype(np.float32)
    node_free = RNG.integers(3, 20, (n, r)).astype(np.float32)
    priority = RNG.integers(0, 5, p).astype(np.int32)
    return scores, feasible, pod_req, node_free, priority


def test_greedy_matches_oracle():
    scores, feasible, pod_req, node_free, priority = random_problem(20, 7)
    res = greedy_assign(
        jnp.asarray(scores),
        jnp.asarray(feasible),
        jnp.asarray(pod_req),
        jnp.asarray(node_free),
        jnp.asarray(priority),
        jnp.ones(20, bool),
    )
    want = oracle.greedy_assign_oracle(
        scores.tolist(), feasible.tolist(), pod_req.tolist(),
        node_free.tolist(), priority.tolist(),
    )
    assert np.asarray(res.node_idx).tolist() == want


def _check_capacity(node_idx, pod_req, node_free):
    used = np.zeros_like(node_free)
    for i, j in enumerate(node_idx):
        if j >= 0:
            used[j] += pod_req[i]
    assert (used <= node_free + 1e-6).all()


def test_greedy_capacity_never_oversubscribed():
    scores, feasible, pod_req, node_free, priority = random_problem(64, 5)
    res = greedy_assign(
        jnp.asarray(scores), jnp.asarray(feasible), jnp.asarray(pod_req),
        jnp.asarray(node_free), jnp.asarray(priority), jnp.ones(64, bool),
    )
    idx = np.asarray(res.node_idx)
    _check_capacity(idx, pod_req, node_free)
    # free_after is consistent
    used = node_free - np.asarray(res.free_after)
    want_used = np.zeros_like(node_free)
    for i, j in enumerate(idx):
        if j >= 0:
            want_used[j] += pod_req[i]
    np.testing.assert_allclose(used, want_used)


def test_greedy_priority_order_wins_scarce_node():
    # One node, capacity for one pod; higher priority pod gets it.
    scores = jnp.asarray([[5.0], [9.0]])
    feasible = jnp.ones((2, 1), bool)
    pod_req = jnp.asarray([[1.0], [1.0]])
    node_free = jnp.asarray([[1.0]])
    priority = jnp.asarray([10, 1], jnp.int32)
    res = greedy_assign(scores, feasible, pod_req, node_free, priority, jnp.ones(2, bool))
    assert np.asarray(res.node_idx).tolist() == [0, -1]


def test_greedy_pod_mask_padding_ignored():
    scores, feasible, pod_req, node_free, priority = random_problem(8, 4)
    mask = np.array([True] * 5 + [False] * 3)
    res = greedy_assign(
        jnp.asarray(scores), jnp.asarray(feasible), jnp.asarray(pod_req),
        jnp.asarray(node_free), jnp.asarray(priority), jnp.asarray(mask),
    )
    idx = np.asarray(res.node_idx)
    assert (idx[5:] == -1).all()


def test_auction_hot_node_contention_spreads():
    # Degenerate case the price mechanism exists for: every pod's best node
    # is node 0 (capacity 1). Without prices, each round fills one node and
    # a fixed round budget strands schedulable pods; with prices, contenders
    # spread and everyone lands somewhere.
    p, n = 32, 40
    scores = np.full((p, n), 1.0, np.float32)
    scores[:, 0] = 10.0
    pod_req = np.ones((p, 1), np.float32)
    node_free = np.ones((n, 1), np.float32)
    res = auction_assign(
        jnp.asarray(scores), jnp.ones((p, n), bool), jnp.asarray(pod_req),
        jnp.asarray(node_free), jnp.zeros(p, jnp.int32), jnp.ones(p, bool),
    )
    idx = np.asarray(res.node_idx)
    assert (idx >= 0).all()
    _check_capacity(idx, pod_req, node_free)
    # no node got two pods
    assert len(set(idx.tolist())) == p


def test_auction_maximal_at_scale():
    # Contested: 256 pods over 32 nodes with tight capacity. At default
    # rounds the result must be maximal — no unassigned pod fits anywhere.
    scores, feasible, pod_req, node_free, priority = random_problem(256, 32)
    res = auction_assign(
        jnp.asarray(scores), jnp.asarray(feasible), jnp.asarray(pod_req),
        jnp.asarray(node_free), jnp.asarray(priority), jnp.ones(256, bool),
    )
    idx = np.asarray(res.node_idx)
    _check_capacity(idx, pod_req, node_free)
    free = np.asarray(res.free_after)
    un = idx < 0
    could = (
        ((pod_req[un][:, None, :] <= free[None]) | (pod_req[un][:, None, :] == 0))
        .all(-1) & feasible[un]
    )
    assert not could.any(1).any(), "auction left schedulable pods unassigned"


def test_auction_capacity_safe_and_complete():
    scores, feasible, pod_req, node_free, priority = random_problem(48, 6)
    res = auction_assign(
        jnp.asarray(scores), jnp.asarray(feasible), jnp.asarray(pod_req),
        jnp.asarray(node_free), jnp.asarray(priority), jnp.ones(48, bool),
        rounds=16,
    )
    idx = np.asarray(res.node_idx)
    _check_capacity(idx, pod_req, node_free)
    # every unassigned pod truly has no feasible node with remaining capacity
    free = np.asarray(res.free_after)
    for i in np.where(idx < 0)[0]:
        for j in range(free.shape[0]):
            assert not (feasible[i, j] and (pod_req[i] <= free[j]).all())


def _final_affinity_violations(node_idx, snap, pods):
    """Count hard (anti)affinity violations in the FINAL state: for every
    placed pod, its anti selectors must match zero OTHER pods (pre-existing
    or window-placed) in its node's topology domain."""
    import numpy as np

    dom_id = np.asarray(snap.domain_id)          # [n, S]
    base = np.asarray(snap.domain_counts)        # [n, S]
    matches = np.asarray(pods.pod_matches)       # [p, S']
    anti = np.asarray(pods.anti_affinity_sel)    # [p, K]
    idx = np.asarray(node_idx)
    s = base.shape[1]
    if matches.shape[1] < s:  # default no-op pod_matches is [p, 1]
        matches = np.pad(matches, ((0, 0), (0, s - matches.shape[1])))
    # final counts per (representative domain row, selector)
    added = np.zeros_like(base)
    for i, j in enumerate(idx):
        if j >= 0:
            added[dom_id[j], np.arange(s)] += matches[i]
    has_anti = np.zeros((len(idx), s), bool)
    for i, row in enumerate(anti):
        for t in row:
            if 0 <= t < s:
                has_anti[i, t] = True
    added_avoid = np.zeros_like(base)
    for i, j in enumerate(idx):
        if j >= 0:
            added_avoid[dom_id[j], np.arange(s)] += has_anti[i]
    base_avoid = np.asarray(getattr(snap, "avoid_counts"))
    viol = 0
    for i, j in enumerate(idx):
        if j < 0:
            continue
        cnt = base[j] + added[dom_id[j], np.arange(s)]
        own = matches[i]
        for t in anti[i]:
            # forward: my anti selector matches another pod in my domain
            if 0 <= t < s and cnt[t] - own[t] > 0:
                viol += 1
        # reverse: another avoider (running or placed) in my domain
        # forbids a selector I match
        avoid_cnt = base_avoid[j] + added_avoid[dom_id[j], np.arange(s)] - has_anti[i]
        if ((avoid_cnt > 0) & own).any():
            viol += 1
    return viol


def test_auction_affinity_exact_no_final_violations():
    import numpy as np
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    for seed in (0, 4, 10, 22):
        snap = gen_cluster(64, seed=seed, constraints=True)
        pods = gen_pods(48, seed=seed + 1, constraints=True)
        res = schedule_batch(snap, pods, assigner="auction", normalizer="none")
        assert _final_affinity_violations(res.node_idx, snap, pods) == 0
        # quality: within a few placements of exact greedy
        g = schedule_batch(snap, pods, assigner="greedy", normalizer="none")
        assert int(res.n_assigned) >= int(g.n_assigned) - 3, (
            seed, int(res.n_assigned), int(g.n_assigned))


def test_auction_carry_fold_dense_and_scatter_paths_agree(monkeypatch):
    """The round body folds placements into the expanded carry tables via
    a dense [p, n, S] compare-and-reduce under DENSE_FOLD_BUDGET and a
    representative-row scatter + gather above it; both layouts must yield
    identical assignments (the budget is a cost knob, not semantics)."""
    import numpy as np
    from kubernetes_scheduler_tpu import ops
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    for seed in (1, 7):
        snap = gen_cluster(48, seed=seed, constraints=True)
        pods = gen_pods(40, seed=seed + 1, constraints=True)
        dense = schedule_batch(snap, pods, assigner="auction", normalizer="none")
        # the budget is read at trace time: clear the jit cache so the
        # patched value actually selects the scatter path
        monkeypatch.setattr(ops.assign, "DENSE_FOLD_BUDGET", 0)
        schedule_batch.clear_cache()
        scatter = schedule_batch(
            snap, pods, assigner="auction", normalizer="none"
        )
        monkeypatch.undo()
        schedule_batch.clear_cache()
        assert np.array_equal(
            np.asarray(dense.node_idx), np.asarray(scatter.node_idx)
        ), seed
        assert _final_affinity_violations(scatter.node_idx, snap, pods) == 0


def test_auction_spread_pods_one_per_domain():
    """Self-anti-affinity (pod matches its own anti selector): at most one
    per topology domain, even when all arrive in one window."""
    import numpy as np
    import jax.numpy as jnp
    from kubernetes_scheduler_tpu.engine import (
        make_pod_batch, make_snapshot, schedule_batch,
    )

    n, p, s = 8, 6, 2
    # two domains of 4 nodes each (representative rows 0 and 4)
    dom = np.repeat([0, 4], 4)[:, None] * np.ones((1, s), np.int32)
    snap = make_snapshot(
        allocatable=np.full((n, 3), 100.0, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.linspace(0, 40, n), cpu_pct=np.linspace(0, 90, n),
        mem_pct=np.zeros(n),
        domain_counts=np.zeros((n, s), np.float32),
        domain_id=dom.astype(np.int32),
    )
    matches = np.zeros((p, s), bool); matches[:, 0] = True
    pods = make_pod_batch(
        request=np.full((p, 3), 1.0, np.float32),
        anti_affinity_sel=np.full((p, 1), 0, np.int32),
        pod_matches=matches,
        priority=np.arange(p),
    )
    res = schedule_batch(snap, pods, assigner="auction", normalizer="none")
    idx = np.asarray(res.node_idx)
    placed = idx[idx >= 0]
    assert len(placed) == 2, idx  # one per domain
    assert len({0 if j < 4 else 1 for j in placed}) == 2
    assert _final_affinity_violations(res.node_idx, snap, pods) == 0
    # highest-priority pods won the two slots
    assert set(np.where(idx >= 0)[0]) == {p - 1, p - 2}, idx


def test_auction_spread_survives_negative_priority():
    """Survivor election in same-round conflict groups must work for
    negative scv/priority labels (rank-based int32 key, not raw priority)."""
    import numpy as np
    from kubernetes_scheduler_tpu.engine import (
        make_pod_batch, make_snapshot, schedule_batch,
    )

    n, p, s = 8, 6, 2
    dom = np.repeat([0, 4], 4)[:, None] * np.ones((1, s), np.int32)
    snap = make_snapshot(
        allocatable=np.full((n, 3), 100.0, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.linspace(0, 40, n), cpu_pct=np.linspace(0, 90, n),
        mem_pct=np.zeros(n),
        domain_counts=np.zeros((n, s), np.float32),
        domain_id=dom.astype(np.int32),
    )
    matches = np.zeros((p, s), bool); matches[:, 0] = True
    pods = make_pod_batch(
        request=np.full((p, 3), 1.0, np.float32),
        anti_affinity_sel=np.full((p, 1), 0, np.int32),
        pod_matches=matches,
        priority=np.arange(p) - 10,  # all negative
    )
    res = schedule_batch(snap, pods, assigner="auction", normalizer="none")
    idx = np.asarray(res.node_idx)
    placed = idx[idx >= 0]
    assert len(placed) == 2, idx
    assert _final_affinity_violations(res.node_idx, snap, pods) == 0


def test_auction_spread_hard_across_rounds():
    """Hard maxSkew must hold across AUCTION ROUNDS, not just within one.

    Repro for the cross-round carry bug: maxSkew=2, one matching pod lands
    in domain A in round 1 (capacity-1 node), two more pods are admitted
    to A's second node in round 2 — each individually legal (skew 1+1=2)
    but jointly skew 3. The round-conflict eviction must see prior rounds'
    `added` carry, elect one survivor, and re-route the other to domain B.
    Semantics: upstream PodTopologySpread DoNotSchedule.
    """
    from kubernetes_scheduler_tpu.ops.assign import AffinityState

    n, p, s = 4, 3, 1
    # nodes 0,1 = domain A (rep row 0); nodes 2,3 = domain B (rep row 2)
    aff = AffinityState(
        domain_counts=jnp.zeros((n, s), jnp.float32),
        domain_id=jnp.asarray([[0], [0], [2], [2]], jnp.int32),
        pod_matches=jnp.ones((p, s), bool),
        affinity_sel=jnp.full((p, 1), -1, jnp.int32),
        anti_affinity_sel=jnp.full((p, 1), -1, jnp.int32),
        avoid_counts=jnp.zeros((n, s), jnp.float32),
        pod_has_anti=jnp.zeros((p, s), bool),
        spread_sel=jnp.zeros((p, 1), jnp.int32),
        spread_max=jnp.full((p, 1), 2, jnp.int32),
        node_mask=jnp.ones((n,), bool),
    )
    # all pods prefer node0 > node1 > node2 > node3; node0 fits ONE pod,
    # so round 1 places only the top-priority pod there and rounds 2+ spill
    # the rest onto node1 (same domain) — the cross-round interaction.
    scores = jnp.tile(jnp.asarray([[10.0, 9.0, 5.0, 4.9]], jnp.float32), (p, 1))
    res = auction_assign(
        scores,
        jnp.ones((p, n), bool),
        jnp.ones((p, 1), jnp.float32),
        jnp.asarray([[1.0], [10.0], [10.0], [10.0]], jnp.float32),
        jnp.asarray([3, 2, 1], jnp.int32),
        jnp.ones((p,), bool),
        rounds=16,
        affinity=aff,
    )
    idx = np.asarray(res.node_idx)
    assert (idx >= 0).all(), idx  # domain B has room — nobody strands
    in_a = int((idx <= 1).sum())
    # skew = count(A) - count(B); placing 3 in A would be skew 3 > maxSkew 2
    assert in_a == 2, idx
