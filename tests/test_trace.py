"""Cycle flight recorder (trace/): journal robustness and replay parity.

Two property families:

Journal robustness — the on-disk format is crash-consistent: a
truncated or corrupt tail recovers to the last good record, a schema-
version skew is rejected with a clear error (never a guessed parse),
rotation keeps every file independently replayable (each opens with a
full snapshot), and the disk budget drops oldest files only.

Replay parity — a journal recorded from a sim-driven run replays with
ZERO binding diffs through every engine mode combination: Local/Remote
x serial/pipelined x full/resident, plus the multi-window backlog path.
This is what turns PARITY.md's bit-identical-bindings guarantees into a
tool: the replayer re-executes the exact recorded tensors, so any
divergence is a real parity break, not test noise."""

import os

import numpy as np
import pytest

from kubernetes_scheduler_tpu.host.scheduler import Scheduler
from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster, gen_host_pods
from kubernetes_scheduler_tpu.trace import inspect as tinspect
from kubernetes_scheduler_tpu.trace.recorder import (
    CycleRecorder,
    TraceVersionError,
    decode_record,
    encode_record,
    journal_files,
    read_journal,
)
from kubernetes_scheduler_tpu.trace.replay import replay_journal
from tests.test_pipeline import make_cfg


def record_workload(
    trace_path,
    *,
    constraints=False,
    n_nodes=24,
    n_pods=60,
    engine=None,
    **cfg_kw,
):
    """Drain a sim backlog with the recorder on; returns (bindings,
    scheduler)."""
    nodes, advisor = gen_host_cluster(n_nodes, seed=0, constraints=constraints)
    running: list = []
    cfg_kw.setdefault("batch_window", 16)
    sched = Scheduler(
        make_cfg(trace_path=str(trace_path), **cfg_kw),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        engine=engine,
    )
    for pod in gen_host_pods(n_pods, seed=1, constraints=constraints):
        sched.submit(pod)
    seen = 0
    for _ in range(64):
        if len(sched.queue) == 0 and sched._prefetched is None:
            break
        sched.run_cycle()
        for b in sched.binder.bindings[seen:]:
            running.append(b.pod)
        seen = len(sched.binder.bindings)
    sched.recorder.close()
    binds = [
        (b.pod.namespace, b.pod.name, b.node_name)
        for b in sched.binder.bindings
    ]
    return binds, sched


# ---- record encoding ------------------------------------------------------


def test_record_roundtrip_every_kind():
    rec = {
        "seq": 7,
        "path": "device",
        "wall_time": 123.25,
        "metrics": {"pods_bound": 3, "used_fallback": False},
        "pod_keys": [["default", "a"]],
        "assign": {"node_idx": np.array([2, -1], np.int32)},
    }
    got = decode_record(encode_record(rec))
    assert got["seq"] == 7 and got["path"] == "device"
    assert got["wall_time"] == 123.25
    assert got["metrics"] == rec["metrics"]
    assert got["pod_keys"] == [["default", "a"]]
    np.testing.assert_array_equal(got["assign"]["node_idx"], [2, -1])


def test_dtype_pin_rejected_never_raises(tmp_path):
    """A leaf whose dtype drifted from the schema pin is REJECTED (the
    record drops and counts) — and the recorder never raises into the
    scheduling loop."""
    rec = CycleRecorder(str(tmp_path / "j"))
    from kubernetes_scheduler_tpu.engine import PodBatch, make_pod_batch

    pods = make_pod_batch(np.zeros((2, 5), np.float32))
    pods = PodBatch(*[np.asarray(a) for a in pods])
    bad = pods._replace(request=np.zeros((2, 5), np.float64))
    from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil
    from kubernetes_scheduler_tpu.host.types import Node

    nodes = [Node(name="n0", allocatable={"cpu": 1.0, "pods": 10.0})]
    snap = SnapshotBuilder().build_snapshot(
        nodes, {"n0": NodeUtil()}, []
    )
    rec.record_cycle(
        path="device", metrics={}, snapshot=snap, pods=bad,
        node_idx=np.zeros(2, np.int32),
    )
    assert rec.records_dropped == 1 and rec.cycles_recorded == 0
    rec.record_cycle(
        path="device", metrics={}, snapshot=snap, pods=pods,
        node_idx=np.zeros(2, np.int32),
    )
    assert rec.cycles_recorded == 1
    rec.close()


# ---- journal robustness ---------------------------------------------------


def _recorded_journal(tmp_path, n_pods=60):
    path = tmp_path / "journal"
    binds, sched = record_workload(path)
    files = journal_files(str(path))
    assert len(files) == 1
    return str(path), files[0], binds


def test_truncated_tail_recovers(tmp_path):
    path, fp, _ = _recorded_journal(tmp_path)
    whole = list(read_journal(path))
    assert len(whole) >= 2
    # cut the file mid-way through the LAST record's payload
    size = os.path.getsize(fp)
    with open(fp, "r+b") as f:
        f.truncate(size - 37)
    got = list(read_journal(path))
    assert len(got) == len(whole) - 1
    assert [r["seq"] for r in got] == [r["seq"] for r in whole[:-1]]


def test_corrupt_tail_recovers(tmp_path):
    path, fp, _ = _recorded_journal(tmp_path)
    whole = list(read_journal(path))
    # flip one byte near the end (inside the last record's payload)
    with open(fp, "r+b") as f:
        f.seek(os.path.getsize(fp) - 5)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    got = list(read_journal(path))
    assert len(got) == len(whole) - 1
    # and the recovered prefix still replays clean
    rep = replay_journal(path)
    assert rep.binding_diffs == 0 and rep.replayed == len(got)


def test_version_skew_rejected(tmp_path):
    path, fp, _ = _recorded_journal(tmp_path)
    with open(fp, "r+b") as f:
        f.seek(4)  # after the 4-byte magic: the u16 version
        f.write((99).to_bytes(2, "little"))
    with pytest.raises(TraceVersionError, match="schema version 99"):
        list(read_journal(path))


def test_rotation_keeps_files_replayable(tmp_path):
    """Tiny per-file budget: the journal rotates mid-run, every file
    opens with a full snapshot (delta chains never cross files), and
    the whole journal still replays with zero diffs."""
    path = tmp_path / "journal"
    binds, sched = record_workload(
        path, n_pods=90, resident_state=True, pipeline_depth=1,
        trace_file_bytes=16_000, trace_max_bytes=10 << 20,
    )
    files = journal_files(str(path))
    assert len(files) >= 2, files
    # every file's FIRST device record carries a full snapshot — checked
    # per file by hard-linking it into a scratch journal directory
    for fp in files:
        sub = tmp_path / ("one_" + os.path.basename(fp))
        sub.mkdir()
        os.link(fp, sub / os.path.basename(fp))
        first_device = next(
            (
                r
                for r in read_journal(str(sub))
                if r.get("path") in ("device", "backlog")
            ),
            None,
        )
        if first_device is not None:
            assert "snapshot" in first_device, (
                "file's first device record must anchor the delta chain"
            )
    rep = replay_journal(str(path))
    assert rep.binding_diffs == 0 and rep.replayed >= 2


def test_torn_write_never_strands_later_records(tmp_path):
    """A transient IO failure mid-append (ENOSPC) may leave a torn
    frame; the writer truncates it away — or, if even that fails,
    poisons the file so the next append rotates. Either way records
    written AFTER the blip stay reachable (readers stop a file at the
    first bad frame)."""
    from unittest import mock

    from kubernetes_scheduler_tpu.trace.recorder import (
        JournalWriter,
        encode_record,
    )

    w = JournalWriter(str(tmp_path / "j"))
    w.append(encode_record({"seq": 0, "path": "scalar"}))
    real_write = w._f.write
    calls = {"n": 0}

    def bad_write(b):
        calls["n"] += 1
        if calls["n"] == 1:
            real_write(b[:3])  # torn frame header on disk
            raise OSError(28, "No space left on device")
        return real_write(b)

    with mock.patch.object(w._f, "write", side_effect=bad_write):
        with mock.patch.object(w._f, "truncate", side_effect=OSError(28, "")):
            with pytest.raises(OSError):
                w.append(encode_record({"seq": 1, "path": "scalar"}))
    assert w._torn  # could not truncate: poisoned, next append rotates
    w.append(encode_record({"seq": 2, "path": "scalar"}))
    w.close()
    assert [r["seq"] for r in read_journal(str(tmp_path / "j"))] == [0, 2]

    # the truncate-succeeds shape: same file keeps serving
    w2 = JournalWriter(str(tmp_path / "j2"))
    w2.append(encode_record({"seq": 0, "path": "scalar"}))
    real2 = w2._f.write
    calls2 = {"n": 0}

    def bad2(b):
        calls2["n"] += 1
        if calls2["n"] == 1:
            real2(b[:3])
            raise OSError(28, "No space left on device")
        return real2(b)

    with mock.patch.object(w2._f, "write", side_effect=bad2):
        with pytest.raises(OSError):
            w2.append(encode_record({"seq": 1, "path": "scalar"}))
    assert not w2._torn  # truncated clean
    w2.append(encode_record({"seq": 2, "path": "scalar"}))
    w2.close()
    assert [r["seq"] for r in read_journal(str(tmp_path / "j2"))] == [0, 2]
    from kubernetes_scheduler_tpu.trace.recorder import journal_files as jf

    assert len(jf(str(tmp_path / "j2"))) == 1  # no rotation needed


def test_disk_budget_drops_oldest(tmp_path):
    path = tmp_path / "journal"
    record_workload(
        path, n_pods=120, trace_file_bytes=12_000, trace_max_bytes=30_000,
    )
    files = journal_files(str(path))
    total = sum(os.path.getsize(fp) for fp in files)
    # budget enforced at rotation time: bounded, and the oldest file is
    # no longer index 0
    assert total <= 30_000 + 16_000
    assert os.path.basename(files[0]) != "journal-00000000.ytrj"
    # the surviving journal still reads and replays (later files anchor
    # their own chains)
    rep = replay_journal(str(path))
    assert rep.binding_diffs == 0


# ---- replay parity --------------------------------------------------------


def test_trace_stats_peak_selector_slots(tmp_path):
    """`trace stats` reports the widest selector table the run shipped
    (snapshot domain_counts / delta dom_vals widths) — the number a warm
    restart feeds to config.mirror_initial_selectors so the restarted
    builder starts past the early bucket-crossing flushes."""
    path = tmp_path / "journal"
    _, sched = record_workload(path, constraints=True, n_pods=90)
    st = tinspect.stats(str(path))
    assert st["peak_selector_slots"] == sched.builder._selector_slots()
    assert st["peak_selector_slots"] >= 2
    # a selector-free workload peaks at the width-1 padding table
    path2 = tmp_path / "journal-plain"
    record_workload(path2, n_pods=20)
    assert tinspect.stats(str(path2))["peak_selector_slots"] <= 1


def test_replay_parity_modes(tmp_path):
    """One recorded constraint workload replays with zero binding diffs
    through serial, pipelined, and resident local engines — and the
    replayed assignment count matches the recording."""
    path = tmp_path / "journal"
    binds, sched = record_workload(path, constraints=True, n_pods=90)
    assert len(binds) > 0
    st = tinspect.stats(str(path))
    assert st["by_path"].get("device", 0) >= 2
    for mode, resident in (
        ("serial", False), ("pipelined", False), ("serial", True),
        ("pipelined", True),
    ):
        rep = replay_journal(str(path), mode=mode, resident=resident)
        assert rep.binding_diffs == 0, (mode, resident, rep.to_dict())
        assert rep.replayed == st["by_path"]["device"]
        assert rep.pods_replayed == rep.pods_recorded


def test_replay_parity_resident_recorded_journal(tmp_path):
    """A journal recorded in resident mode carries deltas; replay folds
    them into the chain and still matches bitwise in every mode."""
    path = tmp_path / "journal"
    record_workload(path, n_pods=90, resident_state=True, pipeline_depth=1)
    st = tinspect.stats(str(path))
    assert st["delta_records"] >= 1, st
    for mode, resident in (("serial", False), ("pipelined", True)):
        rep = replay_journal(str(path), mode=mode, resident=resident)
        assert rep.binding_diffs == 0, (mode, resident, rep.to_dict())


def test_replay_parity_backlog_resident(tmp_path):
    """Deep-queue cycles (schedule_windows) record as backlog records
    and replay through the windows surface — including the windows-
    resident delta path (the ROADMAP follow-up satellite)."""
    path = tmp_path / "journal"
    binds, sched = record_workload(
        path, n_pods=120, max_windows_per_cycle=4, resident_state=True,
    )
    assert sched.totals["delta_uploads"] >= 1  # windows-resident engaged
    st = tinspect.stats(str(path))
    assert st["by_path"].get("backlog", 0) >= 2, st
    assert st["delta_records"] >= 1, st
    for resident in (False, True):
        rep = replay_journal(str(path), resident=resident)
        assert rep.binding_diffs == 0, rep.to_dict()


def test_replay_scalar_cycles_skipped(tmp_path):
    """A --no-tpu run records decision-only scalar records: replay
    skips them (nothing to re-execute) and reports zero diffs."""
    from kubernetes_scheduler_tpu.utils.config import FeatureGates

    path = tmp_path / "journal"
    binds, _ = record_workload(
        path, feature_gates=FeatureGates(tpu_batch_score=False),
    )
    assert len(binds) > 0
    rep = replay_journal(str(path))
    assert rep.replayed == 0 and rep.skipped >= 1
    assert rep.binding_diffs == 0


def test_trace_diff_of_two_identical_replays_is_zero(tmp_path):
    """The acceptance criterion: replay the same journal twice, record
    both replays, and `trace diff` reports zero differences."""
    path = tmp_path / "journal"
    record_workload(path, constraints=True, n_pods=90)
    out_a = str(tmp_path / "replay_a")
    out_b = str(tmp_path / "replay_b")
    rep_a = replay_journal(str(path), record_path=out_a)
    rep_b = replay_journal(str(path), mode="pipelined", record_path=out_b)
    assert rep_a.binding_diffs == 0 and rep_b.binding_diffs == 0
    report = tinspect.diff(out_a, out_b)
    assert report["differences"] == 0, report
    assert report["extra_records_a"] == 0 and report["extra_records_b"] == 0
    # and each replay also diffs clean against the original recording
    report = tinspect.diff(str(path), out_a)
    assert report["differences"] == 0, report


def test_inspect_path_is_engine_free(tmp_path):
    """`trace dump/stats/diff` must run on a laptop without jax: the
    read-only import path (package __init__ + inspect + recorder +
    schema) must not import the engine."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    probe = (
        "import sys\n"
        "from kubernetes_scheduler_tpu.trace import inspect as ti\n"
        "from kubernetes_scheduler_tpu.trace.recorder import read_journal\n"
        "assert 'jax' not in sys.modules, 'inspect path imported jax'\n"
        "assert 'kubernetes_scheduler_tpu.engine' not in sys.modules\n"
        "print('engine-free')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", probe],
        capture_output=True, text=True, timeout=120, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "engine-free" in proc.stdout


def test_diff_pairs_by_seq_after_head_prune(tmp_path):
    """A journal whose head file was pruned (disk budget / operator)
    diffs against the full original on the surviving overlap: extra
    records, ZERO differences — never a positional misalignment."""
    import shutil

    path = tmp_path / "journal"
    record_workload(
        path, n_pods=90, resident_state=True, pipeline_depth=1,
        trace_file_bytes=16_000, trace_max_bytes=10 << 20,
    )
    files = journal_files(str(path))
    assert len(files) >= 2
    pruned = tmp_path / "pruned"
    pruned.mkdir()
    for fp in files[1:]:
        shutil.copy(fp, pruned / os.path.basename(fp))
    report = tinspect.diff(str(path), str(pruned))
    assert report["differences"] == 0, report
    assert report["extra_records_a"] >= 1
    assert report["extra_records_b"] == 0
    assert report["records_compared"] >= 1
    # replaying the PRUNED journal preserves source seqs in the
    # re-recording, so it still pairs with its own replay exactly
    out = tmp_path / "pruned_replayed"
    rep = replay_journal(str(pruned), record_path=str(out))
    assert rep.binding_diffs == 0
    r2 = tinspect.diff(str(pruned), str(out))
    assert r2["differences"] == 0, r2
    assert r2["extra_records_a"] == 0 and r2["extra_records_b"] == 0


def test_seq_resumes_across_restart(tmp_path):
    """A scheduler restarted into the same --trace directory continues
    the seq sequence (like the file numbering): a reset to 0 would
    break `trace diff`'s merge-by-seq pairing, comparing only the first
    run and miscounting the rest as extras."""
    path = tmp_path / "journal"
    record_workload(path, n_pods=60)
    first = [r["seq"] for r in read_journal(str(path))]
    record_workload(path, n_pods=60)  # the "restart"
    seqs = [r["seq"] for r in read_journal(str(path))]
    assert len(seqs) == len(set(seqs)), seqs
    assert seqs == sorted(seqs)
    assert len(seqs) > len(first)
    # the spanning journal replays AND diffs clean against its replay
    out = str(tmp_path / "replayed")
    rep = replay_journal(str(path), record_path=out)
    assert rep.binding_diffs == 0
    report = tinspect.diff(str(path), out)
    assert report["differences"] == 0, report
    assert report["records_compared"] == len(seqs)
    assert report["extra_records_a"] == 0 and report["extra_records_b"] == 0


def test_diff_ignores_bind_outcomes(tmp_path):
    """`bindings` records bind-time outcomes (a live binder's 404/409
    drops), not decisions: two records agreeing on node_idx but
    differing in bindings diff clean."""
    a = tmp_path / "a"
    b = tmp_path / "b"
    idx = np.array([0, 1], np.int32)
    for path, bindings in (
        (a, [("default", "p0", "n0"), ("default", "p1", "n1")]),
        (b, [("default", "p0", "n0")]),  # p1 dropped by a 409 race
    ):
        rec = CycleRecorder(str(path))
        rec.record_cycle(
            path="scalar", metrics={},
            pod_keys=[("default", "p0"), ("default", "p1")],
            bindings=bindings, node_idx=idx,
        )
        rec.close()
    report = tinspect.diff(str(a), str(b))
    assert report["differences"] == 0, report


def test_recorder_metrics_on_exporter(tmp_path):
    from kubernetes_scheduler_tpu.host.observe import render_prometheus

    path = tmp_path / "journal"
    binds, sched = record_workload(path)
    window, totals = sched.metrics_snapshot()
    text = render_prometheus(
        window, totals,
        {
            "cycles_recorded_total": sched.recorder.cycles_recorded,
            "trace_bytes_total": sched.recorder.bytes_written,
            "trace_records_dropped_total": sched.recorder.records_dropped,
        },
    )
    assert "yoda_tpu_cycles_recorded_total" in text
    assert "yoda_tpu_trace_bytes_total" in text
    assert sched.recorder.cycles_recorded >= 1
    assert sched.recorder.bytes_written > 0


# ---- live sidecar ---------------------------------------------------------


def _with_sidecar(fn):
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server

    server, port, service = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    try:
        return fn(client, service)
    finally:
        client.close()
        server.stop(grace=None)


def test_replay_parity_live_sidecar(tmp_path):
    """Remote replay round-trips: the recorded journal re-executes
    through a live sidecar — plain, resident (delta uploads re-derived
    client-side), and the backlog/windows-resident surface gated on the
    HealthReply.windows_resident capability bit."""
    path = tmp_path / "journal"
    record_workload(path, constraints=True, n_pods=90)
    backlog_path = tmp_path / "backlog_journal"
    record_workload(
        backlog_path, n_pods=120, max_windows_per_cycle=4,
        resident_state=True,
    )

    def body(client, service):
        assert client.supports_windows_resident() is True
        rep = replay_journal(str(path), engine=client)
        assert rep.binding_diffs == 0, rep.to_dict()
        rep = replay_journal(str(path), engine=client, resident=True)
        assert rep.binding_diffs == 0, rep.to_dict()
        assert service.resident_deltas_served >= 1
        rep = replay_journal(str(backlog_path), engine=client, resident=True)
        assert rep.binding_diffs == 0, rep.to_dict()
        return service

    _with_sidecar(body)


def test_replay_backfills_pre_gang_pod_tensors():
    """Journals recorded before the gang fields existed decode to a
    PodBatch with the neutral no-gangs defaults; any OTHER missing leaf
    is schema drift and fails loud."""
    import numpy as np
    import pytest

    from kubernetes_scheduler_tpu.engine import PodBatch, make_pod_batch
    from kubernetes_scheduler_tpu.trace.recorder import TraceError
    from kubernetes_scheduler_tpu.trace.replay import pod_batch_from_record

    pods = make_pod_batch(request=np.ones((4, 3), np.float32))
    tensors = {
        name: np.asarray(a) for name, a in zip(PodBatch._fields, pods)
    }
    del tensors["gang_id"], tensors["gang_size"]
    out = pod_batch_from_record(tensors)
    assert np.array_equal(np.asarray(out.gang_id), np.full(4, -1, np.int32))
    assert np.array_equal(np.asarray(out.gang_size), np.zeros(4, np.int32))
    del tensors["priority"]
    with pytest.raises(TraceError, match="drift"):
        pod_batch_from_record(tensors)
