"""Learned policy as a first-class engine: distill → checkpoint → deploy.

The two-tower scorer trains against a heuristic teacher, round-trips
through an orbax checkpoint, and then schedules through the same
constraint/assignment machinery as every heuristic policy (LearnedEngine
→ engine.finish_cycle), including from the host loop via
policy="learned".
"""

import functools

import numpy as np
import jax
import pytest

from kubernetes_scheduler_tpu.engine import compute_scores, schedule_batch
from kubernetes_scheduler_tpu.models.learned import (
    LearnedEngine,
    init_train_state,
    load_learned_engine,
    make_features,
    restore_checkpoint,
    save_checkpoint,
    train_step,
)
from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods


def _train(steps=30, n=32, p=8, seed=0):
    snap = gen_cluster(n, seed=seed)
    pods = gen_pods(p, seed=seed + 1)
    pod_x, node_x = make_features(snap, pods)
    teacher = compute_scores(snap, pods, "balanced_cpu_diskio")
    state, model, tx = init_train_state(jax.random.key(0))
    step = jax.jit(functools.partial(train_step, model=model, tx=tx))
    losses = []
    for _ in range(steps):
        state, loss = step(
            state, pod_x=pod_x, node_x=node_x, teacher_scores=teacher,
            node_mask=snap.node_mask, pod_mask=pods.pod_mask,
        )
        losses.append(float(loss))
    return state, model, losses, (snap, pods)


def test_distillation_reduces_loss_and_checkpoint_roundtrips(tmp_path):
    state, model, losses, _ = _train()
    assert losses[-1] < losses[0] * 0.9, losses[:3] + losses[-3:]

    path = str(tmp_path / "ckpt")
    save_checkpoint(path, state)
    like, _, _ = init_train_state(jax.random.key(1), model=model)
    restored = restore_checkpoint(path, like)
    assert int(restored.step) == int(state.step)
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_learned_engine_schedules_with_full_constraints(tmp_path):
    state, model, _, _ = _train(steps=5)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, state)
    engine = load_learned_engine(path)

    snap = gen_cluster(48, seed=7, constraints=True)
    pods = gen_pods(16, seed=8, constraints=True)
    res = engine.schedule_batch(snap, pods, assigner="greedy")
    idx = np.asarray(res.node_idx)
    feasible = np.asarray(res.feasible)
    # bindings valid and feasibility (incl. taints/affinity) respected —
    # identical machinery to the heuristic engine
    base = schedule_batch(snap, pods)
    np.testing.assert_array_equal(feasible, np.asarray(base.feasible))
    for i, j in enumerate(idx):
        if j >= 0:
            assert feasible[i, j]


def test_host_loop_policy_learned():
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.host.types import Container, Node, Pod
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes = [
        Node(name=f"n{i}", allocatable={"cpu": 8000.0, "memory": 32 * 2**30,
                                        "pods": 110})
        for i in range(5)
    ]

    class A:
        def fetch(self):
            return {nd.name: NodeUtil(cpu_pct=10.0 * i, disk_io=2.0 * i)
                    for i, nd in enumerate(nodes)}

    cfg = SchedulerConfig(policy="learned", min_device_work=0)
    cfg.feature_gates.native_host = False
    s = Scheduler(cfg, advisor=A(), list_nodes=lambda: nodes,
                  list_running_pods=lambda: [])
    assert isinstance(s.engine, LearnedEngine)
    for i in range(6):
        s.submit(Pod(name=f"p{i}", containers=[Container(requests={"cpu": 400.0})]))
    m = s.run_cycle()
    assert m.pods_bound == 6 and not m.used_fallback


def test_learned_windows_matches_sequential_batches():
    """LearnedEngine.schedule_windows (the backlog surface) makes the
    same decisions as per-window schedule_batch with capacity and
    affinity carried on the host — mirroring the dense engine's
    windows-vs-sequential parity."""
    import jax.numpy as jnp
    from kubernetes_scheduler_tpu.engine import stack_windows

    state, model, _, _ = _train(steps=3)
    engine = LearnedEngine(state.params, model=model)

    snap = gen_cluster(32, seed=9, constraints=True)
    pods = gen_pods(16, seed=10, constraints=True)
    windows = stack_windows(pods, 4)
    fused = engine.schedule_windows(snap, windows, assigner="greedy",
                                    normalizer="none")

    from kubernetes_scheduler_tpu.engine import fold_window_counts

    requested = snap.requested
    dc, ac = snap.domain_counts, snap.avoid_counts
    seq_idx, total = [], 0
    for w in range(4):
        one = type(pods)(*[jnp.asarray(f)[w] for f in windows])
        res = engine.schedule_batch(
            snap._replace(requested=requested, domain_counts=dc,
                          avoid_counts=ac),
            one, assigner="greedy", normalizer="none",
        )
        requested = snap.allocatable - res.free_after
        dc, ac = fold_window_counts(snap, one, res.node_idx, dc, ac)
        seq_idx.append(np.asarray(res.node_idx))
        total += int(res.n_assigned)

    np.testing.assert_array_equal(np.asarray(fused.node_idx), np.stack(seq_idx))
    assert int(fused.n_assigned) == total


def test_host_backlog_policy_learned():
    """Deep queues under policy='learned' use the windows surface the
    engine now serves (one dispatch), not the 8x single-batch path."""
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.host.types import Container, Node, Pod
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes = [
        Node(name=f"n{i}", allocatable={"cpu": 8000.0, "memory": 32 * 2**30,
                                        "pods": 110})
        for i in range(5)
    ]

    class A:
        def fetch(self):
            return {nd.name: NodeUtil(cpu_pct=10.0 * i, disk_io=2.0 * i)
                    for i, nd in enumerate(nodes)}

    cfg = SchedulerConfig(policy="learned", min_device_work=0,
                          batch_window=8, adaptive_dispatch=False)
    cfg.feature_gates.native_host = False
    s = Scheduler(cfg, advisor=A(), list_nodes=lambda: nodes,
                  list_running_pods=lambda: [])
    assert s._engine_windows_ok
    for i in range(20):
        s.submit(Pod(name=f"p{i}", containers=[Container(requests={"cpu": 400.0})]))
    m = s.run_cycle()
    assert m.pods_in == 20 and m.pods_bound == 20 and not m.used_fallback


def test_sharded_learned_matches_dense():
    """The two-tower policy on the 8-device mesh: node tower is
    node-local, so the scorer shards with no extra collectives — the
    sharded engine must reproduce the dense LearnedEngine decisions,
    single-window and whole-backlog."""
    import jax
    from kubernetes_scheduler_tpu.engine import stack_windows
    from kubernetes_scheduler_tpu.models.learned import make_sharded_learned_fn
    from kubernetes_scheduler_tpu.parallel.mesh import make_mesh

    assert jax.device_count() == 8
    state, model, _, _ = _train(steps=3)
    engine = LearnedEngine(state.params, model=model)
    mesh = make_mesh(8)

    snap = gen_cluster(32, seed=11, constraints=True)
    pods = gen_pods(12, seed=12, constraints=True)

    dense = engine.schedule_batch(snap, pods, assigner="greedy",
                                  normalizer="min_max")
    fn = make_sharded_learned_fn(state.params, mesh, model=model)
    sharded = fn(snap, pods)
    np.testing.assert_array_equal(
        np.asarray(sharded.node_idx), np.asarray(dense.node_idx)
    )

    windows = stack_windows(pods, 4)
    dense_w = engine.schedule_windows(snap, windows, assigner="greedy",
                                      normalizer="min_max")
    wfn = make_sharded_learned_fn(state.params, mesh, model=model,
                                  windows=True)
    sharded_w = wfn(snap, windows)
    np.testing.assert_array_equal(
        np.asarray(sharded_w.node_idx), np.asarray(dense_w.node_idx)
    )
    assert int(sharded_w.n_assigned) == int(dense_w.n_assigned)


def test_sharded_learned_auction_matches_dense():
    """The learned scorer composes with the distributed AUCTION assigner
    (the factory kwargs flow through make_sharded_learned_fn) — dense
    LearnedEngine auction decisions reproduced on the mesh."""
    import jax
    from kubernetes_scheduler_tpu.models.learned import make_sharded_learned_fn
    from kubernetes_scheduler_tpu.parallel.mesh import make_mesh

    assert jax.device_count() == 8
    state, model, _, _ = _train(steps=3)
    engine = LearnedEngine(state.params, model=model)
    snap = gen_cluster(32, seed=13, constraints=True)
    pods = gen_pods(10, seed=14, constraints=True)
    dense = engine.schedule_batch(
        snap, pods, assigner="auction", normalizer="min_max"
    )
    fn = make_sharded_learned_fn(
        state.params, make_mesh(8), model=model, assigner="auction"
    )
    sharded = fn(snap, pods)
    np.testing.assert_array_equal(
        np.asarray(sharded.node_idx), np.asarray(dense.node_idx)
    )


def test_unknown_policy_still_rejected():
    with pytest.raises(ValueError, match="unknown policy"):
        schedule_batch(gen_cluster(8, seed=0), gen_pods(2, seed=1),
                       policy="nope")
