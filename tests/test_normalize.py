"""NormalizeScore parity tests (pkg/yoda/scheduler.go:158-183)."""

import numpy as np
import jax.numpy as jnp

from kubernetes_scheduler_tpu.ops import min_max_normalize, softmax_normalize
from tests import oracle


def run(scores, n_valid=None):
    scores = np.asarray(scores, np.float32)[None, :]
    n = scores.shape[1] if n_valid is None else n_valid
    mask = np.arange(scores.shape[1]) < n
    return np.asarray(min_max_normalize(jnp.asarray(scores), jnp.asarray(mask)))[0]


def test_basic_rescale():
    s = [3.0, 7.0, 5.0, 9.0]
    np.testing.assert_allclose(run(s), oracle.normalize_oracle(s), rtol=1e-6)


def test_equal_scores_guard():
    # highest == lowest => lowest-- => every node gets exactly 100
    s = [4.0, 4.0, 4.0]
    got = run(s)
    assert got.tolist() == [100.0, 100.0, 100.0]
    assert oracle.normalize_oracle(s) == [100.0, 100.0, 100.0]


def test_highest_seeded_at_zero():
    # Reference seeds highest=0 (scheduler.go:162): all-negative scores
    # normalize against 0, not their own max.
    s = [-5.0, -1.0, -3.0]
    np.testing.assert_allclose(run(s), oracle.normalize_oracle(s), rtol=1e-6)


def test_padding_excluded():
    s = np.array([3.0, 7.0, 5.0, 999.0, -999.0])
    got = run(s, n_valid=3)
    np.testing.assert_allclose(got[:3], oracle.normalize_oracle([3.0, 7.0, 5.0]), rtol=1e-6)
    assert got[3] == 0.0 and got[4] == 0.0


def test_softmax_masked():
    s = jnp.asarray([[1.0, 2.0, 3.0, 50.0]])
    mask = jnp.asarray([True, True, True, False])
    p = np.asarray(softmax_normalize(s, mask))[0]
    np.testing.assert_allclose(p.sum(), 1.0, rtol=1e-6)
    assert p[3] < 1e-12
    assert p[2] > p[1] > p[0]
