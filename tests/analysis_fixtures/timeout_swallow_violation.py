"""graftlint fixture: boundary-call exception swallows (never imported).

The RemoteEngine.healthy() bug class: an external call (timeout-
disciplined, even) whose broad except handler swallows the failure
without counting a metric or feeding the circuit breaker — the outage
stays invisible to dashboards and the breaker never trips.
"""

import urllib.request


class Probe:
    def health(self, url):
        try:
            return urllib.request.urlopen(url, timeout=2.0).read()
        except Exception:  # LINE 16: swallowed, no metric, no breaker
            return None

    def poll(self, stub):
        try:
            return stub.call(timeout=1.0)
        except:  # LINE 22: bare swallow on a boundary call  # noqa: E722
            pass
