"""graftlint fixture: pallas-vmem violations (never imported, only parsed)."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_P = 256
BAD_TILE = 100      # not a multiple of 128
HUGE_TILE = 4096


def _bad_kernel(x_ref, y_ref, out_ref):
    jax.debug.print("x = {}", x_ref[0, 0])  # LINE 16: host callback in body
    print("tracing")                        # LINE 17: host callback in body
    acc = jnp.zeros((8, 128), dtype=jnp.bfloat16)  # LINE 18: bf16 accumulator
    acc = acc + x_ref[...].astype("bfloat16")      # LINE 19: bf16 accumulate
    out_ref[...] = (acc + y_ref[...]).astype(jnp.float32)


def _binop_kernel(x_ref, out_ref):
    out_ref[...] = x_ref[...]


def bad_binop_call(x):
    return pl.pallas_call(
        _binop_kernel,
        out_shape=jax.ShapeDtypeStruct((8, 192), jnp.float32),
        grid=(1, 1),
        in_specs=[
            # LINE: minor axis 64 * 3 = 192, resolved through the BinOp
            # arithmetic the fused megakernel's stacked-row shapes use —
            # not a multiple of 128
            pl.BlockSpec((8, 64 * 3), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((8, 64 * 3), lambda i, j: (i, j)),
    )(x)


def bad_call(x, y):
    return pl.pallas_call(
        functools.partial(_bad_kernel),
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
        grid=(x.shape[0] // TILE_P, 1),
        in_specs=[
            # LINE 29: minor axis 100 does not divide the lane padding
            pl.BlockSpec((TILE_P, BAD_TILE), lambda i, j: (i, j)),
            # LINE 31: 4096 x 4096 x 4B = 64 MB >> VMEM
            pl.BlockSpec((HUGE_TILE, HUGE_TILE), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((TILE_P, 128), lambda i, j: (i, j)),
    )(x, y)
