"""Fixture: every span-hygiene failure mode, one emission site each."""

import time


class SpanSet:  # stand-in for observe.SpanSet
    def add(self, name, t0, t1, **args):
        pass

    def span(self, name, **args):
        pass


class Driver:
    def _span(self, name, t0, t1=None, **args):
        pass

    def cycle(self, ss: SpanSet):
        t0 = time.perf_counter()
        # fine on its own (registered below)
        self._span("queue_pop", t0)
        # emitted but missing from SHIPPED_SPANS — an unregistered stage
        # the attribution table and dashboards never hear about
        self._span("mystery_stage", t0)
        ss.add("orphan_stage", t0, t0 + 1.0)
        # not lower_snake_case — renamed stages silently drop out of
        # every report keyed on the old name
        with ss.span("Bind-Phase"):
            pass
        ss.add("cycle", t0, t0 + 3.0, path="serial")
        # ordinary set.add / two-arg adds must NOT match the pattern
        seen = set()
        seen.add("not_a_span")


SHIPPED_SPANS = (
    "queue_pop",
    "cycle",
    # registered twice
    "cycle",
    # shipped once, no longer emitted anywhere — the removal the rule
    # exists to catch
    "removed_stage",
)
