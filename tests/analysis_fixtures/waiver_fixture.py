"""graftlint fixture: waiver mechanics (never imported)."""

import subprocess


def waived_inline():
    subprocess.run(["make"], check=True)  # graftlint: disable=timeout-hygiene -- CI harness bounds the build


def waived_preceding_line():
    # graftlint: disable=timeout-hygiene -- one-shot tool, bounded by caller
    subprocess.run(["make"], check=True)


def bad_waiver_no_reason():
    subprocess.run(["make"], check=True)  # graftlint: disable=timeout-hygiene


def wrong_rule_waived():
    subprocess.run(["make"], check=True)  # graftlint: disable=jit-purity -- waives the wrong rule
