"""thread-race clean fixture: the same worker/main shape with every
pair discharged — a common lock, publish-before-start, an
Event.set()/wait() pairing, a lock-covered latch, and a join before
the shutdown read."""

import threading

_LOCK = threading.Lock()
COUNTER = 0


def bump():
    global COUNTER
    with _LOCK:
        COUNTER = COUNTER + 1


def reset():
    global COUNTER
    with _LOCK:
        COUNTER = 0


class Pump:
    def __init__(self):
        self.rows = []
        self.total = 0
        self.cache = None
        self.limit = 0
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._t = None

    def start(self):
        # published BEFORE start(): visible to the spawned worker
        self.limit = 4
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        # write-then-set: the Event publishes `total` to the waiter
        self.total = self.limit
        self._ready.set()
        for i in range(4):
            self.ensure()
            with self._lock:
                self.rows.append(i)
            bump()

    def ensure(self):
        with self._lock:
            if self.cache is None:
                self.cache = {}
            return self.cache

    def read(self):
        self._ready.wait()
        total = self.total
        with self._lock:
            n = len(self.rows)
        return n, total

    def close(self):
        if self._t is not None:
            self._t.join(timeout=1.0)
        return self.rows


def drive():
    reset()
    p = Pump()
    p.start()
    p.ensure()
    n, total = p.read()
    return n, total, p.close(), COUNTER
