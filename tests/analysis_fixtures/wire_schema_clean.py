"""graftlint fixture: wire-schema-conformant usage of fixture.proto."""

from tests.analysis_fixtures import fixture_pb2 as pb


def send(req: pb.Ping):
    req.seq = 7
    req.payload.append(1)
    copy = pb.Ping(name="x", seq=2)
    copy.CopyFrom(req)  # protobuf runtime API: fine
    return copy.SerializeToString()


def receive(data):
    reply = pb.Pong()
    reply.ParseFromString(data)
    return reply
