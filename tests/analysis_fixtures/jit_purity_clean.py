"""graftlint fixture: clean jit code — no false positives expected."""

import functools

import jax
import jax.numpy as jnp


def pure_helper(x):
    acc = {}
    acc["scaled"] = x * 2.0  # local mutation: fine
    return acc["scaled"]


@functools.partial(jax.jit, static_argnames=("k",))
def kernel(x, *, k=1):
    y = pure_helper(x)
    return jnp.where(y > 0, y, 0.0) * k


def host_only_reporting(result):
    # impure, but NOT reachable from any jit entry point
    print("cycle done:", result)
    return result
