"""graftlint fixture: pallas-vmem per-shard block dims under shard_map
(violating half — never imported, only parsed).

A kernel invoked inside a shard_map body tiles the PER-SHARD node
axis: the global node count divided by the mesh size BEFORE tiling.
The rule must resolve the floor division and check the per-shard
dimension — here 512 // 8 = 64, not a multiple of 128, which forces a
ragged relayout on every grid step on hardware while "working" under
the interpreter."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

N_NODES = 512
MESH_DEVICES = 8


def _score_kernel(x_ref, out_ref):
    out_ref[...] = x_ref[...] * 2.0


def sharded_launch(x):
    # per-shard node axis: 512 // 8 = 64 — NOT lane-aligned
    n_local = N_NODES // MESH_DEVICES
    return pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((8, n_local), jnp.float32),
        grid=(1, 1),
        in_specs=[pl.BlockSpec((8, n_local), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, N_NODES // MESH_DEVICES), lambda i, j: (i, j)),
    )(x)
