"""Cross-file thread-race fixture, file B: spawns a worker that writes
file A's Registry while the main thread reads it — no lock, no
happens-before edge. The finding must land on file A (where the
accesses live) even though the threading is declared here."""

import threading

from thread_race_xfile_state import Registry


class Loader:
    def __init__(self):
        self.reg = Registry()
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._fill, daemon=True)
        self._t.start()

    def _fill(self):
        for i in range(3):
            self.reg.put("k%d" % i, i)
        self.reg.freeze()

    def read(self):
        return self.reg.dump()


def drive():
    ld = Loader()
    ld.start()
    return ld.read()
