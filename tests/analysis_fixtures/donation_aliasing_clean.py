"""graftlint fixture: disciplined donated-buffer use (never imported)."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def apply_delta(state, rows, vals):
    return state.at[rows].set(vals, mode="drop")


def cycle(state, rows, vals):
    # idiomatic donation: rebind the result to the donated name, so the
    # only live reference is the output aliasing the donated storage
    state = apply_delta(state, rows, vals)
    return state * 2


def cycle_reads_before(state, rows, vals):
    total = state.sum()  # reads BEFORE the donation are fine
    state = apply_delta(state, rows, vals)
    return state + total


def cycle_exclusive_arms(state, rows, vals, flag):
    if flag:
        out = apply_delta(state, rows, vals)
        return out
    # the other arm of the branch: the donation never executed on this
    # control path, so this read is fine
    return state.sum()


def cycle_attribute_rebind(st, rows, vals):
    # the resident-state idiom: donate the retained attribute chain and
    # rebind it before anything can read the dead tree
    st.snapshot = apply_delta(st.snapshot, rows, vals)
    return st.snapshot


def cycle_multiline_call(state, rows, vals):
    # the donating call spans lines: the argument load on line 2 of the
    # call is the donation itself, not a re-read
    state = apply_delta(
        state, rows, vals,
    )
    return state
