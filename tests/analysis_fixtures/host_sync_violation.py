"""graftlint fixture: host-sync violations (never imported, only parsed)."""

import jax
import numpy as np


def apply_results(window, res):
    jax.block_until_ready(res)  # LINE 8: device barrier in the cycle path
    out = []
    for i in range(len(window)):
        out.append(np.asarray(res.node_idx)[i])  # LINE 11: asarray per element
    scores = [s.item() for s in res.scores]  # LINE 12: item per element
    return out, scores
