"""graftlint fixture: lock-discipline violation (never imported)."""

import threading


class SharedCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._store = {}
        self.hits = 0

    def put(self, key, value):
        with self._lock:
            self._store[key] = value

    def drop(self, key):
        # LINE 18: `_store` is lock-guarded in put(), mutated bare here
        self._store.pop(key, None)

    def bump_hits(self):
        self.hits += 1  # never guarded anywhere: not a violation
