"""graftlint fixture: fully-wired capability bits (never imported, only
parsed). The sibling fixture.proto's HealthReply declares cap_a and
cap_b; both ride the canonical tables end to end — probe and
invalidate are table-driven, every latch has an accessor, every switch
is assigned, every direct sender reaches _invalidate_session.
"""


class EngineUnavailable(RuntimeError):
    pass


CAPABILITY_LATCHES = {
    "cap_a": "_cap_a",
    "cap_b": "_cap_b",
}


class WiredClient:
    def __init__(self, target):
        self._target = target
        self._cap_a = None
        self._cap_b = None
        self._wire_cache = {}

    def _probe_capabilities(self):
        info = self.health_info()
        if info is not None:
            for fieldname, attr in CAPABILITY_LATCHES.items():
                if getattr(self, attr) is None:
                    setattr(self, attr, bool(getattr(info, fieldname, False)))

    def _invalidate_session(self):
        self._wire_cache.clear()
        for attr in CAPABILITY_LATCHES.values():
            setattr(self, attr, None)

    def health_info(self):
        return None

    def supports_a(self):
        if self._cap_a is None:
            self._probe_capabilities()
        return bool(self._cap_a)

    def supports_b(self):
        if self._cap_b is None:
            self._probe_capabilities()
        return bool(self._cap_b)

    def preempt(self, request):
        try:
            return self._call_with_retry(self._target, request)
        except EngineUnavailable:
            self._invalidate_session()
            raise

    def _call_with_retry(self, method, request):
        raise EngineUnavailable(method)


CAPABILITY_SWITCHES = {
    "cap_a": "cap_a_enabled",
    "cap_b": "cap_b_enabled",
}


class WiredServer:
    def __init__(self):
        self.cap_a_enabled = True
        self.cap_b_enabled = False
        self.cycles_served = 0

    def health(self, request, context):
        caps = {
            fieldname: bool(getattr(self, attr))
            for fieldname, attr in CAPABILITY_SWITCHES.items()
        }
        return dict({"status": "SERVING"}, **caps)
