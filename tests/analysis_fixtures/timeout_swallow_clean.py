"""graftlint fixture: boundary-call failures accounted for.

Every broad handler around an external call either counts a metric,
feeds the circuit breaker, bumps a counter attribute, or re-raises —
and narrow catches routed into classification pass untouched.
"""

import urllib.request


class Probe:
    def __init__(self, breaker, ctr):
        self.breaker = breaker
        self.ctr = ctr
        self.failures = 0

    def health(self, url):
        try:
            return urllib.request.urlopen(url, timeout=2.0).read()
        except Exception:
            self.ctr.inc(kind="transport")
            self.breaker.record_failure()
            return None

    def poll(self, stub):
        try:
            return stub.call(timeout=1.0)
        except Exception:
            self.failures += 1  # counter bump accounts for it
            return None

    def strict(self, stub):
        try:
            return stub.call(timeout=1.0)
        except Exception:
            raise  # re-raise: the caller's path owns the accounting

    def narrow(self, stub, errors):
        try:
            return stub.call(timeout=1.0)
        except ValueError:  # narrow catch: not a blanket swallow
            return None
