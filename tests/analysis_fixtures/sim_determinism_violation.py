"""Fixture: every unseeded-randomness shape the sim-determinism family
flags."""

import random

import numpy as np
from numpy.random import default_rng


def gen_cluster(n):
    util = np.random.random(n)              # global numpy RNG
    np.random.seed(0)                       # seeding the global is still global
    jitter = np.random.uniform(0, 1, n)     # global numpy RNG again
    rng = default_rng()                     # unseeded: fresh OS entropy
    rng2 = np.random.default_rng()          # unseeded, dotted form
    pick = random.choice([1, 2, 3])         # stdlib global RNG
    return util, jitter, rng, rng2, pick
