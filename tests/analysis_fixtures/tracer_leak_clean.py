"""graftlint fixture: stores the tracer-leak family must NOT flag
(never imported) — jax functional updates, trace-local accumulators,
and host-constant stores."""

import jax
import jax.numpy as jnp


@jax.jit
def functional_update(state, rows, vals):
    # `.at[...].set/add` builds a NEW array — jax's functional update,
    # not a store through the argument
    return state.at[rows].add(vals)


@jax.jit
def entry(counts, x):
    acc = []
    return _accumulate(acc, counts, x)


def _accumulate(acc, counts, x):
    # bare-list accumulator passed between kernel helpers: trace-LOCAL,
    # consumed before the trace ends (the ops/assign _affinity_update
    # pattern) — never flagged
    acc.append(counts * x)
    return jnp.stack(acc)


@jax.jit
def constant_store(cfg, x):
    cfg.shape_hint = (4, 8)  # host constant, not a tracer
    return x * 2


def host_only(store, x):
    # not jit-reachable: host code mutates freely
    store.cache = x
    return x


def _pallas_kernel(x_ref, out_ref):
    # a Pallas KERNEL's calling convention IS mutating its Ref
    # arguments — out_ref[...] = value is the kernel's output surface,
    # not a tracer escaping into host state; kernels (detected from the
    # module's pallas_call sites, functools.partial unwrapped) are
    # exempt
    out_ref[...] = jnp.exp(x_ref[...])


@jax.jit
def run_kernel(x):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _pallas_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
