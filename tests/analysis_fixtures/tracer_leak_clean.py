"""graftlint fixture: stores the tracer-leak family must NOT flag
(never imported) — jax functional updates, trace-local accumulators,
and host-constant stores."""

import jax
import jax.numpy as jnp


@jax.jit
def functional_update(state, rows, vals):
    # `.at[...].set/add` builds a NEW array — jax's functional update,
    # not a store through the argument
    return state.at[rows].add(vals)


@jax.jit
def entry(counts, x):
    acc = []
    return _accumulate(acc, counts, x)


def _accumulate(acc, counts, x):
    # bare-list accumulator passed between kernel helpers: trace-LOCAL,
    # consumed before the trace ends (the ops/assign _affinity_update
    # pattern) — never flagged
    acc.append(counts * x)
    return jnp.stack(acc)


@jax.jit
def constant_store(cfg, x):
    cfg.shape_hint = (4, 8)  # host constant, not a tracer
    return x * 2


def host_only(store, x):
    # not jit-reachable: host code mutates freely
    store.cache = x
    return x
