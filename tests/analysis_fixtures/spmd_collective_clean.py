"""spmd-collective clean fixture: the sanctioned SPMD idioms stay
quiet.

Mirrors the real sharded engine's patterns — psum of sharded partial
sums, `psum(1, axes)` as the device-count idiom, the all_gather
candidate election of a genuinely varying local best, the pcast-varying
carry, and the pmax-over-equal discharge that establishes the
replication `out_specs` declares. AST-only: never imported, only
parsed.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

NODE_AXIS = "node"


def make_mesh():
    return Mesh(np.asarray(jax.devices()), (NODE_AXIS,))


def make_stats_fn(mesh):
    def body(x, w):
        # psum of a SHARDED partial sum: the canonical global reduction
        total = jax.lax.psum(x.sum(), NODE_AXIS)
        # psum of a literal is the sanctioned device-count idiom
        n_dev = jax.lax.psum(1, NODE_AXIS)
        mean = total / (n_dev * x.shape[0])
        # global bounds via pmax/pmin of shard-local extrema
        hi = jax.lax.pmax(x.max(), NODE_AXIS)
        lo = jax.lax.pmin(x.min(), NODE_AXIS)
        # the replicated pod weights scale shard-local columns — no
        # collective needed, none used
        scaled = (x - mean) * w.sum()
        return scaled / jnp.maximum(hi - lo, 1e-6)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(NODE_AXIS), P()),
        out_specs=P(NODE_AXIS),
    )


def make_election_fn(mesh):
    def body(x):
        # the engine's candidate-election shape: gather the VARYING
        # (shard-local) best with its global index, then pick
        # identically on every shard
        n_local = x.shape[0]
        offset = jax.lax.axis_index(NODE_AXIS).astype(jnp.int32) * n_local
        local_best = x.max()
        local_arg = jnp.argmax(x).astype(jnp.int32) + offset
        cand_s = jax.lax.all_gather(local_best, NODE_AXIS)
        cand_i = jax.lax.all_gather(local_arg, NODE_AXIS)
        chosen = cand_i[jnp.argmax(cand_s)]
        # pmax over equal values is the identity: the sanctioned
        # discharge that makes the declared replication provable
        chosen = jax.lax.pmax(chosen, NODE_AXIS)
        return chosen

    return shard_map(body, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P())


def make_cond_fn(mesh):
    def body(x, w):
        # lax.cond over a SHARDED operand: the branch bodies see x as
        # sharded (operands start after the predicate and the two
        # branch functions) — the psum inside is a legitimate global
        # reduction, not a double-count
        def reduce_all(v):
            return jax.lax.psum(v.sum(), NODE_AXIS)

        def reduce_weighted(v):
            return jax.lax.psum((v * v).sum(), NODE_AXIS)

        total = jax.lax.cond(
            w.sum() > 0.0, reduce_all, reduce_weighted, x
        )
        return total

    return shard_map(
        body, mesh=mesh, in_specs=(P(NODE_AXIS), P()), out_specs=P(),
    )


def _global_kw_sum(*, v):
    # a keyword-only SHARDED operand: the psum is a legitimate global
    # reduction — the binding must ride the call's keyword, never fall
    # through to an unmatched-parameter default
    return jax.lax.psum(v.sum(), NODE_AXIS)


def make_kwarg_fn(mesh):
    def body(x):
        return _global_kw_sum(v=x)

    return shard_map(body, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P())


def make_walrus_fn(mesh):
    def body(x):
        # a walrus-bound SHARDED partial sum: the later psum is a
        # legitimate global reduction, not a double-count (the binding
        # must be tracked, not defaulted to host-config/replicated)
        total = jax.lax.psum((partial := x.sum()), NODE_AXIS)
        scaled = jax.lax.psum(partial * 2.0, NODE_AXIS)
        # axis_size is the same integer on every shard — dividing a
        # replicated total by it stays replicated under out_specs P()
        n_dev = jax.lax.axis_size(NODE_AXIS)
        return (total + scaled) / n_dev

    return shard_map(body, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P())


def make_scan_fn(mesh):
    def body(x, order):
        def step(carry, i):
            row = x * carry
            best = jax.lax.all_gather(row.max(), NODE_AXIS).max()
            return carry + best, best

        carry, picks = jax.lax.scan(step, jnp.float32(0.0), order)
        return picks

    return shard_map(
        body, mesh=mesh, in_specs=(P(NODE_AXIS), P()), out_specs=P(),
    )
