"""graftlint fixture (cross-file half): donation through an IMPORTED
helper — invisible to any per-file scan, caught by the project call
graph + donation-summary fixpoint. Lint with donation_helper_mod.py."""

from donation_helper_mod import apply_delta, fold


def cycle_through_helper(snap, delta):
    new = fold(snap, delta)   # `fold` donates arg 0 transitively
    return new + snap.sum()   # re-read after the helper's donation


def cycle_direct_import(snap, delta):
    new = apply_delta(snap, delta)  # donor imported from another module
    return new, snap.mean()         # re-read


def clean_through_helper(snap, delta):
    snap = fold(snap, delta)  # rebind clears — stays quiet
    return snap
