"""Fixture: every metric-hygiene failure mode, one surface each."""


class Histogram:  # stand-in for observe.Histogram
    def __init__(self, name, help="", labels=()):
        pass


class Counter(Histogram):
    pass


class Gauge(Histogram):
    pass


_HELP = {
    # no unit suffix
    "queue_depth": "Pods waiting in the scheduling queue",
    # empty HELP string
    "flushes_total": "",
    # declared twice
    "binds_total": "Pods bound",
    "binds_total": "Pods bound (again)",  # noqa: F601
    # fine on its own, but missing from SHIPPED_METRICS below
    "orphan_metric_total": "Declared but never registered",
}

# Counter without the _total suffix
requests = Counter("requests_seconds", "RPC count mislabeled as seconds")

# Histogram with a bad suffix
steps = Histogram("step_time", "Device step time", labels=("rpc",))

# Histogram with no help text at all
waits = Histogram("wait_duration_seconds")


def render(extra):
    # emitted through the side channel with no HELP entry anywhere
    extra.update(mystery_metric_total=1)
    extra["surprise_sample_bytes"] = 2
    return extra


SHIPPED_METRICS = (
    "queue_depth",
    "flushes_total",
    "binds_total",
    "requests_seconds",
    "step_time",
    "wait_duration_seconds",
    # shipped once, no longer declared anywhere — the removal the rule
    # exists to catch
    "removed_metric_total",
)
