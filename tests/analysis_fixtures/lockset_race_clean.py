"""graftlint fixture: lockset-consistent classes the lockset-race
family must NOT flag (never imported) — including the private-helper
pattern that needs a hand waiver under per-file lock-discipline but is
PROVEN safe by the call graph here."""

import threading


class DisciplinedCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._store = {}
        self._bytes = 0
        # __init__ may call helpers lock-free: construction
        # happens-before publication
        self._rebuild()

    def put(self, k, v):
        with self._lock:
            self._store[k] = v
            self._bytes += len(v)

    def drop(self, k):
        with self._lock:
            self._store.pop(k, None)

    def flush(self):
        with self._lock:
            # the helper mutates guarded state WITHOUT a lexical lock —
            # every intra-class call site holds self._lock, so its
            # entry lockset is {_lock}: clean, no waiver needed
            self._rebuild()

    def _rebuild(self):
        self._store = {}
        self._bytes = 0


class HelpersDefinedFirst:
    """Definition-order regression: the helper chain appears BEFORE its
    only (lock-holding) entry. A fixpoint that injects a default empty
    context for not-yet-computed callers would flag `_deep` here — the
    real entry lockset is {_lock} regardless of method order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}

    def _deep(self):
        self._table = {}

    def _shallow(self):
        self._deep()

    def rebuild(self):
        with self._lock:
            self._shallow()

    def put(self, k, v):
        with self._lock:
            self._table[k] = v


class InitOnlyHelper:
    """Constructor setup refactored into a private helper: `_reset` is
    reachable ONLY from `__init__`, so its lock-free mutation of
    `_store` inherits the construction happens-before exemption — no
    finding, even though `put` guards the same attribute."""

    def __init__(self):
        self._lock = threading.Lock()
        self._reset()

    def _reset(self):
        self._store = {}

    def put(self, k, v):
        with self._lock:
            self._store[k] = v


class UnguardedScratch:
    """A lock exists for something else; `notes` is never mutated under
    it anywhere — no lockset claim, no finding."""

    def __init__(self):
        self._lock = threading.Lock()
        self.notes = []
        self._active = False

    def start(self):
        with self._lock:
            self._active = True

    def scribble(self, line):
        self.notes.append(line)
