"""determinism-taint violating fixture: wall-clock reads, set iteration
order, and id()-keyed ordering flowing into journal records, a
CycleMetrics construction, and an engine operand."""

import time

JOURNAL = []


def record_cycle(rec):
    JOURNAL.append(rec)


def emit(raw, nodes):
    tags = set(raw)
    order = list(tags)
    rec = {
        "started": time.time(),
        "order": order,
        "first_key": id(nodes[0]),
    }
    record_cycle(rec)


def schedule(engine, pending):
    names = {p.name for p in pending}
    batch = [n for n in names]
    engine.schedule_batch(batch)


def metrics(n):
    return CycleMetrics(pods_in=n, stamp=time.perf_counter())
