"""Fixture: a well-formed metric surface the rule stays quiet on."""


class Histogram:  # stand-in for observe.Histogram
    def __init__(self, name, help="", labels=()):
        pass


class Counter(Histogram):
    pass


class Gauge(Histogram):
    pass


_HELP = {
    "queue_depth_count": "Pods waiting in the scheduling queue",
    "flushes_total": "Speculative state discards",
    "binds_total": "Pods bound",
    "drain_rate_per_sec": "Pods drained per second",
    "window_size_mean": "Mean pods per scheduling window",
}

requests = Counter("requests_total", "RPCs served", labels=("rpc",))
steps = Histogram(
    "step_duration_seconds", "Device step time", labels=("rpc",)
)
sessions = Gauge("session_bytes", help="Bytes held by live sessions")


def render(extra):
    extra.update(flushes_total=1)
    extra["binds_total"] = 2
    return extra


SHIPPED_METRICS = (
    "queue_depth_count",
    "flushes_total",
    "binds_total",
    "drain_rate_per_sec",
    "window_size_mean",
    "requests_total",
    "step_duration_seconds",
    "session_bytes",
)
