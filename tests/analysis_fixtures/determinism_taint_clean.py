"""determinism-taint clean fixture: sorted() materialization before
escape, order-insensitive folds, the injected clock, and declared
timing fields (`wall_time`, `*_seconds`) as the sanctioned wall-clock
surface."""

import time

JOURNAL = []


def record_cycle(rec):
    JOURNAL.append(rec)


def emit(raw, clock):
    tags = set(raw)
    order = sorted(tags)
    rec = {
        "order": order,
        "count": len(tags),
        "wall_time": time.time(),
        "elapsed_seconds": clock(),
    }
    record_cycle(rec)


def schedule(engine, pending):
    names = {p.name for p in pending}
    engine.schedule_batch(sorted(names))


def metrics(n):
    return CycleMetrics(pods_in=n, engine_seconds=time.perf_counter())
