"""Fixture: seeded generator flow the sim-determinism family accepts."""

import numpy as np
from numpy.random import default_rng


def gen_cluster(n, seed=0):
    rng = np.random.default_rng(seed)       # seeded: clean
    rng2 = default_rng(seed + 1)            # seeded, bare form: clean
    util = rng.random(n)                    # generator draw: clean
    jitter = rng2.uniform(0, 1, n)
    pick = rng.choice([1, 2, 3])
    # an object that happens to be named like the stdlib module's
    # sibling (rng.random above) is a generator method, not a global
    return util, jitter, pick
