"""graftlint fixture: dtype/shape violations (never imported)."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def kernel(x, mask):
    y = jnp.zeros(x.shape, dtype=np.float64)  # LINE 10: float64 dtype
    z = x.astype(float)  # LINE 11: astype to float64
    if mask.any():  # LINE 12: Python branch on a traced predicate
        z = z + 1.0
    return y + z
