"""graftlint fixture: donated-buffer re-reads (never imported).

Every shape the donation-aliasing family flags in ONE file: the plain
re-read, the two-reads case, an attribute-chain argument (the resident
`st.snapshot` pattern), and a donating `jax.device_put`."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def apply_delta(state, rows, vals):
    return state.at[rows].set(vals, mode="drop")


def cycle(state, rows, vals):
    new = apply_delta(state, rows, vals)
    # `state` was donated — its buffer may already back `new`
    return new + state.sum()


def cycle_two_reads(state, rows, vals):
    out = apply_delta(state, rows, vals)
    total = jnp.sum(state)  # donated leaf re-read
    return out, total


def cycle_attribute_chain(st, rows, vals):
    new = apply_delta(st.snapshot, rows, vals)
    # the donated ATTRIBUTE chain re-read before the rebind — the exact
    # resident-state shape (st.snapshot = new must come FIRST)
    probe = st.snapshot.sum()
    st.snapshot = new
    return probe


def cycle_device_put(buf, dev):
    moved = jax.device_put(buf, dev, donate=True)
    return moved + buf.sum()  # donated via device_put, then re-read


class ResidentFold:
    # jax counts the bound `self` at position 0, so donate_argnums=(1,)
    # donates `buf` — the summary must shift onto the receiver-dropped
    # numbering call sites use (watching `d` instead misses this)
    @functools.partial(jax.jit, donate_argnums=(1,))
    def fold(self, buf, d):
        return buf.at[d].add(1.0)

    def cycle(self, buf, d):
        out = self.fold(buf, d)
        return out + buf.sum()  # re-read after method donation


def cycle_double_donation(state, rows, vals):
    # TWO donating calls before one re-read: the second call's argument
    # is itself a re-read (flagged), but the final `state.sum()` is ONE
    # finding, not one per preceding donation
    a = apply_delta(state, rows, vals)
    b = apply_delta(state, rows, vals)
    return a + b + state.sum()  # re-read after double donation


def cycle_in_match_arm(state, rows, vals, mode):
    # match arms are suites too: a re-read inside one must be visible
    # to the branch-path walker (regression: Match.cases was skipped)
    match mode:
        case "delta":
            new = apply_delta(state, rows, vals)
            return new + state.sum()  # re-read inside the case body
        case _:
            return state
