"""graftlint fixture: clean host path — one bulk sync, loops on host data."""

import numpy as np


def apply_results(window, res):
    idx = np.asarray(res.node_idx)  # ONE bulk device->host sync
    out = []
    for i in range(len(window)):
        out.append(int(idx[i]))  # host numpy indexing: fine
    return out
