"""graftlint fixture: an honored eval_shape contract (never imported by
product code — loaded by contracts.check_fixture_module)."""

import jax.numpy as jnp


def scale_rows(x, w):
    return x * w[:, None]


def row_stats(x):
    hi = jnp.max(x, axis=1)
    lo = jnp.min(x, axis=1)
    return jnp.stack([hi, lo])


CONTRACTS = [
    {
        "fn": "scale_rows",
        "args": [("float32", ("n", "r")), ("float32", ("n",))],
        "out": ("float32", ("n", "r")),
        "grid": [{"n": 8, "r": 4}, {"n": 16, "r": 4}],
    },
    {
        "fn": "row_stats",
        "args": [("float32", ("n", "r"))],
        "out": ("float32", (2, "n")),
        "grid": [{"n": 8, "r": 4}],
    },
]
