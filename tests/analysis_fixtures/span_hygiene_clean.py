"""Fixture: a well-formed span surface the rule stays quiet on."""

import time


class SpanSet:  # stand-in for observe.SpanSet
    def add(self, name, t0, t1, **args):
        pass

    def span(self, name, **args):
        pass


class Driver:
    def _span(self, name, t0, t1=None, **args):
        pass

    def cycle(self, ss: SpanSet):
        t0 = time.perf_counter()
        self._span("queue_pop", t0)
        self._span("snapshot_build", t0, t0 + 1.0)
        ss.add("engine_step", t0, t0 + 2.0, resident=False)
        with ss.span("bind"):
            pass
        ss.add("cycle", t0, t0 + 3.0, path="serial")


SHIPPED_SPANS = (
    "queue_pop",
    "snapshot_build",
    "engine_step",
    "bind",
    "cycle",
)
