"""graftlint fixture: timeout-disciplined external calls."""

import subprocess
import urllib.request


def fetch(url):
    return urllib.request.urlopen(url, timeout=10.0).read()


def build():
    subprocess.run(["make"], check=True, timeout=120)


def shutdown(worker_thread, done_event, proc):
    done_event.wait(5.0)
    proc.communicate(timeout=10)
    worker_thread.join(timeout=2.0)
    "".join(["a", "b"])  # str.join with args: never flagged
