"""Cross-file thread-race fixture, file A: the shared state class.
Nothing in this file is threaded — the race only appears when file B's
worker writes through `put` while file B's main thread reads through
`dump`."""


class Registry:
    def __init__(self):
        self.items = {}
        self.sealed = False

    def put(self, key, val):
        self.items[key] = val

    def freeze(self):
        self.sealed = True

    def dump(self):
        return dict(self.items), self.sealed
