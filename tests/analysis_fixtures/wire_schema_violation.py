"""graftlint fixture: wire-schema drift (never imported, only parsed).

The `fixture.proto` sibling defines Ping{name, seq, payload, tags} and
Pong{}; everything below drifts from it.
"""

from tests.analysis_fixtures import fixture_pb2 as pb


def send(req: pb.Ping):
    req.nonexistent = 3  # LINE 11: Ping has no field `nonexistent`
    return pb.Ping(name="x", bogus=1)  # LINE 12: no field `bogus`


def bad_message():
    return pb.Missing()  # LINE 16: message `Missing` not in the schema


def assigned_var_drift():
    reply = pb.Pong()
    return reply.status  # LINE 21: Pong has no field `status`
