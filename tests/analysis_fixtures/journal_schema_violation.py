"""wire-schema journal fixture: every schema-drift failure mode fires."""

BASE_TAG = 40


class Field:
    def __init__(self, tag, name, kind):
        self.tag, self.name, self.kind = tag, name, kind


SOME_KIND = "u64"

JOURNAL_FIELDS = (
    Field(1, "seq", "u64"),
    Field(1, "path", "str"),            # tag 1 reused -> violation
    Field(2, "seq", "json"),            # name reused -> violation
    Field(BASE_TAG + 1, "extra", "str"),  # computed tag -> violation
    Field(0, "zero", "u64"),            # non-positive tag -> violation
    Field(3, "blob", "bytes_v2"),       # unknown kind -> violation
    Field(5, "computed", SOME_KIND),    # non-literal kind -> violation
    Field(4, "snapshot", "tensors"),
)

TENSOR_DTYPES = {
    "snapshot.allocatable": "float64",   # unpinned dtype -> violation
    "snapshot.requested": "float32",
    "pods.request": "float32",           # `pods` not tensors-kind -> violation
    "snapshot.mask": "bool",
}
