"""graftlint fixture: inconsistent locksets through the class call
graph (never imported)."""

import threading


class TornCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._store = {}
        self._count = 0

    def put(self, k, v):
        with self._lock:
            self._store[k] = v
            self._count += 1

    def drop(self, k):
        # public method, lock-free mutation of guarded state — the
        # classic torn write (lock-discipline catches this too)
        self._store.pop(k, None)

    def reset(self):
        # a private helper called WITHOUT the lock from a public
        # method: the call graph proves the lock-free path — this is
        # the case per-file lexical analysis cannot justify either way
        self._wipe()

    def _wipe(self):
        self._store.clear()
        self._count = 0


class MixedGuards:
    """The same attribute guarded by DIFFERENT locks in different
    methods: no common lock exists, every site flagged."""

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self.shared = []

    def writer_a(self, v):
        with self._lock_a:
            self.shared.append(v)

    def writer_b(self, v):
        with self._lock_b:
            self.shared.append(v)
