"""graftlint fixture (cross-file half): a helper module whose wrapper
donates transitively. Linted TOGETHER with
donation_interproc_violation.py — the case a single-file AST scan
cannot catch."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def apply_delta(state, delta):
    return state + delta


def fold(state, delta):
    # passes its own parameter into a donated position: the donation
    # summary fixpoint marks `fold` as donating argument 0 too
    return apply_delta(state, delta)
