"""graftlint fixture: eval_shape contract DRIFT (never imported by
product code — loaded by contracts.check_fixture_module).

The declared contract says `scale_rows` preserves [n, r] float32; the
implementation transposes — the class of fused/unfused drift the
engine-contract layer exists to catch before a bench round does."""

import jax.numpy as jnp


def scale_rows(x, w):
    # drift: returns [r, n], the declaration says [n, r]
    return (x * w[:, None]).T


def cast_rows(x):
    # drift: promotes dtype vs the declared float32
    return x.astype(jnp.int32)


CONTRACTS = [
    {
        "fn": "scale_rows",
        "args": [("float32", ("n", "r")), ("float32", ("n",))],
        "out": ("float32", ("n", "r")),
        "grid": [{"n": 8, "r": 4}, {"n": 16, "r": 4}],
    },
    {
        "fn": "cast_rows",
        "args": [("float32", ("n", "r"))],
        "out": ("float32", ("n", "r")),
        "grid": [{"n": 8, "r": 4}],
    },
]
