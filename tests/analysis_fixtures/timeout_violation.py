"""graftlint fixture: timeout-hygiene violations (never imported)."""

import subprocess
import urllib.request


def fetch(url):
    return urllib.request.urlopen(url).read()  # LINE 8: no timeout


def build():
    subprocess.run(["make"], check=True)  # LINE 12: no timeout


def shutdown(worker_thread, done_event, proc):
    done_event.wait()  # LINE 16: unbounded event wait
    proc.communicate()  # LINE 17: unbounded process drain
    worker_thread.join()  # LINE 18: unbounded thread join
