"""graftlint fixture: STRUCTURAL waiver placement (never imported) —
the decorated-def and multi-line-statement shapes `_parse_waivers` +
`_resolve_waiver_spans` must honor, plus unwaived twins proving the
spans do not over-cover."""

import jax
import jax.numpy as jnp
import urllib.request


# a waiver above the DECORATOR waives the WHOLE def: the traced-bool
# branch is three lines below the comment, inside the body
# graftlint: disable=dtype-shape -- fixture: decorated-def waiver covers the body finding
@jax.jit
def gated_waived(x):
    if x.any():
        return x
    return -x


@jax.jit
def gated_unwaived(x):
    # the twin without a waiver: still fires (the span above covers
    # ONLY its own def)
    if x.any():
        return x
    return -x


def multiline_statement_waived():
    # graftlint: disable=timeout-hygiene -- fixture: the call spans three lines; the waiver covers all of them
    body = urllib.request.urlopen(
        "http://localhost:9/metrics",
    )
    return body


def multiline_statement_unwaived():
    # no waiver: stays a timeout-hygiene finding (attributed to some
    # line of this multi-line statement)
    body = urllib.request.urlopen(
        "http://localhost:9/metrics",
    )
    return body


def dtype_kw_on_later_line():
    # graftlint: disable=dtype-shape -- fixture: the dtype kw lands two lines into the statement
    table = jnp.zeros(
        (4, 4),
        dtype=jnp.float64,
    )
    return table
