"""graftlint fixture: clean Pallas kernel (never imported, only parsed).

Mirrors the real ops/pallas_fused.py shape: 128-aligned lane tiles,
blocks well under the VMEM budget, f32 accumulation, no host effects;
runtime-valued leading dims (n_res) are legitimately unresolvable and
must not be flagged."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_P = 256
TILE_N = 1024


def _clean_kernel(x_ref, y_ref, out_ref, *, n_res: int):
    acc = jnp.zeros((TILE_P, TILE_N), jnp.float32)
    for i in range(n_res):
        acc = acc + x_ref[i, :][:, None] * y_ref[i, :][None, :]
    out_ref[...] = acc


def clean_call(x, y, tile_p: int = TILE_P, tile_n: int = TILE_N):
    n_res = x.shape[0]
    return pl.pallas_call(
        functools.partial(_clean_kernel, n_res=n_res),
        out_shape=jax.ShapeDtypeStruct((x.shape[1], y.shape[1]), jnp.float32),
        grid=(x.shape[1] // tile_p, y.shape[1] // tile_n),
        in_specs=[
            pl.BlockSpec((n_res, tile_p), lambda i, j: (0, i)),
            pl.BlockSpec((n_res, tile_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((tile_p, tile_n), lambda i, j: (i, j)),
    )(x, y)
