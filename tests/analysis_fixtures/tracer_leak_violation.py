"""graftlint fixture: tracers stored where they outlive the traced call
(never imported)."""

import jax
import jax.numpy as jnp


@jax.jit
def leak_to_self(self, x):
    y = jnp.tanh(x)
    self.cache = y  # tracer stored onto the receiver object
    return y


@jax.jit
def leak_via_helper_entry(state, x):
    return _helper_leak(state, x)


def _helper_leak(state, x):
    # reachable from the jitted entry above THROUGH the call graph: a
    # per-file scan of this function alone sees no jit anywhere
    state.last = x * 2.0
    return x


@jax.jit
def leak_into_container(slots, x):
    v = jnp.exp(x)
    slots.history.append(v)  # attribute-chained container outlives
    return v


@jax.jit
def leak_subscript(registry, x):
    registry["latest"] = jnp.abs(x)  # param subscript store
    return x
