"""graftlint fixture: clean lock usage — every guarded mutation locked."""

import threading


class SharedCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._store = {}
        self.hits = 0  # __init__ writes are exempt (happens-before)

    def put(self, key, value):
        with self._lock:
            self._store[key] = value

    def drop(self, key):
        with self._lock:
            self._store.pop(key, None)

    def snapshot(self):
        with self._lock:
            return dict(self._store)  # reads: unrestricted
