"""wire-schema journal fixture: a well-formed schema table stays quiet."""


class Field:
    def __init__(self, tag, name, kind):
        self.tag, self.name, self.kind = tag, name, kind


JOURNAL_FIELDS = (
    Field(1, "seq", "u64"),
    Field(2, "path", "str"),
    Field(3, "metrics", "json"),
    Field(4, "wall_time", "f64"),
    Field(5, "snapshot", "tensors"),
    Field(6, "assign", "tensors"),
)

TENSOR_DTYPES = {
    "snapshot.allocatable": "float32",
    "snapshot.node_mask": "bool",
    "snapshot.labels": "int32",
    "assign.node_idx": "int32",
}
