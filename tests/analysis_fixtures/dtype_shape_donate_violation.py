"""graftlint fixture: donated-buffer re-read (never imported)."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def apply_delta(state, rows, vals):
    return state.at[rows].set(vals, mode="drop")


def cycle(state, rows, vals):
    new = apply_delta(state, rows, vals)
    # LINE 17: `state` was donated — its buffer may already back `new`
    return new + state.sum()


def cycle_two_reads(state, rows, vals):
    out = apply_delta(state, rows, vals)
    total = jnp.sum(state)  # LINE 23: donated leaf re-read
    return out, total
