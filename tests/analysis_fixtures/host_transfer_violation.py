"""graftlint fixture: implicit device→host syncs on jax values (never
imported). Each conversion shape the host-transfer family flags."""

import jax.numpy as jnp
import numpy as np


def item_sync(x):
    total = jnp.sum(x)
    return total.item()  # blocking device round-trip


def float_sync(x):
    score = jnp.max(x)
    return float(score)  # implicit .item()


def int_sync(x):
    n = jnp.argmax(x)
    best = int(n)  # implicit .item()
    return best


def copy_sync(x):
    scores = jnp.where(x > 0, x, 0.0)
    host = np.asarray(scores)  # device→host copy mid-function
    return host[0]


def bool_branch(x):
    ok = jnp.all(x > 0)
    if ok:  # __bool__ blocks (raises on a tracer)
        return 1
    return 0


def assert_sync(x):
    mask = jnp.any(x)
    assert mask  # __bool__ device sync
    return x


def direct_call_sync(x):
    return float(jnp.mean(x))  # no binding needed — direct jnp call


def annotated_binding_sync(x):
    total: jnp.ndarray = jnp.sum(x)  # AnnAssign taints like Assign
    return float(total)


def kwonly_param_sync(*, scores: jnp.ndarray):
    return float(scores)  # keyword-only annotated param is tainted too
