"""graftlint fixture: half-wired capability bits (never imported, only
parsed). The sibling fixture.proto's HealthReply declares cap_a and
cap_b; everything below wires them WRONG — see the LINE comments.
"""


class EngineUnavailable(RuntimeError):
    pass


# LINE 12: cap_b missing from the table; cap_zz names no proto field
CAPABILITY_LATCHES = {
    "cap_a": "_cap_a",
    "cap_zz": "_cap_zz",
}


class HalfWiredClient:
    def __init__(self, target):
        self._target = target
        self._cap_a = None
        self._cap_zz = None
        self._wire_cache = {}

    def _probe_capabilities(self):
        # LINE 26: hand-rolled latch list, not driven by the table
        info = self.health_info()
        if info is not None and self._cap_a is None:
            self._cap_a = bool(info.cap_a)

    def _invalidate_session(self):
        # LINE 32: resets one latch by hand instead of the whole table
        self._wire_cache.clear()
        self._cap_a = None

    def health_info(self):
        return None

    # no accessor ever reads self._cap_a or self._cap_zz outside the
    # plumbing above: both latches gate nothing

    def preempt(self, request):
        # LINE 43: sends through _call_with_retry but a failure never
        # reaches the session invalidation — latches outlive the sidecar
        return self._call_with_retry(self._target, request)

    def _call_with_retry(self, method, request):
        raise EngineUnavailable(method)


# LINE 51: cap_b missing from the switch table too
CAPABILITY_SWITCHES = {
    "cap_a": "cap_a_enabled",
}


class HalfWiredServer:
    def __init__(self):
        # LINE 58: cap_a_enabled is never assigned anywhere in the
        # class — health() would getattr-default its way to False
        self.cycles_served = 0

    def health(self, request, context):
        # LINE 63: renders a hand-picked bit, not the switch table
        return {"status": "SERVING", "cap_a": True}
