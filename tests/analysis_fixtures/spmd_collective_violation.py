"""spmd-collective violating fixture: every check in the family fires.

A miniature mesh-sharded scoring pipeline with the four SPMD bug
classes seeded: a psum of an already-replicated value (double-count),
a collective on an axis name no mesh declares (wrong-axis), an
all_gather of a replicated value (redundant collective) plus the
axis=-name misuse, and an out_specs leaf declaring replication the
body never establishes. AST-only: never imported, only parsed.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

NODE_AXIS = "node"


def make_mesh():
    return Mesh(np.asarray(jax.devices()), (NODE_AXIS,))


def make_bad_stats_fn(mesh):
    def body(x, w):
        # x sharded along NODE_AXIS, w replicated (see in_specs below)
        total = jax.lax.psum(x.sum(), NODE_AXIS)
        # VIOLATION (replicated-psum): w is replicated — every shard
        # contributes the same sum, so this counts it D times
        wsum = jax.lax.psum(w.sum(), NODE_AXIS)
        # VIOLATION (unbound-axis): "nodez" is declared by no mesh
        hi = jax.lax.pmax(x.max(), "nodez")
        # VIOLATION (replicated-gather): total is already identical on
        # every shard; gathering stacks D copies for nothing
        stacked = jax.lax.all_gather(total, NODE_AXIS)
        # VIOLATION (gather-axis-misuse): axis= is the insertion
        # position (an int), not the mesh axis name
        cols = jax.lax.all_gather(x.max(), NODE_AXIS, axis=NODE_AXIS)
        return total + wsum + hi + stacked.sum() + cols.sum()

    return shard_map(
        body, mesh=mesh, in_specs=(P(NODE_AXIS), P()), out_specs=P(),
    )


def make_unestablished_out_fn(mesh):
    def body(x):
        # VIOLATION (out-spec-replication): the local max is one
        # shard's value, but out_specs declares it replicated — the
        # discharge is hi = jax.lax.pmax(hi, NODE_AXIS)
        hi = x.max()
        return hi

    return shard_map(body, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P())


def make_varying_out_fn(mesh):
    def body(x):
        # VIOLATION (out-spec-replication, varying flavor): an
        # axis_index-derived value is device-varying by construction
        offset = jax.lax.axis_index(NODE_AXIS).astype(jnp.int32)
        return jnp.argmax(x).astype(jnp.int32) + offset

    return shard_map(body, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P())
