"""thread-race violating fixture: a worker thread and the main thread
share attributes with no common lockset and no happens-before edge —
plus the classic lock-free check-then-act latch and an unguarded
module global."""

import threading

COUNTER = 0


def bump():
    global COUNTER
    COUNTER = COUNTER + 1


def reset():
    global COUNTER
    COUNTER = 0


class Pump:
    def __init__(self):
        self.rows = []
        self.total = 0
        self.cache = None
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
        # written AFTER start(): the worker can already be reading
        self.total = 1

    def _run(self):
        for i in range(4):
            self.ensure()
            self.rows.append(i)
            self.total += 1
            bump()

    def ensure(self):
        # lock-free check-then-act: two threads both observe None
        if self.cache is None:
            self.cache = {}
        return self.cache

    def read(self):
        return len(self.rows), self.total


def drive():
    reset()
    p = Pump()
    p.start()
    p.ensure()
    rows, total = p.read()
    return rows, total, COUNTER
