"""graftlint fixture: jit-purity violations (never imported, only parsed)."""

import functools

import jax
import jax.numpy as jnp

TRACE_LOG = {}


@functools.partial(jax.jit, static_argnames=("k",))
def score_kernel(x, *, k=1):
    print("scoring", k)  # LINE 14: side-effecting call at trace time
    TRACE_LOG[k] = x.shape  # LINE 15: module-state mutation
    return jnp.tanh(x) * k


def impure_helper(x):
    global _CALLS  # LINE 20: global declaration
    _CALLS = x
    return x * 2


@jax.jit
def entry(x):
    # the helper is reachable from a jit entry, so its impurity counts
    return impure_helper(x)
