"""graftlint fixture: dtype/shape-disciplined kernel code."""

import jax
import jax.numpy as jnp


@jax.jit
def kernel(x, mask):
    y = jnp.zeros(x.shape, dtype=jnp.float32)
    if x.shape[0] > 4:  # static-shape branch: idiomatic, never flagged
        y = y[:4]
        mask = mask[:4]
    return jnp.where(mask, y, x[: y.shape[0]].astype(jnp.float32))
