"""graftlint fixture: pallas-vmem per-shard block dims under shard_map
(clean half — never imported, only parsed).

The lane-aligned counterpart: 1024 global nodes over 8 shards gives a
128-lane per-shard axis, and a non-dividing split (`n_res // 3`) stays
UNRESOLVABLE — skipped, not guessed: the floor division's value is not
the true dimension when the split is ragged, and shard_map would have
rejected the layout first."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

N_NODES = 1024
MESH_DEVICES = 8


def _score_kernel(x_ref, out_ref):
    out_ref[...] = x_ref[...] * 2.0


def rebound_launch(x):
    # a rebound name is UNRESOLVABLE, skipped not guessed: a
    # flow-insensitive last-wins value (64) would have checked the
    # first, correctly 128-aligned BlockSpec against the wrong dim
    n_loc = N_NODES // MESH_DEVICES
    first = pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((8, n_loc), jnp.float32),
        grid=(1, 1),
        in_specs=[pl.BlockSpec((8, n_loc), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, n_loc), lambda i, j: (i, j)),
    )(x)
    n_loc = n_loc // 2
    return pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((8, n_loc), jnp.float32),
        grid=(1, 1),
        in_specs=[pl.BlockSpec((8, n_loc), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, n_loc), lambda i, j: (i, j)),
    )(first)


def sharded_launch(x, n_res):
    # per-shard node axis: 1024 // 8 = 128 — lane-aligned
    n_local = N_NODES // MESH_DEVICES
    ragged = n_res // 3  # runtime operand: unresolvable, skipped
    return pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((8, n_local), jnp.float32),
        grid=(1, 1),
        in_specs=[pl.BlockSpec((8, n_local), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, ragged), lambda i, j: (i, j)),
    )(x)
