"""graftlint fixture: host-side conversions the host-transfer family
must NOT flag (never imported) — every false-positive pattern the
analyzer was taught, pinned."""

import jax
import jax.numpy as jnp
import numpy as np


def untainted_receiver(records):
    # host numpy by construction: local dataflow cannot tie this to jax,
    # so the rule stays quiet (precision over recall)
    arr = np.zeros(len(records), np.float32)
    return float(arr.sum())


def materialized_is_host(x):
    dev = jnp.cumsum(x)
    host = np.asarray(dev)  # graftlint: disable=host-transfer -- the fixture's one bulk boundary sync
    # `host` is numpy now: per-element reads off it are free
    return int(host[0]) + float(host[1])


def backend_probe_is_host(x):
    # jax.default_backend() returns a STRING — branching on it is host
    # control flow, not a device sync
    backend = jax.default_backend()
    if backend == "tpu":
        return x
    return x * 2


def shape_branch(x):
    y = jnp.dot(x, x)
    if y.shape[0] > 4:  # shapes are Python ints — no sync
        return y
    return y * 2


def shape_bound_to_name(x):
    # binding static metadata to a local must not taint it: `n` is a
    # Python int, so the bare branch below is host control flow
    y = jnp.dot(x, x)
    n = y.shape[0]
    if n:
        return y
    return int(n) + float(y.ndim)


def len_is_static(x):
    # len() reads static shape metadata — a Python int, no sync; the
    # binding, the bare branch, and float(len(...)) all stay quiet
    y = jnp.cumsum(x)
    idx = len(y)
    if idx:
        return y
    return float(len(y))


def comparison_not_bare(x, limit):
    count = jnp.sum(x)
    # a comparison feeding `if` is still a sync in principle, but the
    # family only flags BARE tainted tests — this stays the waivable
    # grey zone, documented here
    return count, limit
