"""Replicated scheduler fleet: partition hash, bind-table CAS, fencing.

The `replica-bind` protocol model (analysis/model/protocols.py) proved
no-double-bind and bound-pod-never-re-popped over every interleaving of
the ABSTRACT transitions; these tests pin the shipped primitives those
transitions anchor to:

- pod_partition: crc32(namespace), stable across interpreter restarts
  (hash() is salted per process and would fork a pod's partition on
  resubmit-after-crash), gangs never straddling by construction.
- PartitionedQueue: per-partition pop/restore semantics EXACTLY the
  single-queue semantics, on both queue backends (the PR-6 ordering
  pins, per partition).
- BindTable.try_bind: first bind wins, stale-epoch fencing (the
  `unfenced-replica-bind` mutant's load-bearing line).
- ReplicaCoordinator / FencedBinder: the pop-filter (drop_bound), the
  conflict flow (bind_lose -> requeue -> 409 -> drop_bound), requeue
  latency accounting.
- ReplicaFleet: partition-routed drains, N-replica union-of-bindings
  parity with 1 replica on conflict-free workloads (PARITY round 19).
- ReplicaMembership: slot claiming, standby, slot release.
"""

import os
import subprocess
import sys

import pytest

from kubernetes_scheduler_tpu.host.queue import (
    PartitionedQueue,
    make_queue,
    namespace_partition,
    pod_gang,
    pod_partition,
    pod_partition_key,
)
from kubernetes_scheduler_tpu.host.replica import (
    BindConflictError,
    BindTable,
    FencedBinder,
    ReplicaCoordinator,
    ReplicaFleet,
)
from kubernetes_scheduler_tpu.host.types import Container, Pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_pod(name, ns="default", priority=None, gang=None, gang_size=0,
           cpu=100.0):
    labels = {}
    if priority is not None:
        labels["scv/priority"] = str(priority)
    if gang is not None:
        labels["scv/gang"] = gang
        labels["scv/gang-size"] = str(gang_size)
    return Pod(
        name=name,
        namespace=ns,
        labels=labels,
        containers=[Container(requests={"cpu": cpu, "memory": 2**28})],
    )


# ---- partition hash -------------------------------------------------------


def test_partition_assignment_survives_interpreter_restarts():
    """The determinism claim that rules out Python's salted hash():
    two interpreters with DIFFERENT hash seeds must agree with this
    process on every namespace's partition."""
    namespaces = [f"tenant-{i}" for i in range(16)] + ["default", "kube-system"]
    here = {ns: namespace_partition(ns, 4) for ns in namespaces}

    src = (
        "import json, sys\n"
        "from kubernetes_scheduler_tpu.host.queue import namespace_partition\n"
        "print(json.dumps({ns: namespace_partition(ns, 4)"
        " for ns in sys.argv[1:]}))\n"
    )
    for seed in ("0", "12345"):
        out = subprocess.run(
            [sys.executable, "-c", src, *namespaces],
            capture_output=True, text=True, timeout=60, cwd=REPO,
            env={**os.environ, "PYTHONHASHSEED": seed},
        )
        assert out.returncode == 0, out.stderr[-500:]
        import json

        assert json.loads(out.stdout) == here, f"hash seed {seed} diverged"


def test_pod_partition_matches_namespace_partition_and_is_memoized():
    for i in range(8):
        pod = mk_pod("p", ns=f"tenant-{i}")
        for n in (1, 2, 3, 4, 8):
            assert pod_partition(pod, n) == namespace_partition(pod.namespace, n)
    # the crc is memoized on the pod, the modulus is not: the same pod
    # re-partitions correctly when the fleet is resized
    pod = mk_pod("p", ns="tenant-3")
    parts = {n: pod_partition(pod, n) for n in (2, 4, 8)}
    assert parts == {n: namespace_partition("tenant-3", n) for n in (2, 4, 8)}
    assert "_part_crc" in pod.__dict__


def test_single_partition_short_circuits_to_zero():
    assert namespace_partition("anything", 1) == 0
    assert namespace_partition("anything", 0) == 0
    assert pod_partition(mk_pod("p", ns="x"), 1) == 0


def test_gangs_never_straddle_partitions():
    """The gang identity key is namespace-prefixed (pod_gang), and the
    partition key IS the namespace — so every member of a gang lands on
    one partition for every fleet size, by construction."""
    for g in range(6):
        ns = f"team-{g}"
        members = [
            mk_pod(f"g{g}-m{i}", ns=ns, gang=f"job-{g}", gang_size=4)
            for i in range(4)
        ]
        key, size = pod_gang(members[0])
        assert key.startswith(f"{ns}/") and size == 4
        assert pod_partition_key(members[0]) == ns
        for n in (2, 3, 4, 8):
            assert len({pod_partition(p, n) for p in members}) == 1


# ---- partitioned queue ----------------------------------------------------


@pytest.mark.parametrize("native", [True, False])
def test_partitioned_queue_routes_by_namespace(native):
    q = PartitionedQueue(2, prefer_native=native, clock=lambda: 0.0)
    ns = {p: None for p in range(2)}
    i = 0
    while any(v is None for v in ns.values()):
        name = f"tenant-{i}"
        part = namespace_partition(name, 2)
        if ns[part] is None:
            ns[part] = name
        i += 1
    pods = [mk_pod(f"p{j}", ns=ns[j % 2]) for j in range(8)]
    for pod in pods:
        q.push(pod)
    assert len(q) == 8
    for part in range(2):
        got = q.partition(part).pop_window(8)
        assert {p.name for p in got} == {
            p.name for p in pods if q.partition_of(p) == part
        }
    assert len(q) == 0


@pytest.mark.parametrize("native", [True, False])
def test_restore_window_order_per_partition_matches_single_queue(native):
    """The PR-6 restore-ordering pins, per partition: a partition's
    pop -> restore -> push -> pop sequence produces EXACTLY the order a
    standalone queue of the same backend produces for the same pods —
    the router adds no ordering semantics of its own."""
    ns0 = next(
        f"tenant-{i}" for i in range(64)
        if namespace_partition(f"tenant-{i}", 2) == 0
    )
    ns1 = next(
        f"tenant-{i}" for i in range(64)
        if namespace_partition(f"tenant-{i}", 2) == 1
    )

    def traffic(ns):
        return [
            mk_pod("a", ns=ns, priority=5),
            mk_pod("b", ns=ns, priority=5),
            mk_pod("c", ns=ns, priority=9),
        ]

    def drive(queue, ns):
        for pod in traffic(ns):
            queue.push(pod)
        window = queue.pop_window(2)
        queue.restore_window(window)
        queue.push(mk_pod("d", ns=ns, priority=9))
        return [p.name for p in queue.pop_window(4)]

    part = PartitionedQueue(2, prefer_native=native, clock=lambda: 0.0)
    got = {
        0: drive(part.partition(0), ns0),
        1: drive(part.partition(1), ns1),
    }
    for ns, sequence in ((ns0, got[0]), (ns1, got[1])):
        solo = make_queue(prefer_native=native, clock=lambda: 0.0)
        assert drive(solo, ns) == sequence


# ---- bind table -----------------------------------------------------------


def test_bind_table_first_bind_wins():
    t = BindTable()
    assert t.holder("ns/p") == "" and t.epoch("ns/p") == 0
    assert t.try_bind("ns/p", 0, "r0") is True
    assert t.holder("ns/p") == "r0"
    assert t.epoch("ns/p") == 1  # success advances the epoch
    # the racer loses regardless of the epoch it presents
    assert t.try_bind("ns/p", 0, "r1") is False
    assert t.try_bind("ns/p", 1, "r1") is False
    assert t.holder("ns/p") == "r0"
    assert t.bound == 1 and t.double_binds == 0
    assert t.holders() == {"ns/p": "r0"}


def test_bind_table_stale_epoch_fence():
    """The fence the `unfenced-replica-bind` mutant removes: an unbound
    key still rejects a bind whose seen-epoch is not current (a pop that
    never recorded the epoch presents -1 — the coordinator's default for
    an un-popped pod)."""
    t = BindTable()
    assert t.try_bind("ns/p", -1, "r0") is False  # never saw a pop
    assert t.try_bind("ns/p", 1, "r0") is False   # future epoch: stale state
    assert t.holder("ns/p") == ""
    assert t.try_bind("ns/p", 0, "r0") is True    # the honest pop wins


# ---- coordinator + fenced binder ------------------------------------------


class _StubBinder:
    def __init__(self):
        self.bindings = []

    def bind(self, pod, node_name):
        self.bindings.append((pod.name, node_name))


def _coordinator_pair():
    """Two coordinators over their own partitions, one shared table —
    the 2-replica topology without schedulers."""
    table = BindTable()
    queues = PartitionedQueue(2, prefer_native=False, clock=lambda: 0.0)
    c0 = ReplicaCoordinator("r0", queues.partition(0), table)
    c1 = ReplicaCoordinator("r1", queues.partition(1), table)
    return table, c0, c1


def test_pop_window_filters_bound_pods_and_records_epochs():
    table, c0, c1 = _coordinator_pair()
    mine = mk_pod("mine", ns="a")
    stale = mk_pod("stale", ns="a")
    c0.push(mine)
    c0.push(stale)
    # the other replica already bound its copy of "stale"
    assert table.try_bind("a/stale", 0, "r1")
    got = c0.pop_window(8)
    assert [p.name for p in got] == ["mine"]
    assert c0.pods_discarded == 1
    assert len(c0) == 0  # the filtered pod was retired, not requeued
    assert c0._seen == {"a/mine": 0}


def test_fenced_binder_conflict_resolves_without_losing_the_pod():
    table, c0, c1 = _coordinator_pair()
    b0 = FencedBinder(_StubBinder(), c0)
    b1 = FencedBinder(_StubBinder(), c1)
    # both replicas hold a popped copy of the same pod (partition
    # handoff overlap): epochs recorded on both sides
    for c in (c0, c1):
        c.push(mk_pod("racer", ns="x"))
    w0 = c0.pop_window(4)
    w1 = c1.pop_window(4)
    assert [p.name for p in w0] == [p.name for p in w1] == ["racer"]
    b0.bind(w0[0], "node-1")  # first bind wins
    assert b0.bindings == [("racer", "node-1")]
    with pytest.raises(BindConflictError) as err:
        b1.bind(w1[0], "node-2")
    assert err.value.status == 409
    assert b1.bindings == []  # the real bind never ran
    assert c1.conflicts == 1
    assert len(c1) == 1  # bind_lose requeued the loser's copy...
    redo = c1.pop_window(4)
    assert redo == []  # ...and the re-pop retires it via drop_bound
    assert c1.pods_discarded == 1
    assert len(c1) == 0
    assert len(c1.requeue_latencies) == 1
    assert table.double_binds == 0 and table.bound == 1


def test_bind_win_on_unpopped_pod_is_fenced():
    """A bind attempt for a pod this replica never popped (no recorded
    epoch) must lose — the -1 default can never match a real epoch."""
    _, c0, _ = _coordinator_pair()
    assert c0.bind_win(mk_pod("ghost", ns="x")) is False


# ---- fleet ----------------------------------------------------------------


def _tenant_for(residue, n):
    return next(
        ns for i in range(256)
        if namespace_partition(ns := f"tenant-{i}", n) == residue
    )


def _fleet_workload(pods_per=12):
    # one tenant per partition residue, so a 2-replica fleet is
    # guaranteed traffic on BOTH partitions
    ns_names = [_tenant_for(r, 2) for r in range(2)]
    return [
        mk_pod(f"w{t}-{j}", ns=ns_names[t])
        for t in range(2)
        for j in range(pods_per)
    ]


def _make_fleet(n_replicas, nodes, advisor, running):
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    return ReplicaFleet(
        SchedulerConfig(batch_window=64, normalizer="none"),
        n_replicas=n_replicas,
        advisor_factory=lambda i: advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )


def test_fleet_partitioned_drain_and_union_parity():
    """Disjoint partitioned traffic: zero conflicts, zero double binds,
    every pod bound by the replica owning its namespace — and the
    2-replica UNION of bound pods equals the 1-replica bound set on the
    same workload (the PARITY round-19 claim; node choices may differ,
    membership of the bound set may not)."""
    from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster

    nodes, advisor = gen_host_cluster(16, seed=0)

    def drain(n_replicas):
        running: list = []
        fleet = _make_fleet(n_replicas, nodes, advisor, running)
        for pod in _fleet_workload():
            fleet.submit(pod)
        evidence = fleet.run_until_empty(max_cycles=100)
        return fleet, evidence

    fleet2, ev2 = drain(2)
    assert ev2["bind_conflicts_total"] == 0
    assert ev2["double_binds"] == 0
    assert ev2["pods_discarded"] == 0
    assert ev2["total_binds"] == 24
    assert all(v > 0 for v in ev2["binds_per_replica"].values())
    # partition honesty: each replica bound only namespaces it owns
    for i, sched in enumerate(fleet2.schedulers):
        for binding in sched.binder.bindings:
            assert fleet2.partition_of(binding.pod) == i

    fleet1, ev1 = drain(1)
    assert ev1["total_binds"] == 24
    union2 = {b.pod.name for s in fleet2.schedulers for b in s.binder.bindings}
    union1 = {b.pod.name for b in fleet1.schedulers[0].binder.bindings}
    assert union2 == union1


def test_fleet_overlap_submissions_resolve_exactly_once():
    """submit_overlap hands the SAME pod to every replica (membership
    churn re-homing a namespace): exactly one replica binds it, every
    other copy is retired, nothing is lost, nothing double-binds."""
    from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster

    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    fleet = _make_fleet(2, nodes, advisor, running)
    for pod in _fleet_workload(pods_per=4):
        fleet.submit(pod)
    for j in range(5):
        fleet.submit_overlap(mk_pod(f"overlap-{j}", ns="contested"))
    ev = fleet.run_sequential(max_cycles=100)
    assert ev["double_binds"] == 0
    assert ev["total_binds"] == 8 + 5  # every pod bound exactly once
    # the 5 losing copies resolved (conflict or filtered pop, depending
    # on interleaving — sequential drains resolve via the pop filter)
    assert ev["pods_discarded"] + ev["bind_conflicts_total"] == 5
    assert sum(len(s.queue) for s in fleet.schedulers) == 0


def test_replica_scenario_is_deterministic():
    """Two runs of the 2-replica conflict storm at the same (seed,
    scale) produce identical evidence — conflicts included. Wall-time
    fields are the only legitimate diffs (requeue latency runs on the
    shared SimClock, so even it must match)."""
    from kubernetes_scheduler_tpu.sim.scenarios import run

    def storm():
        out = run("replica-conflict-storm", n_nodes=24, seed=3)
        for key in ("seconds", "pods_per_sec"):
            out.pop(key, None)
        return out

    first, second = storm(), storm()
    assert first["bind_conflicts"] > 0
    assert first["double_binds"] == 0
    assert first == second


# ---- membership -----------------------------------------------------------


def test_replica_membership_slots(tmp_path):
    from kubernetes_scheduler_tpu.host.leader import ReplicaMembership

    path = str(tmp_path / "fleet-lease")
    kw = dict(retry_period=0.05)
    m0 = ReplicaMembership.on_files(path, 2, **kw)
    m1 = ReplicaMembership.on_files(path, 2, **kw)
    assert m0.join(timeout=5) == 0
    # a second in-process membership must NOT look like the same holder
    # (identities carry a per-instance sequence number)
    assert m1.join(timeout=5) == 1
    assert m0.is_member() and m1.is_member()
    standby = ReplicaMembership.on_files(path, 2, **kw)
    assert standby.join(timeout=0.3) is None  # all slots held: stand by
    m0.leave()
    assert not m0.is_member()
    # the freed slot (and ONLY that slot) is claimable again — the
    # successor resumes partition 0
    successor = ReplicaMembership.on_files(path, 2, **kw)
    assert successor.join(timeout=5) == 0
    m1.leave()
    successor.leave()
