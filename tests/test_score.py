"""Golden tests: batched score kernels vs. the scalar oracle."""

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_scheduler_tpu.ops import (
    balanced_cpu_diskio,
    balanced_diskio,
    free_capacity,
    utilization_stats,
)
from tests import oracle

RNG = np.random.default_rng(0)


def make_cluster(n):
    disk_io = RNG.uniform(0, 50, n)
    cpu = RNG.uniform(0, 100, n)
    mem = RNG.uniform(0, 100, n)
    return disk_io, cpu, mem


def padded_stats(disk_io, cpu, pad=0):
    n = len(disk_io)
    d = np.concatenate([disk_io, np.zeros(pad)])
    c = np.concatenate([cpu, np.zeros(pad)])
    mask = np.arange(n + pad) < n
    return utilization_stats(jnp.asarray(d, jnp.float32), jnp.asarray(c, jnp.float32), jnp.asarray(mask))


@pytest.mark.parametrize("n,pad", [(1, 0), (7, 0), (16, 5), (64, 64)])
def test_stats_match_oracle(n, pad):
    disk_io, cpu, _ = make_cluster(n)
    stats = padded_stats(disk_io, cpu, pad)
    _, _, u_avg, m_tmp = oracle.stats_oracle(disk_io, cpu)
    np.testing.assert_allclose(float(stats.u_avg), u_avg, rtol=1e-5)
    np.testing.assert_allclose(float(stats.m_var), m_tmp, rtol=1e-4, atol=1e-6)
    assert int(stats.n_valid) == n


@pytest.mark.parametrize("r_cpu,r_io", [(100.0, 10.0), (250.0, 1.0), (100.0, 0.0), (4000.0, 40.0)])
def test_balanced_cpu_diskio_matches_oracle(r_cpu, r_io):
    disk_io, cpu, _ = make_cluster(12)
    stats = padded_stats(disk_io, cpu, pad=4)
    s = balanced_cpu_diskio(stats, jnp.asarray([r_cpu]), jnp.asarray([r_io]))
    want = oracle.balanced_cpu_diskio_oracle(disk_io, cpu, r_cpu, r_io)
    np.testing.assert_allclose(np.asarray(s)[0, :12], want, rtol=1e-5, atol=1e-5)


def test_balanced_cpu_diskio_truncation_parity():
    disk_io, cpu, _ = make_cluster(20)
    stats = padded_stats(disk_io, cpu)
    s = balanced_cpu_diskio(stats, jnp.asarray([300.0]), jnp.asarray([25.0]), truncate=True)
    want = oracle.balanced_cpu_diskio_oracle(disk_io, cpu, 300.0, 25.0, truncate=True)
    np.testing.assert_array_equal(np.asarray(s)[0], want)


def test_balanced_cpu_diskio_batched_pods():
    """The kernel scores P pods in one call == P oracle calls."""
    disk_io, cpu, _ = make_cluster(9)
    stats = padded_stats(disk_io, cpu, pad=7)
    r_cpu = np.array([100.0, 2000.0, 50.0])
    r_io = np.array([10.0, 5.0, 0.0])
    s = np.asarray(balanced_cpu_diskio(stats, jnp.asarray(r_cpu), jnp.asarray(r_io)))
    for p in range(3):
        want = oracle.balanced_cpu_diskio_oracle(disk_io, cpu, r_cpu[p], r_io[p])
        np.testing.assert_allclose(s[p, :9], want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n", [3, 17])
def test_balanced_diskio_matches_oracle(n):
    disk_io, cpu, _ = make_cluster(n)
    stats = padded_stats(disk_io, cpu, pad=3)
    mask = jnp.asarray(np.arange(n + 3) < n)
    d = jnp.asarray(np.concatenate([disk_io, np.zeros(3)]), jnp.float32)
    s = balanced_diskio(stats, d, jnp.asarray([12.0]), mask)
    want = oracle.balanced_diskio_oracle(disk_io, cpu, 12.0)
    np.testing.assert_allclose(np.asarray(s)[0, :n], want, rtol=2e-4, atol=2e-3)


def test_free_capacity_matches_oracle():
    disk_io, cpu, mem = make_cluster(15)
    s = free_capacity(jnp.asarray(cpu, jnp.float32), jnp.asarray(mem, jnp.float32), jnp.asarray(disk_io, jnp.float32))
    want = oracle.free_capacity_oracle(cpu, mem, disk_io)
    np.testing.assert_allclose(np.asarray(s), want, rtol=1e-5)
