"""Run the native test surface against the sanitized libyoda_host.so.

`make -C native asan` builds an ASan+UBSan-instrumented library
(-fno-sanitize-recover: any finding aborts the process and fails the
run); this test then re-executes tests/test_native.py in a subprocess
with

  YODA_NATIVE_LIB=native/build-asan/libyoda_host.so
  LD_PRELOAD=<libasan.so>          (the interpreter is uninstrumented)
  ASAN_OPTIONS=detect_leaks=0      (CPython "leaks" by design at exit)

so every queue/scalar-cycle/native-loop path — including the ctypes
boundary, where an overrun would otherwise corrupt silently — runs under
the sanitizers. Slow-marked: it is a full nested pytest run plus a
native rebuild.
"""

import os
import subprocess
import sys

import pytest

from kubernetes_scheduler_tpu import native

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "native")
ASAN_LIB = os.path.join(NATIVE_DIR, "build-asan", "libyoda_host.so")


def _libasan_path() -> str | None:
    try:
        out = subprocess.run(
            ["gcc", "-print-file-name=libasan.so"],
            capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except (subprocess.SubprocessError, OSError):
        return None
    return out if out and os.path.exists(out) else None


@pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)
def test_native_surface_under_asan_e2e():
    libasan = _libasan_path()
    if libasan is None:
        pytest.skip("libasan runtime not found")
    build = subprocess.run(
        ["make", "-C", NATIVE_DIR, "asan"],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr
    assert os.path.exists(ASAN_LIB)

    env = dict(os.environ)
    env.update(
        YODA_NATIVE_LIB=ASAN_LIB,
        LD_PRELOAD=libasan,
        ASAN_OPTIONS="detect_leaks=0:abort_on_error=1",
        JAX_PLATFORMS="cpu",
    )
    run = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_native.py",
            "-q", "-p", "no:cacheprovider",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert run.returncode == 0, (
        f"sanitized native tests failed\n--- stdout ---\n{run.stdout[-4000:]}"
        f"\n--- stderr ---\n{run.stderr[-4000:]}"
    )
    # the override really was in effect (not the plain build): the
    # subprocess suite must not have skipped for a missing library
    assert "skipped" not in run.stdout.splitlines()[-1]


@pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)
def test_tsan_build_target_links():
    """The TSan variant stays buildable (drift check for the Makefile
    target; running the full surface under TSan needs an instrumented
    interpreter, so the build is the gate here)."""
    build = subprocess.run(
        ["make", "-C", NATIVE_DIR, "tsan"],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr
    assert os.path.exists(
        os.path.join(NATIVE_DIR, "build-tsan", "libyoda_host.so")
    )
