"""Scalar golden oracle: an independent plain-Python port of the reference's
scoring math, written loop-by-loop from the Go formulas (not from our JAX
kernels) so kernel tests have something to disagree with.

Formula sources (all in /root/reference):
  - balanced_cpu_diskio: pkg/yoda/score/algorithm.go:99-119
  - stats (u_avg, M_tmp): pkg/yoda/score/algorithm.go:67-89
  - balanced_diskio:      pkg/yoda/score/algorithm.go:121-176
  - free_capacity:        pkg/yoda/score/algorithm.go:178-198
  - card scoring:         pkg/yoda/score/algorithm.go:264-291 (commented legacy)
  - card predicates:      pkg/yoda/filter/filter.go:11-58
  - min-max normalize:    pkg/yoda/scheduler.go:158-183
  - max collection:       pkg/yoda/collection/collection.go:30-76
"""

from __future__ import annotations

import math


def stats_oracle(disk_io, cpu_pct):
    u = [d / 50.0 for d in disk_io]
    v = [c / 100.0 for c in cpu_pct]
    u_avg = sum(u) / len(u)
    m_tmp = sum((ui - u_avg) ** 2 for ui in u) / len(u)
    return u, v, u_avg, m_tmp


def balanced_cpu_diskio_oracle(disk_io, cpu_pct, r_cpu, r_io, truncate=False):
    """Score of one pod against every node."""
    u, v, _, _ = stats_oracle(disk_io, cpu_pct)
    if r_io > 0:
        beta = 1.0 / (1.0 + r_cpu / r_io)
    else:
        beta = 0.0  # Go: Rcpu/0 = +Inf => beta = 0
    alpha = 1.0 - beta
    out = []
    for ui, vi in zip(u, v):
        li = abs(alpha * vi - beta * ui)
        si = 10.0 - 10.0 * li
        if truncate:
            si = float(int(si)) if si >= 0 else 0.0
        out.append(si)
    return out


def balanced_diskio_oracle(disk_io, cpu_pct, r_io):
    u, _, u_avg, m_tmp = stats_oracle(disk_io, cpu_pct)
    n = len(disk_io)
    m_max, m_min = 0.0, 1000000.0  # sentinel seeds, algorithm.go:122-123
    ms = []
    for j in range(n):
        tj = disk_io[j] + r_io
        fj = tj / 100.0
        uj = u[j]
        f_avg = u_avg - (uj - fj) / n
        mj = m_tmp - ((uj - u_avg) ** 2 - (fj - f_avg) ** 2) / n
        m_max = max(m_max, mj)
        m_min = min(m_min, mj)
        ms.append(mj)
    return [100.0 - (100.0 * (m - m_min) / (m_max - m_min)) for m in ms]


def free_capacity_oracle(cpu_pct, mem_pct, disk_io):
    out = []
    for c, m, d in zip(cpu_pct, mem_pct, disk_io):
        out.append(100 * (100 - int(d)) + 2 * (100 - c) + 3 * (100 - m))
    return out


def normalize_oracle(scores, max_node_score=100.0):
    highest = 0.0
    lowest = scores[0]
    for s in scores:
        lowest = min(lowest, s)
        highest = max(highest, s)
    if highest == lowest:
        lowest -= 1
    return [(s - lowest) * max_node_score / (highest - lowest) for s in scores]


# --- GPU-card path -----------------------------------------------------------
# A card is a dict: bandwidth, clock, core, power, free_memory, total_memory,
# healthy (bool). A node is a list of cards.


def card_fits_memory(card, memory):
    return card["healthy"] and card["free_memory"] >= memory  # filter.go:52-54


def card_fits_clock(card, clock):
    return card["healthy"] and card["clock"] == clock  # filter.go:56-58


def pod_fits_node_oracle(cards, want_number, want_memory, want_clock):
    """filter.go:11-50 against one node's card list.

    want_memory / want_clock = -1 encodes "label absent" (the reference
    gates on label presence, filter.go:19,36); a present-but-zero label is
    a real demand (FreeMemory >= 0 from healthy cards / Clock == 0).
    want_number = 0 encodes a pod with no GPU demand.
    """
    if want_number == 0:
        return True
    if want_number > len(cards):
        return False
    if want_memory >= 0:
        if sum(1 for c in cards if card_fits_memory(c, want_memory)) < want_number:
            return False
    if want_clock >= 0:
        if sum(1 for c in cards if card_fits_clock(c, want_clock)) < want_number:
            return False
    return True


def collect_max_oracle(nodes, want_number, want_memory, want_clock):
    """collection.go:30-55: maxima over fitting cards of fitting nodes.

    The demands used for card admission are the PodFits* return values,
    which are 0 for absent labels (filter.go:32,49) — clamp -1 to 0.
    """
    mem = max(want_memory, 0)
    clock = max(want_clock, 0)
    maxima = dict(
        bandwidth=1, clock=1, core=1, power=1, free_memory=1, total_memory=1
    )
    for cards in nodes:
        if not pod_fits_node_oracle(cards, want_number, want_memory, want_clock):
            continue
        for c in cards:
            if c["free_memory"] >= mem and c["clock"] >= clock:
                for k in maxima:
                    maxima[k] = max(maxima[k], c[k])
    return maxima


def card_score_oracle(cards, maxima, want_memory, want_clock,
                      reference_clock_bug=False, integer_parity=False):
    """algorithm.go:264-291 for one node: sum of per-card weighted scores
    over cards meeting the (>=) demands. Note the reference does not check
    card health in this loop (algorithm.go:270-272), and its arithmetic is
    uint division — metric*100/max floors (integer_parity=True)."""
    mem = max(want_memory, 0)
    clock = max(want_clock, 0)
    total = 0.0
    div = (lambda a, b: a * 100 // b) if integer_parity else (lambda a, b: a * 100 / b)
    clock_denom = maxima["bandwidth"] if reference_clock_bug else maxima["clock"]
    for c in cards:
        if not (c["free_memory"] >= mem and c["clock"] >= clock):
            continue
        total += (
            div(c["bandwidth"], maxima["bandwidth"]) * 1
            + div(c["clock"], clock_denom) * 1
            + div(c["core"], maxima["core"]) * 2
            + div(c["power"], maxima["power"]) * 1
            + div(c["free_memory"], maxima["free_memory"]) * 3
            + div(c["total_memory"], maxima["total_memory"]) * 1
        )
    return total


# --- constraint predicates (upstream Kubernetes semantics) -------------------
# taints: list of (key, value, effect); effect 1=NoSchedule 2=Prefer 3=NoExecute
# tolerations: list of (key, value, op, effect); op 0=Exists 1=Equal;
#   key None = wildcard; effect 0 = all


def toleration_tolerates(tol, taint):
    key, value, op, effect = tol
    t_key, t_value, t_effect = taint
    if effect != 0 and effect != t_effect:
        return False
    if key is None:
        return op == 0  # empty key + Exists tolerates everything
    if key != t_key:
        return False
    return op == 0 or value == t_value


def taint_fit_oracle(taints, tolerations):
    for taint in taints:
        if taint[2] not in (1, 3):  # only NoSchedule/NoExecute filter
            continue
        if not any(toleration_tolerates(t, taint) for t in tolerations):
            return False
    return True


def node_affinity_fit_oracle(node_labels, exprs):
    """node_labels: dict key->value; exprs: list of (key, op, values) with
    op 0=In 1=NotIn 2=Exists 3=DoesNotExist; ANDed."""
    for key, op, values in exprs:
        present = key in node_labels
        if op == 0:  # In
            if not (present and node_labels[key] in values):
                return False
        elif op == 1:  # NotIn
            if present and node_labels[key] in values:
                return False
        elif op == 2:  # Exists
            if not present:
                return False
        elif op == 3:  # DoesNotExist
            if present:
                return False
    return True


def node_affinity_terms_oracle(node_labels, terms):
    """Upstream OR-of-ANDs nodeSelectorTerms: terms is a list of
    expression AND-lists (see node_affinity_fit_oracle); a node passes
    iff SOME term's expressions all hold. No terms at all = pass."""
    if not terms:
        return True
    return any(node_affinity_fit_oracle(node_labels, t) for t in terms)


def greedy_assign_oracle(scores, feasible, pod_request, node_free, priority):
    """Reference-semantics sequential scheduling: pods in priority order
    (sort.go:8-18, stable on queue order), each binds to its best feasible
    node with remaining capacity."""
    p = len(scores)
    free = [list(row) for row in node_free]
    order = sorted(range(p), key=lambda i: (-priority[i], i))
    out = [-1] * p
    for i in order:
        best, best_s = -1, -math.inf
        for j in range(len(free)):
            if not feasible[i][j]:
                continue
            if any(pod_request[i][r] > free[j][r] for r in range(len(free[j]))):
                continue
            if scores[i][j] > best_s:
                best, best_s = j, scores[i][j]
        if best >= 0:
            out[i] = best
            for r in range(len(free[best])):
                free[best][r] -= pod_request[i][r]
    return out
