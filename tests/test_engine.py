"""Engine tests: the full batched cycle, single-device and sharded."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_scheduler_tpu.engine import (
    make_pod_batch,
    make_snapshot,
    schedule_batch,
    schedule_windows,
    stack_windows,
)
from kubernetes_scheduler_tpu.parallel import make_mesh, make_sharded_schedule_fn
from tests import oracle

RNG = np.random.default_rng(3)


def random_state(n, p, r=3, c=2, gpu=False):
    snapshot = make_snapshot(
        allocatable=RNG.integers(4000, 16000, (n, r)).astype(np.float32),
        requested=RNG.integers(0, 4000, (n, r)).astype(np.float32),
        disk_io=RNG.uniform(0, 50, n),
        cpu_pct=RNG.uniform(0, 100, n),
        mem_pct=RNG.uniform(0, 100, n),
        net_up=RNG.uniform(0, 10, n),
        net_down=RNG.uniform(0, 10, n),
        cards=RNG.integers(1, 1000, (n, c, 6)),
        card_mask=RNG.random((n, c)) > 0.3,
        card_healthy=RNG.random((n, c)) > 0.2,
    )
    pods = make_pod_batch(
        request=RNG.integers(100, 3000, (p, r)),
        r_io=RNG.uniform(0, 40, p),
        priority=RNG.integers(0, 10, p),
        want_number=RNG.integers(0, 3, p) if gpu else np.zeros(p),
    )
    return snapshot, pods


def test_schedule_batch_end_to_end():
    snapshot, pods = random_state(32, 10)
    res = schedule_batch(snapshot, pods)
    idx = np.asarray(res.node_idx)
    # every assigned pod's node was feasible
    feas = np.asarray(res.feasible)
    for i, j in enumerate(idx):
        if j >= 0:
            assert feas[i, j]
    # capacity respected
    free = np.asarray(snapshot.allocatable - snapshot.requested)
    used = np.zeros_like(free)
    for i, j in enumerate(idx):
        if j >= 0:
            used[j] += np.asarray(pods.request)[i]
    assert (used <= free + 1e-3).all()


def test_schedule_batch_matches_scalar_oracle_pipeline():
    """The engine's assignment equals the scalar oracle run on the engine's
    own (oracle-verified) score/feasibility matrices."""
    snapshot, pods = random_state(24, 8)
    res = schedule_batch(snapshot, pods)
    want = oracle.greedy_assign_oracle(
        np.asarray(res.scores).tolist(),
        np.asarray(res.feasible).tolist(),
        np.asarray(pods.request).tolist(),
        np.asarray(
            jnp.where(snapshot.node_mask[:, None],
                      snapshot.allocatable - snapshot.requested, 0.0)
        ).tolist(),
        np.asarray(pods.priority).tolist(),
    )
    assert np.asarray(res.node_idx).tolist() == want


def test_schedule_windows_matches_sequential_batches():
    """The fused scan over windows makes the same decisions as running
    schedule_batch per window with capacity carried on the host."""
    snapshot, pods = random_state(40, 24)
    windows = stack_windows(pods, 8)
    fused = schedule_windows(snapshot, windows, assigner="greedy")

    requested = snapshot.requested
    seq_idx, total = [], 0
    for w in range(3):
        one = type(pods)(*[jnp.asarray(f)[w] for f in windows])
        res = schedule_batch(
            snapshot._replace(requested=requested), one,
            assigner="greedy", normalizer="none",
        )
        requested = snapshot.allocatable - res.free_after
        seq_idx.append(np.asarray(res.node_idx))
        total += int(res.n_assigned)

    np.testing.assert_array_equal(
        np.asarray(fused.node_idx), np.stack(seq_idx)
    )
    assert int(fused.n_assigned) == total
    np.testing.assert_allclose(
        np.asarray(fused.free_after),
        np.asarray(snapshot.allocatable - requested),
        atol=1e-3,
    )


def test_schedule_windows_carries_anti_affinity_across_windows():
    """A window-1 pod with hard anti-affinity to a selector must see
    window-0 placements, not the stale pre-backlog domain counts."""
    n, s = 4, 1
    snapshot = make_snapshot(
        allocatable=np.full((n, 3), 1e6, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.zeros(n),
        cpu_pct=np.zeros(n),
        mem_pct=np.zeros(n),
        domain_counts=np.zeros((n, s), np.float32),
        # all nodes in ONE topology domain (represented by node 0)
        domain_id=np.zeros((n, s), np.int32),
    )
    # window 0: one pod matching selector 0; window 1: one pod with hard
    # anti-affinity against selector 0 (fits nowhere once pod 0 lands)
    pods = make_pod_batch(
        request=np.ones((2, 3), np.float32),
        pod_matches=np.asarray([[True], [False]]),
        anti_affinity_sel=np.asarray([[-1], [0]], np.int32),
    )
    res = schedule_windows(
        snapshot, stack_windows(pods, 1), assigner="greedy"
    )
    idx = np.asarray(res.node_idx).ravel()
    assert idx[0] >= 0
    assert idx[1] == -1, "anti-affinity ignored window 0's placement"
    assert int(res.n_assigned) == 1


def test_windows_auction_knobs_traced_not_static():
    """schedule_windows must trace auction_rounds/auction_price_frac like
    schedule_batch does (round-3 verdict: a runtime knob change recompiled
    the whole backlog program on one surface and not the other)."""
    snapshot, pods = random_state(16, 8)
    windows = stack_windows(pods, 4)
    schedule_windows.clear_cache()
    r1 = schedule_windows(
        snapshot, windows, auction_price_frac=1.0 / 16.0, auction_rounds=1024
    )
    n1 = schedule_windows._cache_size()
    r2 = schedule_windows(
        snapshot, windows, auction_price_frac=1.0, auction_rounds=64
    )
    assert schedule_windows._cache_size() == n1, (
        "auction knob change recompiled schedule_windows"
    )
    assert int(r1.n_assigned) >= 0 and int(r2.n_assigned) >= 0


def test_stack_windows_rejects_ragged():
    _, pods = random_state(4, 10)
    with pytest.raises(ValueError):
        stack_windows(pods, 4)


@pytest.mark.parametrize("policy", ["balanced_cpu_diskio", "balanced_diskio", "free_capacity", "card"])
def test_sharded_engine_matches_single_device(policy):
    assert jax.device_count() == 8, "conftest must force 8 cpu devices"
    n, p = 64, 6
    snapshot, pods = random_state(n, p, gpu=(policy == "card"))
    single = schedule_batch(snapshot, pods, policy=policy)
    mesh = make_mesh(8)
    sharded_fn = make_sharded_schedule_fn(mesh, policy=policy)
    sharded = sharded_fn(snapshot, pods)
    # psum/pmax reduce in a different order than a single-device sum, so
    # float32 results agree only to ~1e-3 absolute.
    np.testing.assert_allclose(
        np.asarray(sharded.raw_scores), np.asarray(single.raw_scores),
        rtol=1e-4, atol=2e-3,
    )
    np.testing.assert_allclose(
        np.asarray(sharded.scores), np.asarray(single.scores),
        rtol=1e-4, atol=2e-3,
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.feasible), np.asarray(single.feasible)
    )
    assert np.asarray(sharded.node_idx).tolist() == np.asarray(single.node_idx).tolist()
    np.testing.assert_allclose(
        np.asarray(sharded.free_after), np.asarray(single.free_after), atol=1e-3
    )


def test_sharded_windows_matches_dense_schedule_windows():
    """Whole-backlog scheduling on the 8-device mesh: the sharded
    multi-window scan (capacity + affinity carries threaded across
    windows) must make exactly the dense schedule_windows decisions,
    constraint families included."""
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snapshot = gen_cluster(64, seed=5, constraints=True)
    pods = gen_pods(24, seed=6, constraints=True)
    windows = stack_windows(pods, 8)
    dense = schedule_windows(
        snapshot, windows, assigner="greedy", affinity_aware=True,
        normalizer="none",
    )
    mesh = make_mesh(8)
    fn = make_sharded_windows_fn(mesh, normalizer="min_max")
    sharded = fn(snapshot, windows)
    np.testing.assert_array_equal(
        np.asarray(sharded.node_idx), np.asarray(dense.node_idx)
    )
    assert int(sharded.n_assigned) == int(dense.n_assigned)
    np.testing.assert_allclose(
        np.asarray(sharded.free_after)[np.asarray(snapshot.node_mask)],
        np.asarray(dense.free_after)[np.asarray(snapshot.node_mask)],
        atol=1e-2,
    )


def test_sharded_windows_soft_sees_earlier_window_placements():
    """soft=True across windows: preferred inter-pod affinity toward a
    pod PLACED IN AN EARLIER WINDOW must boost that pod's domain, exactly
    as the dense scan does (which folds placements into its carried
    domain counts before scoring) — the carry must reach the soft terms,
    not only the hard masks."""
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn

    n, s = 8, 1
    # two topology domains: nodes 0-3 (rep 0) and 4-7 (rep 4); base
    # scores strictly favor domain B (higher disk_io balances the
    # r_io-less pods' alpha-heavy score toward low-CPU nodes — simpler:
    # make domain A's CPU% higher so its base score is lower)
    snapshot = make_snapshot(
        allocatable=np.full((n, 3), 1e6, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.zeros(n),
        cpu_pct=np.asarray([50.0] * 4 + [0.0] * 4),
        mem_pct=np.zeros(n),
        domain_counts=np.zeros((n, s), np.float32),
        domain_id=np.repeat([0, 4], 4)[:, None].astype(np.int32),
    )
    # window 0: pod A matches selector 0 and is PINNED to node 1
    # (domain A, the low-score domain). window 1: pod B prefers
    # selector 0 with a weight that dwarfs the base-score gap.
    pods = make_pod_batch(
        request=np.ones((2, 3), np.float32),
        pod_matches=np.asarray([[True], [False]]),
        target_node=np.asarray([1, -1], np.int32),
        pref_affinity_sel=np.asarray([[-1], [0]], np.int32),
        pref_affinity_weight=np.asarray([[0.0], [1000.0]], np.float32),
    )
    windows = stack_windows(pods, 1)
    dense = schedule_windows(
        snapshot, windows, assigner="greedy", affinity_aware=True,
        normalizer="min_max", soft=True,
    )
    didx = np.asarray(dense.node_idx).ravel()
    assert didx[0] == 1
    assert 0 <= didx[1] < 4, "dense soft carry should pull B into domain A"

    fn = make_sharded_windows_fn(make_mesh(8), soft=True)
    sharded = fn(snapshot, windows)
    np.testing.assert_array_equal(np.asarray(sharded.node_idx), didx.reshape(2, 1))


def test_sharded_windows_carries_anti_affinity_across_windows():
    """Sharded mirror of the dense cross-window anti-affinity test: a
    window-1 avoider must see window-0's placement through the carried
    [2, n_global, S] table, across shard boundaries."""
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn

    n, s = 8, 1
    snapshot = make_snapshot(
        allocatable=np.full((n, 3), 1e6, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.zeros(n),
        cpu_pct=np.zeros(n),
        mem_pct=np.zeros(n),
        domain_counts=np.zeros((n, s), np.float32),
        domain_id=np.zeros((n, s), np.int32),  # one global domain
    )
    pods = make_pod_batch(
        request=np.ones((2, 3), np.float32),
        pod_matches=np.asarray([[True], [False]]),
        anti_affinity_sel=np.asarray([[-1], [0]], np.int32),
    )
    mesh = make_mesh(8)
    fn = make_sharded_windows_fn(mesh)
    res = fn(snapshot, stack_windows(pods, 1))
    idx = np.asarray(res.node_idx).ravel()
    assert idx[0] >= 0
    assert idx[1] == -1, "anti-affinity ignored window 0's placement"
    assert int(res.n_assigned) == 1


def test_sharded_auction_matches_dense_auction():
    """The distributed auction must make bit-identical decisions to the
    dense auction_assign path (the tie-break jitter is a counter-based
    hash of global coordinates, so shards see the dense path's values)."""
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    assert jax.device_count() == 8
    snapshot = gen_cluster(64, seed=5, constraints=True)
    pods = gen_pods(12, seed=6, constraints=True)
    dense = schedule_batch(snapshot, pods, assigner="auction", affinity_aware=True)
    sharded = make_sharded_schedule_fn(make_mesh(8), assigner="auction")(
        snapshot, pods
    )
    assert (
        np.asarray(sharded.node_idx).tolist()
        == np.asarray(dense.node_idx).tolist()
    )
    np.testing.assert_allclose(
        np.asarray(sharded.free_after), np.asarray(dense.free_after), atol=1e-3
    )


def test_sharded_auction_contention_spreads_across_shards():
    """Hot-node contention: many identical pods all preferring one node
    must spread via prices to nodes on OTHER shards, and the result must
    match the dense auction exactly (admission + repricing cross the
    shard boundary correctly)."""
    n, p, r = 16, 12, 2
    # node 3 (shard 0) scores highest for everyone; capacity fits 2 pods
    # per node, so most pods must overflow to other shards' nodes
    score = np.zeros((p, n), np.float32)
    score[:, 3] = 10.0
    score[:, :] += np.linspace(0, 1, n)[None, :]
    snapshot = make_snapshot(
        allocatable=np.full((n, r), 2000.0, np.float32),
        requested=np.zeros((n, r), np.float32),
        disk_io=np.zeros(n),
        cpu_pct=np.linspace(0, 50, n),
        mem_pct=np.zeros(n),
    )
    pods = make_pod_batch(request=np.full((p, r), 1000.0, np.float32))
    dense = schedule_batch(
        snapshot, pods, assigner="auction", policy="free_capacity"
    )
    sharded = make_sharded_schedule_fn(
        make_mesh(8), assigner="auction", policy="free_capacity"
    )(snapshot, pods)
    didx = np.asarray(dense.node_idx)
    sidx = np.asarray(sharded.node_idx)
    assert sidx.tolist() == didx.tolist()
    assert (sidx >= 0).all(), "capacity exists for every pod"
    # capacity respected: at most 2 pods per node
    counts = np.bincount(sidx, minlength=n)
    assert counts.max() <= 2
    # contention actually crossed shards (nodes 0-7 are shards 0-3)
    assert len({i // 2 for i in sidx}) >= 3


def test_sharded_windows_auction_matches_dense():
    """Whole-backlog scheduling with the AUCTION assigner on the mesh:
    cross-window capacity + (anti)affinity carries must thread through
    the distributed auction exactly as dense schedule_windows does."""
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snapshot = gen_cluster(64, seed=5, constraints=True)
    pods = gen_pods(24, seed=6, constraints=True)
    windows = stack_windows(pods, 8)
    dense = schedule_windows(
        snapshot, windows, assigner="auction", affinity_aware=True,
        normalizer="none",
    )
    sharded = make_sharded_windows_fn(make_mesh(8), assigner="auction")(
        snapshot, windows
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.node_idx), np.asarray(dense.node_idx)
    )
    assert int(sharded.n_assigned) == int(dense.n_assigned)
    np.testing.assert_allclose(
        np.asarray(sharded.free_after)[np.asarray(snapshot.node_mask)],
        np.asarray(dense.free_after)[np.asarray(snapshot.node_mask)],
        atol=1e-2,
    )


def test_sharded_windows_auction_carries_anti_affinity():
    """A window-1 avoider must see window-0's placement through the
    auction's carried [2, n_global, S] table, across shard boundaries."""
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn

    n, s = 8, 1
    snapshot = make_snapshot(
        allocatable=np.full((n, 3), 1e6, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.zeros(n),
        cpu_pct=np.zeros(n),
        mem_pct=np.zeros(n),
        domain_counts=np.zeros((n, s), np.float32),
        domain_id=np.zeros((n, s), np.int32),  # one global domain
    )
    pods = make_pod_batch(
        request=np.ones((2, 3), np.float32),
        pod_matches=np.asarray([[True], [False]]),
        anti_affinity_sel=np.asarray([[-1], [0]], np.int32),
    )
    fn = make_sharded_windows_fn(make_mesh(8), assigner="auction")
    res = fn(snapshot, stack_windows(pods, 1))
    idx = np.asarray(res.node_idx).ravel()
    assert idx[0] >= 0
    assert idx[1] == -1, "anti-affinity ignored window 0's placement"
    assert int(res.n_assigned) == 1


@pytest.mark.parametrize("assigner", ["greedy", "auction"])
def test_sharded_fused_matches_dense_fused(assigner):
    """The fused Pallas score+fit kernel on the mesh: the formula is
    node-local, so the kernel shards with zero extra collectives and must
    reproduce the dense fused decisions under both assigners."""
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    assert jax.device_count() == 8
    snap = gen_cluster(64, seed=5, constraints=True)
    pods = gen_pods(12, seed=6, constraints=True)
    dense = schedule_batch(
        snap, pods, assigner=assigner, normalizer="none", fused=True,
        affinity_aware=True,
    )
    sharded = make_sharded_schedule_fn(
        make_mesh(8), assigner=assigner, normalizer="none", fused=True
    )(snap, pods)
    assert (
        np.asarray(sharded.node_idx).tolist()
        == np.asarray(dense.node_idx).tolist()
    )
    np.testing.assert_allclose(
        np.asarray(sharded.free_after), np.asarray(dense.free_after), atol=1e-3
    )


def test_sharded_fused_windows_and_validation():
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(64, seed=5, constraints=True)
    w = stack_windows(gen_pods(24, seed=7, constraints=True), 8)
    dw = schedule_windows(snap, w, assigner="auction", normalizer="none",
                          fused=True)
    sw = make_sharded_windows_fn(
        make_mesh(8), assigner="auction", normalizer="none", fused=True
    )(snap, w)
    np.testing.assert_array_equal(
        np.asarray(sw.node_idx), np.asarray(dw.node_idx)
    )
    # the dense fused contract applies on the mesh too
    with pytest.raises(ValueError, match="normalizer"):
        make_sharded_schedule_fn(make_mesh(8), fused=True)
    with pytest.raises(ValueError, match="balanced_cpu_diskio"):
        make_sharded_schedule_fn(
            make_mesh(8), fused=True, policy="card", normalizer="none"
        )


def test_sharded_fused_soft_matches_dense():
    """fused + soft on the mesh: the soft terms (incl. the pmin'd spread
    dmin) layer onto the NEG-masked fused matrix exactly as the dense
    fused path does."""
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(64, seed=31, constraints=True)
    pods = gen_pods(10, seed=32, constraints=True)
    pods = pods._replace(
        soft_spread_sel=jnp.zeros((10, 1), jnp.int32),
        pref_affinity_sel=jnp.asarray(
            np.where(np.arange(10)[:, None] % 3 == 0, 1, -1), jnp.int32
        ),
        pref_affinity_weight=jnp.full((10, 1), 9.0, jnp.float32),
    )
    dense = schedule_batch(
        snap, pods, assigner="auction", normalizer="none", fused=True,
        affinity_aware=True, soft=True,
    )
    sharded = make_sharded_schedule_fn(
        make_mesh(8), assigner="auction", normalizer="none", fused=True,
        soft=True,
    )(snap, pods)
    assert (
        np.asarray(sharded.node_idx).tolist()
        == np.asarray(dense.node_idx).tolist()
    )


def test_sharded_grouped_preferred_terms_match_dense():
    """Multi-expression preferred node-affinity terms (pna_term groups)
    score identically on the mesh — the grouped contraction is
    node-local, so decisions and soft scores must match dense exactly."""
    n = 16
    labels = np.zeros((n, 2, 2), np.int32)
    lmask = np.zeros((n, 2), bool)
    # nodes 12..15 carry BOTH keys (full term match); 4..11 only key 3
    labels[4:, 0] = (3, 7)
    lmask[4:, 0] = True
    labels[12:, 1] = (4, 1)
    lmask[12:, 1] = True
    snapshot = make_snapshot(
        allocatable=np.full((n, 3), 1e6, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.zeros(n),
        cpu_pct=np.zeros(n),
        mem_pct=np.zeros(n),
        node_labels=labels,
        node_label_mask=lmask,
    )
    from kubernetes_scheduler_tpu.ops.constraints import OP_EXISTS, OP_IN

    pods = make_pod_batch(
        request=np.ones((2, 3), np.float32),
        pna_key=np.asarray([[3, 4], [3, 4]], np.int32),
        pna_op=np.asarray([[OP_IN, OP_EXISTS]] * 2, np.int32),
        pna_vals=np.asarray([[[7], [0]]] * 2, np.int32),
        pna_val_mask=np.asarray([[[True], [False]]] * 2),
        pna_weight=np.full((2, 2), 50.0, np.float32),
        # pod 0: one AND group (weight once, only full matches);
        # pod 1: independent terms (weights add)
        pna_term=np.asarray([[0, 0], [0, 1]], np.int32),
    )
    dense = schedule_batch(snapshot, pods, soft=True)
    sharded = make_sharded_schedule_fn(make_mesh(8), soft=True)(snapshot, pods)
    assert (
        np.asarray(sharded.node_idx).tolist()
        == np.asarray(dense.node_idx).tolist()
    )
    np.testing.assert_allclose(
        np.asarray(sharded.scores), np.asarray(dense.scores),
        rtol=1e-4, atol=2e-3,
    )
    # the grouped pod must land on a BOTH-keys node
    assert int(dense.node_idx[0]) >= 12


def test_sharded_soft_spread_global_dmin():
    """ScheduleAnyway spread on the mesh: the marginal-skew term's
    min-over-domains must be GLOBAL (domains span shards) — a pod must
    prefer the emptier domain even when that domain's nodes live
    entirely on other shards."""
    n, s = 16, 1
    # domain A = nodes 0-7 (shards 0-3), domain B = nodes 8-15; A holds
    # 3 matching pods, B none. A shard seeing only A-nodes would compute
    # dmin=3 locally and zero skew — the global dmin is 0.
    snapshot = make_snapshot(
        allocatable=np.full((n, 3), 1e6, np.float32),
        requested=np.zeros((n, 3), np.float32),
        disk_io=np.zeros(n),
        cpu_pct=np.zeros(n),
        mem_pct=np.zeros(n),
        domain_counts=np.asarray([[3.0]] * 8 + [[0.0]] * 8, np.float32),
        domain_id=np.asarray([0] * 8 + [8] * 8, np.int32)[:, None],
    )
    pods = make_pod_batch(
        request=np.ones((1, 3), np.float32),
        soft_spread_sel=np.zeros((1, 1), np.int32),
    )
    dense = schedule_batch(snapshot, pods, soft=True)
    assert int(dense.node_idx[0]) >= 8, "dense soft spread must pick B"
    sharded = make_sharded_schedule_fn(make_mesh(8), soft=True)(snapshot, pods)
    assert int(sharded.node_idx[0]) == int(dense.node_idx[0])
    np.testing.assert_allclose(
        np.asarray(sharded.scores), np.asarray(dense.scores),
        rtol=1e-4, atol=2e-3,
    )


@pytest.mark.parametrize("normalizer", ["softmax", "none"])
def test_sharded_normalizers_match_single_device(normalizer):
    snapshot, pods = random_state(64, 6)
    single = schedule_batch(snapshot, pods, normalizer=normalizer)
    sharded = make_sharded_schedule_fn(make_mesh(8), normalizer=normalizer)(
        snapshot, pods
    )
    np.testing.assert_allclose(
        np.asarray(sharded.scores), np.asarray(single.scores),
        rtol=1e-4, atol=1e-5,
    )
    assert np.asarray(sharded.node_idx).tolist() == np.asarray(single.node_idx).tolist()


def test_sharded_engine_padded_nodes():
    """Real node count not divisible by the mesh: padding spread across
    shards must not change results."""
    n_real, n_pad, p = 50, 64, 5
    snapshot, pods = random_state(n_pad, p)
    mask = np.zeros(n_pad, bool)
    mask[:n_real] = True
    snapshot = snapshot._replace(node_mask=jnp.asarray(mask))
    single = schedule_batch(snapshot, pods)
    sharded = make_sharded_schedule_fn(make_mesh(8))(snapshot, pods)
    assert np.asarray(sharded.node_idx).tolist() == np.asarray(single.node_idx).tolist()
    np.testing.assert_allclose(
        np.asarray(sharded.scores)[:, :n_real],
        np.asarray(single.scores)[:, :n_real],
        rtol=1e-5, atol=1e-4,
    )
    assert (np.asarray(sharded.node_idx) < n_real).all()


@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
@pytest.mark.parametrize("assigner", ["greedy", "auction"])
def test_multihost_mesh_matches_single_device(shape, assigner):
    """2-D (dcn, node) hierarchical mesh — the multi-host layout — must
    produce the same decisions as single-device, under BOTH assigners."""
    from kubernetes_scheduler_tpu.parallel.mesh import (
        DCN_AXIS, NODE_AXIS, make_mesh_multihost,
    )

    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snapshot = gen_cluster(64, seed=21, constraints=True)
    pods = gen_pods(6, seed=22, constraints=True)
    single = schedule_batch(snapshot, pods, assigner=assigner)
    mesh = make_mesh_multihost(*shape)
    assert mesh.axis_names == (DCN_AXIS, NODE_AXIS)
    fn = make_sharded_schedule_fn(
        mesh, node_axes=(DCN_AXIS, NODE_AXIS), assigner=assigner
    )
    sharded = fn(snapshot, pods)
    np.testing.assert_array_equal(
        np.asarray(sharded.feasible), np.asarray(single.feasible)
    )
    assert np.asarray(sharded.node_idx).tolist() == np.asarray(single.node_idx).tolist()
    np.testing.assert_allclose(
        np.asarray(sharded.free_after), np.asarray(single.free_after), atol=1e-3
    )


def test_sharded_fn_rejects_missing_axis():
    with pytest.raises(ValueError, match="lacks axes"):
        make_sharded_schedule_fn(make_mesh(8), node_axes=("dcn", "node"))


def test_dense_node_name_pinning():
    """spec.nodeName (upstream NodeName filter) on the dense path: a pinned
    pod lands on its node even when higher-scoring nodes exist; pinning to
    an absent node (encoded >= n) makes the pod unschedulable; -1 leaves
    the pod unconstrained."""
    n, p = 16, 3
    snapshot, pods = random_state(n, p)
    # generous capacity: every node feasible for every pod, so the pin
    # target is independent of the shared RNG stream (this test must not
    # depend on which tests ran before it)
    snapshot = snapshot._replace(
        allocatable=jnp.full_like(snapshot.allocatable, 1e6),
        requested=jnp.zeros_like(snapshot.requested),
    )
    free = schedule_batch(snapshot, pods)
    natural = int(np.asarray(free.node_idx)[0])
    pin = (natural + 1) % n  # NOT pod 0's natural (highest-score) choice
    target = np.array([pin, -1, n + 7], np.int32)
    pods = pods._replace(target_node=jnp.asarray(target))
    res = schedule_batch(snapshot, pods)
    idx = np.asarray(res.node_idx)
    feas = np.asarray(res.feasible)
    assert idx[0] == pin, idx
    assert feas[0].sum() <= 1 and feas[0][pin]
    assert idx[2] == -1 and not feas[2].any()
    assert idx[1] >= 0  # unpinned pod unaffected by others' pins


@pytest.mark.parametrize("assigner", ["greedy", "auction"])
def test_dense_node_name_pinning_assigners(assigner):
    """Pinning must hold under both dense assigners."""
    n, p = 12, 4
    snapshot, pods = random_state(n, p)
    target = np.array([5, -1, 5, n + 1], np.int32)
    pods = pods._replace(target_node=jnp.asarray(target))
    res = schedule_batch(snapshot, pods, assigner=assigner)
    idx = np.asarray(res.node_idx)
    assert idx[0] in (5, -1) and idx[2] in (5, -1)
    assert idx[3] == -1
    # both pods pinned to node 5 cannot land elsewhere, and capacity
    # permitting at least one of them takes it
    assert (idx[0] == 5) or (idx[2] == 5)


def test_sharded_node_name_matches_single_device():
    """target_node is a GLOBAL index; the sharded path must translate it to
    shard-local columns (a global pin must not vanish off-shard or match
    one node on every shard). Pins cover every shard of the 8-way mesh plus
    the absent-node encoding."""
    assert jax.device_count() == 8
    n, p = 64, 10
    snapshot, pods = random_state(n, p)
    # pins: one per shard boundary region, an absent node, and unpinned
    target = np.array([0, 7, 8, 15, 33, 56, 63, n + 2, -1, -1], np.int32)
    pods = pods._replace(target_node=jnp.asarray(target))
    single = schedule_batch(snapshot, pods)
    sharded = make_sharded_schedule_fn(make_mesh(8))(snapshot, pods)
    np.testing.assert_array_equal(
        np.asarray(sharded.feasible), np.asarray(single.feasible)
    )
    assert np.asarray(sharded.node_idx).tolist() == np.asarray(single.node_idx).tolist()
    idx = np.asarray(sharded.node_idx)
    for i in range(8):
        assert idx[i] in (target[i], -1)
    assert idx[7] == -1


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("assigner", ["greedy", "auction"])
def test_sharded_full_constraint_parity_sweep(seed, assigner):
    """Randomized dense-vs-sharded parity across EVERY constraint family
    at once: taints/tolerations, node affinity, inter-pod (anti)affinity
    with in-window interaction, topology spread, spec.nodeName pinning,
    and soft (preferred) terms — on the 8-device mesh, under BOTH
    assigners. The sharded engine must make byte-identical decisions to
    the dense path."""
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    assert jax.device_count() == 8
    rng = np.random.default_rng(100 + seed)
    n, p = 64, 12
    snapshot = gen_cluster(n, seed=seed, constraints=True)
    pods = gen_pods(p, seed=seed + 1, constraints=True)
    # spread constraints on ~25% of pods
    pods = pods._replace(
        spread_sel=jnp.asarray(
            np.where(rng.random((p, 1)) < 0.25, rng.integers(0, 8, (p, 1)), -1),
            jnp.int32,
        ),
        spread_max=jnp.full((p, 1), 2, jnp.int32),
        # pinning: a couple of pods pinned, one to an absent node
        target_node=jnp.asarray(
            np.where(
                rng.random(p) < 0.2, rng.integers(0, n + 4, p), -1
            ),
            jnp.int32,
        ),
        # preferred inter-pod terms on ~30%
        pref_affinity_sel=jnp.asarray(
            np.where(rng.random((p, 1)) < 0.3, rng.integers(0, 8, (p, 1)), -1),
            jnp.int32,
        ),
        pref_affinity_weight=jnp.full((p, 1), 7, jnp.int32),
        pref_anti_sel=jnp.asarray(
            np.where(rng.random((p, 1)) < 0.3, rng.integers(0, 8, (p, 1)), -1),
            jnp.int32,
        ),
        pref_anti_weight=jnp.full((p, 1), 5, jnp.int32),
    )
    # existing pods' preferred terms (symmetric scoring half)
    snapshot = snapshot._replace(
        pref_attract=jnp.asarray(
            (rng.random((n, 8)) < 0.1) * rng.integers(1, 5, (n, 8)), jnp.float32
        ),
        pref_avoid=jnp.asarray(
            (rng.random((n, 8)) < 0.1) * rng.integers(1, 5, (n, 8)), jnp.float32
        ),
    )
    single = schedule_batch(
        snapshot, pods, assigner=assigner, affinity_aware=True, soft=True
    )
    sharded = make_sharded_schedule_fn(
        make_mesh(8), soft=True, assigner=assigner
    )(snapshot, pods)
    assert (
        np.asarray(sharded.node_idx).tolist()
        == np.asarray(single.node_idx).tolist()
    ), seed
    np.testing.assert_allclose(
        np.asarray(sharded.scores), np.asarray(single.scores),
        rtol=1e-4, atol=2e-3,
    )
    np.testing.assert_allclose(
        np.asarray(sharded.free_after), np.asarray(single.free_after), atol=1e-3
    )
