"""JournalTailer regression pins: live journals are readable mid-write.

The closed-set reader (read_journal) stops a file at the first short or
CRC-failing frame — correct post-mortem, fatal for a live consumer.
These tests pin the three live-tail behaviors the shadow scheduler
depends on: rotation boundaries are followed (each new file opens with
a full snapshot, so the delta chain re-anchors), a truncated tail that
later grows is recovered rather than treated as EOF, and a resume_seq
watermark filters already-applied records across a reopen.

Engine/jax-free, like the rest of the journal read tooling.
"""

import os
import struct
import zlib

import pytest

from kubernetes_scheduler_tpu.trace.recorder import (
    JournalTailer,
    JournalWriter,
    TraceError,
    encode_record,
    journal_files,
    read_journal,
)


def _payload(seq: int, path: str = "scalar") -> bytes:
    return encode_record(
        {"seq": seq, "path": path, "metrics": {"pods_in": seq}}
    )


def _frame(payload: bytes) -> bytes:
    return (
        struct.pack("<II", len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
        + payload
    )


def _append(w: JournalWriter, payload: bytes) -> None:
    """Append honoring the writer's file-size budget, the way
    CycleRecorder drives it (JournalWriter never rotates on its own)."""
    w.append(payload, rotate=w.needs_rotation(len(payload)))


def test_tailer_matches_closed_reader(tmp_path):
    """Over a closed journal the tailer is bitwise the batch reader."""
    path = str(tmp_path / "journal")
    w = JournalWriter(path)
    for i in range(7):
        _append(w, _payload(i))
    w.close()
    tailer = JournalTailer(path)
    got = tailer.poll()
    want = list(read_journal(path))
    assert [r["seq"] for r in got] == [r["seq"] for r in want] == list(
        range(7)
    )
    assert tailer.poll() == []  # no growth, no records
    assert tailer.rotations_followed == 0


def test_tailer_follows_rotation_live(tmp_path):
    """Records appended AND rotated after the first poll are picked up;
    every boundary crossing is counted."""
    path = str(tmp_path / "journal")
    w = JournalWriter(path, file_bytes=1)  # every append rotates
    _append(w, _payload(0))
    tailer = JournalTailer(path)
    assert [r["seq"] for r in tailer.poll()] == [0]
    for i in range(1, 5):
        _append(w, _payload(i))
    assert [r["seq"] for r in tailer.poll()] == [1, 2, 3, 4]
    w.close()
    assert len(journal_files(path)) == 5
    assert tailer.rotations_followed == 4
    assert tailer.poll() == []


def test_tailer_resumes_by_seq(tmp_path):
    """resume_seq filters already-applied records — the reopen contract
    for a consumer that remembers its last applied seq."""
    path = str(tmp_path / "journal")
    w = JournalWriter(path, file_bytes=1)
    for i in range(8):
        _append(w, _payload(i))
    w.close()
    tailer = JournalTailer(path, resume_seq=4)
    assert [r["seq"] for r in tailer.poll()] == [5, 6, 7]
    assert tailer.records_filtered == 5
    assert tailer.last_seq == 7


def test_tailer_truncated_tail_then_grew(tmp_path):
    """A frame cut mid-payload is NOT end-of-file for the tailer: once
    the writer's remaining bytes land, the record decodes and the
    recovery is surfaced."""
    path = str(tmp_path / "journal")
    w = JournalWriter(path)
    _append(w, _payload(0))
    w.close()
    fp = journal_files(path)[0]
    full = _frame(_payload(1))
    cut = len(full) // 2
    with open(fp, "ab") as f:
        f.write(full[:cut])
    tailer = JournalTailer(path)
    assert [r["seq"] for r in tailer.poll()] == [0]
    assert tailer.truncations_recovered == 0
    with open(fp, "ab") as f:
        f.write(full[cut:])
    assert [r["seq"] for r in tailer.poll()] == [1]
    assert tailer.truncations_recovered == 1
    # the closed-set reader would have stopped at the cut forever; pin
    # that the recovered record is also what a fresh batch read sees
    assert [r["seq"] for r in read_journal(path)] == [0, 1]


def test_tailer_torn_tail_superseded_by_rotation(tmp_path):
    """A torn tail in a file that has a successor is final garbage (the
    writer only appends to the newest file): skip it, follow the
    rotation, keep every good record."""
    path = str(tmp_path / "journal")
    w = JournalWriter(path, file_bytes=1)
    _append(w, _payload(0))
    files = journal_files(path)
    with open(files[0], "ab") as f:
        f.write(_frame(_payload(99))[:-3])  # torn, never completed
    _append(w, _payload(1))
    w.close()
    tailer = JournalTailer(path)
    assert [r["seq"] for r in tailer.poll()] == [0, 1]
    assert tailer.dead_tails_skipped == 1
    assert tailer.rotations_followed == 1


def test_tailer_crc_mismatch_holds_then_rotation_supersedes(tmp_path):
    """Garbage with a valid length prefix on the newest file holds
    position (the writer may truncate and rewrite); once a successor
    file appears the tail is abandoned."""
    path = str(tmp_path / "journal")
    w = JournalWriter(path, file_bytes=1)
    _append(w, _payload(0))
    fp = journal_files(path)[0]
    bad = bytearray(_frame(_payload(7)))
    bad[-1] ^= 0xFF  # break the CRC
    with open(fp, "ab") as f:
        f.write(bytes(bad))
    tailer = JournalTailer(path)
    assert [r["seq"] for r in tailer.poll()] == [0]
    assert tailer.poll() == []  # held, not crashed, not advanced
    _append(w, _payload(1))
    w.close()
    assert [r["seq"] for r in tailer.poll()] == [1]
    assert tailer.dead_tails_skipped == 1


def test_tailer_header_not_yet_complete(tmp_path):
    """A file shorter than its header (the writer's open() landed, the
    header write has not) yields nothing and does not error."""
    path = str(tmp_path / "journal")
    os.makedirs(path)
    fp = os.path.join(path, "journal-00000000.ytrj")
    with open(fp, "wb") as f:
        f.write(b"YT")
    tailer = JournalTailer(path)
    assert tailer.poll() == []
    w = JournalWriter(path)  # opens journal-00000001
    _append(w, _payload(0))
    w.close()
    # the stub never grew a valid header; tailer waits on it until the
    # successor supersedes it
    assert [r["seq"] for r in tailer.poll()] == [0]


def test_tailer_bad_magic_raises(tmp_path):
    path = str(tmp_path / "journal")
    os.makedirs(path)
    with open(os.path.join(path, "journal-00000000.ytrj"), "wb") as f:
        f.write(b"NOPE" + struct.pack("<H", 1) + b"x" * 16)
    with pytest.raises(TraceError):
        JournalTailer(path).poll()


def test_tailer_survives_disk_budget_drop(tmp_path):
    """When the file being tailed is dropped by the disk budget, the
    tailer resumes at the oldest survivor."""
    path = str(tmp_path / "journal")
    w = JournalWriter(path, file_bytes=1)
    _append(w, _payload(0))
    tailer = JournalTailer(path)
    assert [r["seq"] for r in tailer.poll()] == [0]
    first = journal_files(path)[0]
    for i in range(1, 4):
        _append(w, _payload(i))
    w.close()
    os.remove(first)  # simulate enforce_disk_budget dropping the head
    assert [r["seq"] for r in tailer.poll()] == [1, 2, 3]
