"""Randomized invariant sweep: many seeds, one compiled shape.

Complements the golden suites: across random clusters/pods (mixed GPU,
taints, affinity), the batched cycle must always satisfy the scheduling
invariants the upstream framework guarantees structurally — no capacity
oversubscription, no bind to an infeasible node, greedy priority order,
and fused/sharded variants agreeing with the dense single-device path.
Shapes are fixed across seeds so XLA compiles each program once.
"""

import numpy as np
import pytest

from kubernetes_scheduler_tpu.engine import schedule_batch
from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

N, P = 48, 16
SEEDS = range(0, 40, 2)


def _features(seed):
    return {
        "gpu": seed % 3 == 0,
        "constraints": seed % 2 == 0,
    }


def _replay_capacity(res, snap, pods):
    """Re-apply assignments on the numpy side; assert no oversubscription
    of any requested resource at any step."""
    alloc = np.asarray(snap.allocatable)
    used = np.asarray(snap.requested).copy()
    req = np.asarray(pods.request)
    for i, j in enumerate(np.asarray(res.node_idx)):
        if j < 0:
            continue
        used[j] += req[i]
        over = (used[j] > alloc[j] + 1e-3) & (req[i] > 0)
        assert not over.any(), f"pod {i} oversubscribed node {j}"


@pytest.mark.parametrize("seed", SEEDS)
def test_cycle_invariants(seed):
    feats = _features(seed)
    snap = gen_cluster(N, seed=seed, **feats)
    pods = gen_pods(P, seed=seed + 1, **feats)
    res = schedule_batch(snap, pods)
    idx = np.asarray(res.node_idx)
    feasible = np.asarray(res.feasible)
    prio = np.asarray(pods.priority)

    # 1. a bound pod's node was feasible for it
    for i, j in enumerate(idx):
        if j >= 0:
            assert feasible[i, j], f"pod {i} bound to infeasible node {j}"

    # 2. capacity never oversubscribed (replayed independently)
    _replay_capacity(res, snap, pods)

    # 3. greedy priority order: if pod a (higher priority) went unbound,
    # no strictly lower-priority pod may hold a node that was feasible
    # for a AND still had capacity for a at a's turn. Weaker provable
    # variant without replaying capacities: an unbound pod must have had
    # no feasible node with untouched free capacity at the END (any such
    # node would have been taken at its earlier turn too, since later
    # pods only shrink capacity).
    free_after = np.asarray(res.free_after)
    req = np.asarray(pods.request)
    has_sel = (
        (np.asarray(pods.affinity_sel) >= 0).any(-1)
        | (np.asarray(pods.anti_affinity_sel) >= 0).any(-1)
    )
    for i, j in enumerate(idx):
        if j >= 0 or not bool(np.asarray(pods.pod_mask)[i]):
            continue
        if has_sel[i]:
            # inter-pod (anti)affinity is evaluated dynamically at the
            # pod's turn against counts that keep growing — the end-state
            # argument below does not apply
            continue
        fits_now = (
            ((req[i][None, :] <= free_after) | (req[i][None, :] == 0)).all(-1)
            & feasible[i]
        )
        assert not fits_now.any(), (
            f"pod {i} (prio {prio[i]}) left unbound but node "
            f"{np.argmax(fits_now)} still fits it"
        )

    # 4. n_assigned consistent
    assert int(res.n_assigned) == int((idx >= 0).sum())


@pytest.mark.parametrize("seed", [0, 6, 12])
def test_fused_sweep_matches_unfused(seed):
    feats = _features(seed)
    snap = gen_cluster(N, seed=seed, **feats)
    pods = gen_pods(P, seed=seed + 1, **feats)
    base = schedule_batch(snap, pods, normalizer="none", fused=False)
    got = schedule_batch(snap, pods, normalizer="none", fused=True)
    np.testing.assert_array_equal(
        np.asarray(got.node_idx), np.asarray(base.node_idx)
    )


@pytest.mark.parametrize("seed", [0, 8])
def test_auction_sweep_invariants(seed):
    snap = gen_cluster(N, seed=seed)
    pods = gen_pods(P, seed=seed + 1)
    res = schedule_batch(snap, pods, assigner="auction", normalizer="none")
    idx = np.asarray(res.node_idx)
    feasible = np.asarray(res.feasible)
    for i, j in enumerate(idx):
        if j >= 0:
            assert feasible[i, j]
    _replay_capacity(res, snap, pods)
    # maximality: every unassigned pod truly fits nowhere with current free
    free_after = np.asarray(res.free_after)
    req = np.asarray(pods.request)
    for i, j in enumerate(idx):
        if j < 0:
            fits = (
                ((req[i][None, :] <= free_after) | (req[i][None, :] == 0)).all(-1)
                & feasible[i]
            )
            assert not fits.any()
