"""Bridge tests: codec roundtrip, server/client golden parity with the
in-process engine, decisions_only wire slimming, health, and the
unreachable-sidecar fallback path in the host scheduler."""

import grpc
import numpy as np
import pytest

from kubernetes_scheduler_tpu import engine
from kubernetes_scheduler_tpu.bridge import codec
from kubernetes_scheduler_tpu.bridge import schedule_pb2 as pb
from kubernetes_scheduler_tpu.bridge.client import (
    EngineUnavailable,
    LocalEngine,
    RemoteEngine,
)
from kubernetes_scheduler_tpu.bridge.server import make_server
from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods


@pytest.fixture(scope="module")
def live_server():
    server, port, service = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    yield client, service
    client.close()
    server.stop(grace=None)


# ---- codec ----------------------------------------------------------------


def test_codec_roundtrip_dtypes():
    for arr in [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.array([[True, False], [False, True]]),
        np.arange(5, dtype=np.int32),
        np.float32(3.5),  # scalar
    ]:
        out = codec.unpack_array(codec.pack_array(arr))
        np.testing.assert_array_equal(out, np.asarray(arr))
        assert out.dtype == np.asarray(arr).dtype
        assert out.shape == np.asarray(arr).shape


def test_codec_namedtuple_roundtrip():
    snap = gen_cluster(16, seed=0, constraints=True)
    named = codec.pack_fields(snap, pb.NamedTensors())
    back = codec.unpack_fields(engine.SnapshotArrays, named)
    for name, a, b in zip(snap._fields, snap, back):
        np.testing.assert_array_equal(np.asarray(a), b, err_msg=name)


def test_codec_rejects_unknown_and_missing_fields():
    named = codec.pack_fields(gen_pods(4, seed=1), pb.NamedTensors())
    named.tensors["bogus"].CopyFrom(codec.pack_array(np.zeros(2)))
    with pytest.raises(ValueError, match="unknown"):
        codec.unpack_fields(engine.PodBatch, named)
    del named.tensors["bogus"]
    del named.tensors["request"]
    with pytest.raises(ValueError, match="missing"):
        codec.unpack_fields(engine.PodBatch, named)


def test_codec_rejects_bad_payload():
    t = codec.pack_array(np.zeros((2, 3), np.float32))
    t.shape[:] = [2, 4]
    with pytest.raises(ValueError, match="elements"):
        codec.unpack_array(t)


# ---- server/client --------------------------------------------------------


def test_remote_matches_local(live_server):
    client, _ = live_server
    snap = gen_cluster(32, seed=2, constraints=True)
    pods = gen_pods(8, seed=3, constraints=True)
    local = LocalEngine().schedule_batch(snap, pods)
    remote = client.schedule_batch(snap, pods)
    np.testing.assert_array_equal(np.asarray(local.node_idx), remote.node_idx)
    np.testing.assert_allclose(
        np.asarray(local.scores), remote.scores, rtol=1e-6
    )
    assert int(local.n_assigned) == int(remote.n_assigned)
    assert client.last_engine_seconds > 0


def test_decisions_only_slims_reply(live_server):
    client, _ = live_server
    snap = gen_cluster(16, seed=4)
    pods = gen_pods(4, seed=5)
    slim = RemoteEngine(client.target, decisions_only=True, deadline_seconds=60.0)
    try:
        full = client.schedule_batch(snap, pods)
        thin = slim.schedule_batch(snap, pods)
    finally:
        slim.close()
    np.testing.assert_array_equal(full.node_idx, thin.node_idx)
    np.testing.assert_array_equal(full.free_after, thin.free_after)
    assert not thin.scores.any()  # matrices omitted on the wire


def test_invalid_policy_is_not_retried(live_server):
    client, _ = live_server
    snap = gen_cluster(8, seed=6)
    pods = gen_pods(2, seed=7)
    with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
        client.schedule_batch(snap, pods, policy="nope")


def test_sharded_sidecar_rejects_mismatched_options():
    """A sidecar whose engine is baked to one policy must reject, not
    silently override, a request asking for another."""
    from kubernetes_scheduler_tpu.engine import schedule_batch

    fixed = lambda s, p: schedule_batch(s, p, policy="balanced_diskio")  # noqa: E731
    server, port, _ = make_server(
        "127.0.0.1:0",
        sharded_fn=fixed,
        sharded_opts={"policy": "balanced_diskio", "normalizer": "min_max"},
    )
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    try:
        snap, pods = gen_cluster(8, seed=8), gen_pods(2, seed=9)
        ok = client.schedule_batch(snap, pods, policy="balanced_diskio")
        assert ok.node_idx.shape == (2,)
        with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
            client.schedule_batch(snap, pods, policy="balanced_cpu_diskio")
        # the sharded engine is greedy-only: asking for the auction must
        # fail loud even when the opts dict never mentions an assigner
        with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
            client.schedule_batch(
                snap, pods, policy="balanced_diskio", assigner="auction"
            )
    finally:
        client.close()
        server.stop(grace=None)


def test_sharded_auction_sidecar_honors_request_knobs():
    """A mesh sidecar baked to the AUCTION assigner serves it with dense
    parity and honors REQUEST-carried auction knobs: rounds and price
    step are traced operands of the sharded program (the round-loop bound
    and the bid increment), so per-request values cost no recompile —
    round-4 verdict weak #5 replaced the INVALID_ARGUMENT pinning.
    Structural options (policy/assigner/normalizer) stay pinned: those
    ARE baked into the compiled program."""
    import jax
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_schedule_fn
    from kubernetes_scheduler_tpu.parallel.mesh import make_mesh

    from kubernetes_scheduler_tpu.engine import schedule_windows, stack_windows
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_windows_fn
    from kubernetes_scheduler_tpu.utils.padding import pad_pod_batch

    assert jax.device_count() == 8
    mesh = make_mesh(8)
    server, port, _ = make_server(
        "127.0.0.1:0",
        sharded_fn=make_sharded_schedule_fn(mesh, assigner="auction"),
        sharded_windows_fn=make_sharded_windows_fn(mesh, assigner="auction"),
        sharded_opts={
            "policy": "balanced_cpu_diskio",
            "assigner": "auction",
            "normalizer": "min_max",
        },
    )
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        snap = gen_cluster(32, seed=30, constraints=True)
        pods = gen_pods(10, seed=31, constraints=True)
        remote = client.schedule_batch(snap, pods, assigner="auction")
        dense = schedule_batch(
            snap, pods, assigner="auction", affinity_aware=True
        )
        assert (
            np.asarray(remote.node_idx).tolist()
            == np.asarray(dense.node_idx).tolist()
        )
        # structural mismatches still fail loud
        with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
            client.schedule_batch(snap, pods, assigner="greedy")
        # request-carried knobs are honored and keep bit-identical parity
        # with the dense auction run under the SAME knobs
        # 0.3 pins the non-power-of-two case: both paths must compute
        # the tie-jitter scale identically (traced f32 on both)
        for rounds, frac in ((64, 1.0 / 16.0), (256, 1.0 / 4.0), (512, 0.3)):
            r = client.schedule_batch(
                snap, pods, assigner="auction",
                auction_rounds=rounds, auction_price_frac=frac,
            )
            d = schedule_batch(
                snap, pods, assigner="auction", affinity_aware=True,
                auction_rounds=rounds, auction_price_frac=frac,
            )
            assert (
                np.asarray(r.node_idx).tolist()
                == np.asarray(d.node_idx).tolist()
            ), (rounds, frac)
        # the WINDOWS surface threads request knobs into its per-window
        # scan too — parity against the dense backlog under the same knobs
        pw = stack_windows(pad_pod_batch(pods, 12), 4)
        rw = client.schedule_windows(
            snap, pw, assigner="auction", normalizer="min_max",
            auction_rounds=128, auction_price_frac=0.3,
        )
        dw = schedule_windows(
            snap, pw, assigner="auction", normalizer="min_max",
            affinity_aware=True, auction_rounds=128, auction_price_frac=0.3,
        )
        np.testing.assert_array_equal(
            np.asarray(rw.node_idx), np.asarray(dw.node_idx)
        )
    finally:
        client.close()
        server.stop(grace=None)


def test_preempt_rpc_matches_local(live_server):
    """The Preempt RPC reproduces engine.preempt_batch exactly: victim
    tables + candidate selection run on the sidecar's device, decisions
    come back bit-identical."""
    import jax.numpy as jnp

    from kubernetes_scheduler_tpu.ops.preempt import VictimArrays

    client, _ = live_server
    snap = gen_cluster(16, seed=40)
    # saturate the nodes so the pending pods need preemption
    snap = snap._replace(requested=snap.allocatable)
    pend = gen_pods(4, seed=41)
    pend = pend._replace(priority=jnp.full((4,), 9, jnp.int32))
    m = 12
    rng = np.random.default_rng(42)
    # victims sized like real pods (same generator as the preemptors),
    # concentrated on a few nodes so evicting a small prefix demonstrably
    # frees room
    vic_req = np.asarray(gen_pods(m, seed=42).request)
    s_cols = int(np.asarray(snap.domain_counts).shape[1])
    victims = VictimArrays(
        node=jnp.asarray(rng.integers(0, 4, m), jnp.int32),
        prio=jnp.asarray(rng.integers(0, 5, m), jnp.int32),
        req=jnp.asarray(vic_req * 3.0, jnp.float32),
        mask=jnp.ones((m,), bool),
        start=jnp.asarray(rng.integers(0, 1000, m), jnp.int32),
        matches=jnp.zeros((m, s_cols), bool),
        anti=jnp.zeros((m, s_cols), bool),
    )
    local = engine.preempt_batch(snap, pend, victims, k_cap=4)
    remote = client.preempt(snap, pend, victims, k_cap=4)
    np.testing.assert_array_equal(np.asarray(local.node), remote.node)
    np.testing.assert_array_equal(np.asarray(local.victims), remote.victims)
    np.testing.assert_array_equal(
        np.asarray(local.n_victims), remote.n_victims
    )
    # at least one preemptor found a candidate, or the test is vacuous
    assert (np.asarray(remote.node) >= 0).any()


def test_preempt_rpc_rejects_bad_k_cap(live_server):
    from kubernetes_scheduler_tpu.ops.preempt import VictimArrays
    import jax.numpy as jnp

    client, _ = live_server
    snap = gen_cluster(8, seed=43)
    pend = gen_pods(2, seed=44)
    victims = VictimArrays(
        node=jnp.zeros((1,), jnp.int32),
        prio=jnp.zeros((1,), jnp.int32),
        req=jnp.zeros((1, np.asarray(pend.request).shape[1]), jnp.float32),
        mask=jnp.ones((1,), bool),
        start=jnp.zeros((1,), jnp.int32),
        matches=jnp.zeros((1, 1), bool),
        anti=jnp.zeros((1, 1), bool),
    )
    with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
        client.preempt(snap, pend, victims, k_cap=0)


def test_schedule_windows_rpc_matches_local(live_server):
    """Whole-backlog RPC: one ScheduleWindows call reproduces the local
    schedule_windows decisions, auction knobs riding the wire."""
    from kubernetes_scheduler_tpu.engine import schedule_windows, stack_windows
    from kubernetes_scheduler_tpu.utils.padding import pad_pod_batch

    client, _ = live_server
    snap = gen_cluster(24, seed=20, constraints=True)
    pods = gen_pods(16, seed=21, constraints=True)
    pw = stack_windows(pad_pod_batch(pods, 16), 4)
    local = schedule_windows(
        snap, pw, assigner="auction", affinity_aware=True,
        auction_price_frac=1.0,
    )
    remote = client.schedule_windows(
        snap, pw, assigner="auction", affinity_aware=True,
        auction_price_frac=1.0,
    )
    np.testing.assert_array_equal(
        np.asarray(remote.node_idx), np.asarray(local.node_idx)
    )
    assert int(remote.n_assigned) == int(local.n_assigned)
    np.testing.assert_allclose(
        np.asarray(remote.free_after), np.asarray(local.free_after), atol=1e-3
    )


def test_sharded_sidecar_serves_windows():
    """A mesh-sharded sidecar serves the whole-backlog RPC through
    make_sharded_windows_fn, matching the dense decisions."""
    import jax
    from kubernetes_scheduler_tpu.engine import schedule_windows, stack_windows
    from kubernetes_scheduler_tpu.parallel.engine import (
        make_sharded_schedule_fn,
        make_sharded_windows_fn,
    )
    from kubernetes_scheduler_tpu.parallel.mesh import make_mesh
    from kubernetes_scheduler_tpu.utils.padding import pad_pod_batch

    assert jax.device_count() == 8
    mesh = make_mesh(8)
    server, port, _ = make_server(
        "127.0.0.1:0",
        sharded_fn=make_sharded_schedule_fn(mesh),
        sharded_opts={"policy": "balanced_cpu_diskio", "normalizer": "min_max"},
        sharded_windows_fn=make_sharded_windows_fn(mesh),
    )
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        snap = gen_cluster(32, seed=22, constraints=True)
        pods = gen_pods(12, seed=23, constraints=True)
        pw = stack_windows(pad_pod_batch(pods, 12), 4)
        dense = schedule_windows(
            snap, pw, assigner="greedy", normalizer="none",
        )
        remote = client.schedule_windows(
            snap, pw, assigner="greedy", normalizer="min_max",
        )
        np.testing.assert_array_equal(
            np.asarray(remote.node_idx), np.asarray(dense.node_idx)
        )
        # greedy-only: asking the sharded sidecar for the auction fails
        with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
            client.schedule_windows(snap, pw, assigner="auction")
        # soft without a soft variant fails loud too
        with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
            client.schedule_windows(
                snap, pw, assigner="greedy", normalizer="min_max", soft=True
            )
    finally:
        client.close()
        server.stop(grace=None)


def test_unimplemented_rpc_maps_to_not_implemented(live_server):
    """A version-skewed sidecar answering UNIMPLEMENTED must surface as
    NotImplementedError (the host's windows degradation trigger), not as
    an outage-style EngineUnavailable."""
    client, _ = live_server
    bogus = client._channel.unary_unary(
        "/yodatpu.Engine/DoesNotExist",
        request_serializer=pb.HealthRequest.SerializeToString,
        response_deserializer=pb.HealthReply.FromString,
    )
    with pytest.raises(NotImplementedError):
        client._call_with_retry(bogus, pb.HealthRequest())


def test_sidecar_serves_learned_engine():
    """engine_override: a sidecar built around a LearnedEngine serves
    both RPC surfaces with the learned scorer's decisions."""
    import jax
    from kubernetes_scheduler_tpu.engine import stack_windows
    from kubernetes_scheduler_tpu.models.learned import (
        LearnedEngine,
        init_train_state,
    )

    state, model, _ = init_train_state(jax.random.key(3))
    learned = LearnedEngine(state.params, model=model)
    server, port, _ = make_server("127.0.0.1:0", engine_override=learned)
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        snap = gen_cluster(16, seed=40, constraints=True)
        pods = gen_pods(8, seed=41, constraints=True)
        local = learned.schedule_batch(snap, pods, assigner="greedy")
        remote = client.schedule_batch(snap, pods, assigner="greedy")
        np.testing.assert_array_equal(
            np.asarray(remote.node_idx), np.asarray(local.node_idx)
        )
        pw = stack_windows(pods, 4)
        local_w = learned.schedule_windows(snap, pw, assigner="greedy")
        remote_w = client.schedule_windows(
            snap, pw, assigner="greedy", normalizer="min_max"
        )
        np.testing.assert_array_equal(
            np.asarray(remote_w.node_idx), np.asarray(local_w.node_idx)
        )
    finally:
        client.close()
        server.stop(grace=None)


def test_health(live_server):
    client, service = live_server
    assert client.healthy()
    info = client.health_info()
    assert info.status == "SERVING"
    assert info.device_count >= 1
    assert info.cycles_served == service.cycles_served


def test_unreachable_sidecar():
    client = RemoteEngine("127.0.0.1:1", deadline_seconds=0.5, retries=1)
    try:
        assert not client.healthy(timeout=0.5)
        with pytest.raises(EngineUnavailable):
            client.schedule_batch(gen_cluster(4, seed=0), gen_pods(2, seed=1))
    finally:
        client.close()


def test_scheduler_falls_back_when_sidecar_down():
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil, StaticAdvisor
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.host.types import Container, Node, Pod
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes = [
        Node(name=f"n{i}", allocatable={"cpu": 8000.0, "memory": 2**34, "pods": 110})
        for i in range(4)
    ]
    utils = {
        n.name: NodeUtil(cpu_pct=10.0 * i, mem_pct=20.0, disk_io=5.0)
        for i, n in enumerate(nodes)
    }
    client = RemoteEngine("127.0.0.1:1", deadline_seconds=0.3, retries=0)
    sched = Scheduler(
        SchedulerConfig(batch_window=8),
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
        engine=client,
    )
    try:
        for i in range(3):
            sched.submit(
                Pod(name=f"p{i}", containers=[Container(requests={"cpu": 100.0})])
            )
        m = sched.run_cycle()
    finally:
        client.close()
    assert m.used_fallback
    assert m.pods_bound == 3


# ---- wire field cache (Tensor.same_as_last) -------------------------------


def test_codec_field_cache_markers_and_resolution():
    """Client packing with a cache replaces unchanged leaves with
    same_as_last markers; server unpacking with a cache resolves them;
    a changed leaf rides full and refreshes both sides."""
    snap = gen_cluster(16, seed=0, constraints=True)
    client_cache: dict = {}
    server_cache: dict = {}
    n1 = codec.pack_fields(snap, pb.NamedTensors(), cache=client_cache)
    assert not any(t.same_as_last for t in n1.tensors.values())
    back1 = codec.unpack_fields(engine.SnapshotArrays, n1, cache=server_cache)
    # identical second cycle: every leaf is a marker
    n2 = codec.pack_fields(snap, pb.NamedTensors(), cache=client_cache)
    assert all(t.same_as_last for t in n2.tensors.values())
    assert sum(len(t.data) for t in n2.tensors.values()) == 0
    back2 = codec.unpack_fields(engine.SnapshotArrays, n2, cache=server_cache)
    for name, a, b in zip(snap._fields, back1, back2):
        np.testing.assert_array_equal(np.asarray(a), b, err_msg=name)
    # one leaf changes: only it rides full
    snap3 = snap._replace(disk_io=np.asarray(snap.disk_io) + 1.0)
    n3 = codec.pack_fields(snap3, pb.NamedTensors(), cache=client_cache)
    full = [k for k, t in n3.tensors.items() if not t.same_as_last]
    assert full == ["disk_io"]
    back3 = codec.unpack_fields(engine.SnapshotArrays, n3, cache=server_cache)
    np.testing.assert_array_equal(
        np.asarray(back3.disk_io), np.asarray(snap3.disk_io)
    )


def test_codec_field_cache_miss_raises():
    snap = gen_cluster(8, seed=0)
    client_cache: dict = {}
    codec.pack_fields(snap, pb.NamedTensors(), cache=client_cache)
    marked = codec.pack_fields(snap, pb.NamedTensors(), cache=client_cache)
    assert any(t.same_as_last for t in marked.tensors.values())
    with pytest.raises(codec.FieldCacheMiss):
        codec.unpack_fields(engine.SnapshotArrays, marked, cache={})
    with pytest.raises(codec.FieldCacheMiss):
        codec.unpack_fields(engine.SnapshotArrays, marked, cache=None)


def test_remote_field_cache_steady_state_and_restart_recovery():
    """E2E: the second identical cycle rides markers (client cache
    populated, decisions unchanged); killing the sidecar and starting a
    fresh one on the same port forces a field-cache miss, which the
    client recovers from by resending in full — one warning, no error."""
    snap = gen_cluster(16, seed=0)
    pods = gen_pods(8, seed=1)
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    try:
        r1 = client.schedule_batch(snap, pods, assigner="greedy")
        assert client._field_cache_ok is True
        assert client._wire_cache["batch:snapshot"]  # populated
        r2 = client.schedule_batch(snap, pods, assigner="greedy")
        np.testing.assert_array_equal(
            np.asarray(r1.node_idx), np.asarray(r2.node_idx)
        )
        # sidecar restart: same port, empty session store
        server.stop(grace=None)
        server2, _, _ = make_server(f"127.0.0.1:{port}")
        server2.start()
        try:
            r3 = client.schedule_batch(snap, pods, assigner="greedy")
            np.testing.assert_array_equal(
                np.asarray(r1.node_idx), np.asarray(r3.node_idx)
            )
        finally:
            server2.stop(grace=None)
            server = None
    finally:
        client.close()
        if server is not None:
            server.stop(grace=None)


def test_remote_field_cache_disabled_for_old_sidecar():
    """A sidecar that does not advertise the capability must never see
    markers or a session id — simulated by pinning the probe result."""
    snap = gen_cluster(8, seed=0)
    pods = gen_pods(4, seed=1)
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    try:
        client._field_cache_ok = False  # what an old HealthReply yields
        client.schedule_batch(snap, pods, assigner="greedy")
        client.schedule_batch(snap, pods, assigner="greedy")
        assert client._wire_cache == {}  # never engaged
    finally:
        client.close()
        server.stop(grace=None)


def test_remote_field_cache_cleared_on_failed_send():
    """A send that never reaches the sidecar must clear the client-side
    cache: packing commits values optimistically, and a desynced cache
    would resolve later markers to stale server tensors (silent wrong
    snapshot — the round-5 review's top finding)."""
    snap = gen_cluster(8, seed=0)
    pods = gen_pods(4, seed=1)
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(
        f"127.0.0.1:{port}", deadline_seconds=10.0, retries=0
    )
    try:
        client.schedule_batch(snap, pods, assigner="greedy")
        assert client._wire_cache["batch:snapshot"]
        server.stop(grace=None)
        server = None
        snap2 = snap._replace(disk_io=np.asarray(snap.disk_io) + 1.0)
        with pytest.raises(EngineUnavailable):
            client.schedule_batch(snap2, pods, assigner="greedy")
        assert client._wire_cache == {}  # desync impossible: wiped
    finally:
        client.close()
        if server is not None:
            server.stop(grace=None)


def _start_pre_field_cache_server(address):
    """A sidecar predating the wire field cache: HealthReply does not
    advertise the capability, and a marker-bearing tensor is read as a
    malformed empty payload (INVALID_ARGUMENT) — exactly what an old
    build's codec does."""
    from concurrent import futures

    import grpc as _grpc

    from kubernetes_scheduler_tpu.bridge.server import (
        MAX_MESSAGE_BYTES,
        SERVICE,
    )

    local = LocalEngine()

    def schedule_batch(request, context):
        import jax

        for nt in (request.snapshot, request.pods):
            for name, t in nt.tensors.items():
                if t.same_as_last:
                    context.abort(
                        _grpc.StatusCode.INVALID_ARGUMENT,
                        f"unsupported dtype '' for tensor {name!r}",
                    )
        snapshot = codec.unpack_fields(engine.SnapshotArrays, request.snapshot)
        # this fake predates gang scheduling too (health advertises
        # neither bit), so the client rightly strips the gang tensors —
        # but the fake runs on TODAY'S PodBatch struct, hence the
        # backfill defaults a real old build would not need
        from kubernetes_scheduler_tpu.bridge.server import _POD_WIRE_DEFAULTS

        pods = codec.unpack_fields(
            engine.PodBatch, request.pods, defaults=_POD_WIRE_DEFAULTS
        )
        res = jax.tree_util.tree_map(
            np.asarray, local.schedule_batch(snapshot, pods)
        )
        reply = pb.ScheduleReply(engine_seconds=1e-9)
        codec.pack_fields(res, reply.result)
        return reply

    def health(request, context):
        return pb.HealthReply(
            status="SERVING", device_count=1, platform="cpu"
        )  # proto3 default: field_cache=False

    handlers = _grpc.method_handlers_generic_handler(
        SERVICE,
        {
            "ScheduleBatch": _grpc.unary_unary_rpc_method_handler(
                schedule_batch,
                request_deserializer=pb.ScheduleRequest.FromString,
                response_serializer=pb.ScheduleReply.SerializeToString,
            ),
            "Health": _grpc.unary_unary_rpc_method_handler(
                health,
                request_deserializer=pb.HealthRequest.FromString,
                response_serializer=pb.HealthReply.SerializeToString,
            ),
        },
    )
    server = _grpc.server(
        futures.ThreadPoolExecutor(max_workers=1),
        options=[("grpc.max_receive_message_length", MAX_MESSAGE_BYTES)],
    )
    server.add_generic_rpc_handlers((handlers,))
    assert server.add_insecure_port(address) != 0
    server.start()
    return server


def test_remote_field_cache_downgrade_reprobe():
    """ADVICE r5 (medium): the field-cache capability must not latch True
    for the client's lifetime. When the sidecar behind the target is
    replaced by an older build (no field_cache), the marker-bearing send
    fails INVALID_ARGUMENT; the client must drop the capability back to
    unknown, re-probe health on the next cycle, and settle into full
    sends — NOT fail every other cycle forever."""
    snap = gen_cluster(8, seed=0)
    pods = gen_pods(4, seed=1)
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    old_server = None
    try:
        r1 = client.schedule_batch(snap, pods, assigner="greedy")
        client.schedule_batch(snap, pods, assigner="greedy")  # markers engaged
        assert client._field_cache_ok is True
        assert client._wire_cache["batch:snapshot"]
        # rollback: an old build takes over the same target
        server.stop(grace=None)
        server = None
        old_server = _start_pre_field_cache_server(f"127.0.0.1:{port}")
        # the in-flight capability is stale: ONE failed cycle is expected
        with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
            client.schedule_batch(snap, pods, assigner="greedy")
        assert client._field_cache_ok is None  # forced re-probe
        # every later cycle succeeds: health resolves field_cache=False,
        # full sends, no markers, decisions unchanged
        for _ in range(3):
            r = client.schedule_batch(snap, pods, assigner="greedy")
            np.testing.assert_array_equal(
                np.asarray(r1.node_idx), np.asarray(r.node_idx)
            )
        assert client._field_cache_ok is False
        assert client._wire_cache == {}
    finally:
        client.close()
        if server is not None:
            server.stop(grace=None)
        if old_server is not None:
            old_server.stop(grace=None)


class _FakeRpcError(grpc.RpcError):
    def __init__(self, code, details):
        self._code, self._details = code, details

    def code(self):
        return self._code

    def details(self):
        return self._details


def test_remote_field_cache_failed_resend_clears_cache():
    """ADVICE r5 (low): when the full resend after a field-cache-miss
    itself fails, build_request() has just repopulated _wire_cache with
    values the server never stored — the failure path must clear it (and
    drop the capability latch), or the next cycle burns a guaranteed
    FAILED_PRECONDITION round-trip on stale markers."""
    snap = gen_cluster(8, seed=0)
    pods = gen_pods(4, seed=1)
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=10.0, retries=0)
    try:
        r1 = client.schedule_batch(snap, pods, assigner="greedy")
        assert client._wire_cache["batch:snapshot"]
        calls = []
        real_schedule = client._schedule

        def failing(request, timeout=None):
            calls.append(request)
            if len(calls) == 1:
                raise _FakeRpcError(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    "field-cache-miss: SnapshotArrays.disk_io",
                )
            raise _FakeRpcError(
                grpc.StatusCode.UNAVAILABLE, "connection reset mid-resend"
            )

        client._schedule = failing
        with pytest.raises(EngineUnavailable):
            client.schedule_batch(snap, pods, assigner="greedy")
        assert len(calls) == 2  # the miss, then the failed full resend
        # the resend WAS full (markers cleared before rebuilding)
        assert not any(
            t.same_as_last for t in calls[1].snapshot.tensors.values()
        )
        # and its optimistically-repopulated cache was wiped again
        assert client._wire_cache == {}
        assert client._field_cache_ok is None
        # recovery: real stub back, the next cycle resends full and the
        # cache re-engages from scratch
        client._schedule = real_schedule
        r2 = client.schedule_batch(snap, pods, assigner="greedy")
        np.testing.assert_array_equal(
            np.asarray(r1.node_idx), np.asarray(r2.node_idx)
        )
        assert client._wire_cache["batch:snapshot"]
    finally:
        client.close()
        server.stop(grace=None)


def test_remote_field_cache_constraint_sweep_matches_local():
    """Capstone for the wire cache: three consecutive cycles of the
    property generator's full constraint surface (taints, OR-affinity,
    namespace-scoped (anti)affinity, spread) through a LIVE sidecar with
    the field cache engaged — decisions must be identical to the
    in-process engine even when most leaves ride as markers and the
    running set (hence domain counts and `requested`) shifts between
    cycles."""
    import dataclasses

    from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder
    from tests.test_property_families import gen_pod, gen_scenario

    rng = np.random.default_rng(7)
    nodes, spread_groups, running, utils = gen_scenario(rng, 12, 3)
    pods_per_cycle = [
        [gen_pod(rng, 100 * c + i, spread_groups) for i in range(6)]
        for c in range(3)
    ]
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    b_remote, b_local = SnapshotBuilder(), SnapshotBuilder()
    run_remote, run_local = list(running), list(running)
    marker_counts = []
    orig_send = client._schedule

    def counting_send(req, timeout=None):
        marker_counts.append(sum(
            t.same_as_last for t in req.snapshot.tensors.values()
        ))
        return orig_send(req, timeout=timeout)

    client._schedule = counting_send
    try:
        for cyc, pods in enumerate(pods_per_cycle):
            pods_l = [dataclasses.replace(p) for p in pods]
            sr = b_remote.build_snapshot(
                nodes, utils, run_remote, pending_pods=pods
            )
            pr = b_remote.build_pod_batch(pods)
            rr = client.schedule_batch(
                sr, pr, assigner="auction", normalizer="none",
                affinity_aware=True, soft=True,
            )
            sl = b_local.build_snapshot(
                nodes, utils, run_local, pending_pods=pods_l
            )
            pl = b_local.build_pod_batch(pods_l)
            rl = engine.schedule_batch(
                sl, pl, assigner="auction", normalizer="none",
                affinity_aware=True, soft=True,
            )
            np.testing.assert_array_equal(
                np.asarray(rr.node_idx), np.asarray(rl.node_idx),
                err_msg=f"cycle {cyc}",
            )
            for pod, pod_l, j in zip(
                pods, pods_l, np.asarray(rl.node_idx)[: len(pods)]
            ):
                if 0 <= j < len(nodes):
                    run_remote.append(
                        dataclasses.replace(pod, node_name=nodes[int(j)].name)
                    )
                    run_local.append(
                        dataclasses.replace(pod_l, node_name=nodes[int(j)].name)
                    )
        # the cache really engaged: cycle 1 all-full, cycles 2-3 rode
        # markers for the unchanged snapshot leaves
        assert client._field_cache_ok is True
        assert marker_counts[0] == 0
        assert marker_counts[1] > 0 and marker_counts[2] > 0
    finally:
        client.close()
        server.stop(grace=None)
