"""Streaming state ingestion (host/mirror.SnapshotMirror): event-driven
mirror parity against the per-cycle rebuild, flush-to-full rules, delta
semantics, advisor coalescing, and the event-driven cycle trigger.

The PARITY round-16 guarantee lives here: mirror-on and mirror-off
bindings are BITWISE identical across serial/pipelined x full/resident,
and the mirror's periodic cross-check (verify_interval) never fires on
any of these workloads.
"""

import threading
import time

import numpy as np
import pytest

from kubernetes_scheduler_tpu.engine import apply_snapshot_delta_np
from kubernetes_scheduler_tpu.host.advisor import (
    BackgroundAdvisor,
    CoalescingAdvisor,
    NodeUtil,
    StaticAdvisor,
)
from kubernetes_scheduler_tpu.host.mirror import CycleTrigger, SnapshotMirror
from kubernetes_scheduler_tpu.host.scheduler import RecordingBinder, Scheduler
from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster, gen_host_pods
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig


class _ChurnAdvisor:
    """Deterministically perturbs a rotating slice of nodes per fetch,
    with the coalescing surface (fetch_changed) reporting exactly the
    perturbed slice."""

    def __init__(self, base, names, k=3):
        self.utils = dict(base.fetch())
        self.names = list(names)
        self.k = k
        self.i = 0
        self._changed: dict = {}

    def fetch(self):
        self._changed = {}
        for j in range(self.k):
            nm = self.names[(self.i + j) % len(self.names)]
            u = self.utils[nm]
            nu = NodeUtil(
                cpu_pct=u.cpu_pct + 0.25, mem_pct=u.mem_pct,
                disk_io=u.disk_io, net_up=u.net_up, net_down=u.net_down,
            )
            self.utils[nm] = nu
            self._changed[nm] = nu
        self.i += self.k
        return self.utils

    def fetch_changed(self):
        self.fetch()
        return dict(self._changed)


def _mk_sched(
    nodes, advisor, running, *, mirror, verify_interval=1, **overrides
):
    from kubernetes_scheduler_tpu.sim.scenarios import SimClock

    overrides.setdefault("max_windows_per_cycle", 1)
    cfg = SchedulerConfig(
        batch_window=32,
        normalizer="none",
        adaptive_dispatch=False,
        min_device_work=1,
        snapshot_mirror=mirror,
        mirror_verify_interval=verify_interval,
        **overrides,
    )
    clock = SimClock()
    sched = Scheduler(
        cfg,
        advisor=advisor,
        binder=RecordingBinder(),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        # virtual queue clock: retry backoffs resolve per-cycle, so the
        # mirror-on/off runs pop IDENTICAL windows regardless of how
        # fast each host path drains (wall-clock backoffs would diverge)
        queue_clock=clock,
    )
    sched._test_clock = clock
    return sched


def _drain(sched, nodes, running, *, events=None, max_cycles=60):
    """Drain the queue, feeding binds back as running pods; `events` is
    {cycle index: fn(sched, nodes, running)} fired between cycles (the
    informer-event injection point)."""
    seen = 0
    for c in range(max_cycles):
        if events and c in events:
            events[c](sched, nodes, running)
        sched._test_clock.advance(1.0)
        if len(sched.queue) == 0 and sched._prefetched is None:
            break
        sched.run_cycle()
        for b in sched.binder.bindings[seen:]:
            running.append(b.pod)
        seen = len(sched.binder.bindings)
    sched.drain_pipeline()
    return [(b.pod.namespace, b.pod.name, b.node_name)
            for b in sched.binder.bindings]


def _run_workload(*, mirror: bool, constraints=False, flap=False, **overrides):
    nodes, base = gen_host_cluster(48, seed=0, constraints=constraints)
    advisor = _ChurnAdvisor(base, [nd.name for nd in nodes])
    running: list = []
    sched = _mk_sched(nodes, advisor, running, mirror=mirror, **overrides)
    for pod in gen_host_pods(220, seed=1, constraints=constraints):
        sched.submit(pod)
    events = None
    if flap:
        def fail(sched, nodes, running):
            nd = nodes.pop(3)
            fail.node = nd
            displaced = [p for p in running if p.node_name == nd.name]
            for p in displaced:
                running.remove(p)
                if sched.mirror is not None:
                    sched.mirror.apply_pod_event("DELETED", p)
                p.node_name = None
                sched.submit(p)
            if sched.mirror is not None:
                sched.mirror.apply_node_event("DELETED", nd)

        def restore(sched, nodes, running):
            nodes.append(fail.node)
            if sched.mirror is not None:
                sched.mirror.apply_node_event("ADDED", fail.node)

        events = {2: fail, 4: restore}
    bindings = _drain(sched, nodes, running, events=events)
    return sched, bindings


@pytest.mark.parametrize(
    "overrides",
    [
        {},                                            # serial, full uploads
        {"pipeline_depth": 1},                         # pipelined
        {"pipeline_depth": 1, "resident_state": True},  # pipelined resident
    ],
    ids=["serial", "pipelined", "resident"],
)
def test_mirror_binding_parity_pod_churn(overrides):
    a, ba = _run_workload(mirror=False, **overrides)
    b, bb = _run_workload(mirror=True, **overrides)
    assert ba and ba == bb
    assert not b.mirror.ctr_verify_failures._series  # every emit verified
    if overrides.get("resident_state"):
        # the delta/full split must MATCH: the mirror flushes to full on
        # exactly the cycles snapshot_delta would have returned None
        assert (
            a.totals["delta_uploads"], a.totals["full_uploads"],
        ) == (b.totals["delta_uploads"], b.totals["full_uploads"])
        assert b.totals["delta_uploads"] > 0


@pytest.mark.parametrize(
    "overrides",
    [{}, {"pipeline_depth": 1, "resident_state": True}],
    ids=["serial", "resident"],
)
def test_mirror_binding_parity_node_flap(overrides):
    a, ba = _run_workload(mirror=False, flap=True, **overrides)
    b, bb = _run_workload(mirror=True, flap=True, **overrides)
    assert ba and ba == bb
    assert not b.mirror.ctr_verify_failures._series
    # the flap forced flush-to-full rebuilds beyond the seed build
    assert b.mirror.ctr_rebuilds.total() >= 3
    # the {reason} breakdown attributes them: node add/remove churn
    assert b.mirror.ctr_rebuilds.value(reason="node-churn") >= 1


def test_mirror_binding_parity_selector_drift():
    # constraint traffic: anti-affinity terms mint selectors as pods
    # arrive — mints inside the allocated power-of-two column bucket
    # extend the mirror in place, crossings flush, and decisions must
    # not move either way (verify_interval=1 cross-checks every emit)
    a, ba = _run_workload(mirror=False, constraints=True)
    b, bb = _run_workload(mirror=True, constraints=True)
    assert ba and ba == bb
    assert not b.mirror.ctr_verify_failures._series


def test_mirror_default_config_parity_with_mirror_off():
    """The shipped SchedulerConfig defaults run the mirror ON: against
    an otherwise-identical mirror-off config, bindings are bitwise
    identical on constraint traffic — the default flip moved host-side
    cost, never decisions — and every emit cross-checks clean."""
    from kubernetes_scheduler_tpu.sim.scenarios import SimClock

    assert SchedulerConfig().snapshot_mirror is True  # the shipped default

    def run(overrides):
        nodes, base = gen_host_cluster(24, seed=0, constraints=True)
        advisor = _ChurnAdvisor(base, [nd.name for nd in nodes])
        running: list = []
        clock = SimClock()
        sched = Scheduler(
            SchedulerConfig(mirror_verify_interval=1, **overrides),
            advisor=advisor,
            binder=RecordingBinder(),
            list_nodes=lambda: nodes,
            list_running_pods=lambda: running,
            queue_clock=clock,
        )
        sched._test_clock = clock
        for pod in gen_host_pods(96, seed=1, constraints=True):
            sched.submit(pod)
        return sched, _drain(sched, nodes, running)

    on_s, on = run({})
    assert on_s.mirror is not None
    assert not on_s.mirror.ctr_verify_failures._series
    off_s, off = run({"snapshot_mirror": False})
    assert off_s.mirror is None
    assert on and on == off


def test_mirror_idle_emit_zero_row_delta():
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    sched = _mk_sched(
        nodes, CoalescingAdvisor(advisor), running, mirror=True,
        resident_state=True, pipeline_depth=1,
    )
    for pod in gen_host_pods(8, seed=1):
        sched.submit(pod)
    _drain(sched, nodes, running)
    mir = sched.mirror
    prev, _, _ = mir.emit([], pending_all_plain=True, prev=None)
    snap, delta, rebuilt = mir.emit([], pending_all_plain=True, prev=prev)
    assert not rebuilt
    n = int(np.asarray(snap.node_mask).shape[0])
    # zero-row delta: every row index is the out-of-range pad sentinel
    assert (np.asarray(delta.req_rows) == n).all()
    assert (np.asarray(delta.util_rows) == n).all()
    assert (np.asarray(delta.dom_rows) == n).all()
    # unchanged leaves are served by identity across idle emits
    assert snap.requested is prev.requested
    assert snap.disk_io is prev.disk_io


def test_mirror_delta_reproduces_snapshot_bitwise():
    nodes, base = gen_host_cluster(24, seed=0)
    advisor = _ChurnAdvisor(base, [nd.name for nd in nodes])
    running: list = []
    sched = _mk_sched(nodes, advisor, running, mirror=True)
    for pod in gen_host_pods(40, seed=1):
        sched.submit(pod)
    _drain(sched, nodes, running)
    mir = sched.mirror
    prev, _, _ = mir.emit([], pending_all_plain=True, prev=None)
    # events: utilization churn + a pod removal
    mir.apply_util_events(advisor.fetch_changed())
    victim = running[len(running) // 2]
    mir.apply_pod_event("DELETED", victim)
    snap, delta, rebuilt = mir.emit([], pending_all_plain=True, prev=prev)
    assert not rebuilt and delta is not None
    folded = apply_snapshot_delta_np(prev, delta)
    for name in snap._fields:
        a, b = np.asarray(getattr(folded, name)), np.asarray(getattr(snap, name))
        assert np.array_equal(a, b), name
    # the removal really changed the row (not a vacuous delta)
    assert (np.asarray(delta.req_rows) < len(nodes)).any()


def test_mirror_flush_reasons():
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    sched = _mk_sched(nodes, CoalescingAdvisor(advisor), running, mirror=True)
    for pod in gen_host_pods(8, seed=1):
        sched.submit(pod)
    _drain(sched, nodes, running)
    mir = sched.mirror
    base_rebuilds = mir.ctr_rebuilds.total()
    base_node_churn = mir.ctr_rebuilds.value(reason="node-churn")
    # node event -> flush
    mir.apply_node_event("MODIFIED", nodes[0])
    _, delta, rebuilt = mir.emit([], pending_all_plain=True, prev=None)
    assert rebuilt and delta is None
    assert mir.ctr_rebuilds.total() == base_rebuilds + 1
    assert mir.ctr_rebuilds.value(reason="node-churn") == base_node_churn + 1
    # a window minting ONE selector fits the allocated power-of-two
    # column bucket: absorbed in place (extension), NOT a rebuild
    from kubernetes_scheduler_tpu.host.types import Pod, PodAffinityTerm

    def drift_pod(i):
        return Pod(
            name=f"drift-{i}", namespace="d",
            pod_affinity=[
                PodAffinityTerm(
                    match_labels={"nonesuch": str(i)},
                    topology_key="kubernetes.io/hostname",
                    anti=True,
                )
            ],
        )

    _, delta, rebuilt = mir.emit([drift_pod(0)], pending_all_plain=False, prev=None)
    assert not rebuilt
    assert mir.ctr_rebuilds.total() == base_rebuilds + 1
    assert mir.ctr_extensions.value(kind="selector") == 1
    assert mir.verify([drift_pod(0)])
    # drift PAST the bucket (1 -> 4 selector slots): shapes grow, flush
    _, delta, rebuilt = mir.emit(
        [drift_pod(1), drift_pod(2)], pending_all_plain=False, prev=None
    )
    assert rebuilt
    assert mir.ctr_rebuilds.total() == base_rebuilds + 2
    assert mir.ctr_rebuilds.value(reason="selector-drift") >= 1
    # the labeled series renders per-reason (both exporters share
    # Counter.render); the seed build is attributed too
    rendered = "\n".join(mir.ctr_rebuilds.render())
    assert 'mirror_full_rebuilds_total{reason="seed"}' in rendered
    assert 'mirror_full_rebuilds_total{reason="selector-drift"}' in rendered


def test_mirror_port_remap_in_place():
    """A same-width hostPort remap (a port retires, another appears) is
    absorbed by recomputing only the port-hosting rows — no rebuild —
    and the surviving port's contribution moves to its new column."""
    from kubernetes_scheduler_tpu.host.types import Pod

    nodes, advisor = gen_host_cluster(16, seed=0)
    p8080 = Pod(name="web", namespace="d", node_name=nodes[0].name,
                host_ports=[8080])
    p9090 = Pod(name="db", namespace="d", node_name=nodes[1].name,
                host_ports=[9090])
    running: list = [p8080, p9090]
    sched = _mk_sched(nodes, CoalescingAdvisor(advisor), running, mirror=True)
    for pod in gen_host_pods(8, seed=1):
        sched.submit(pod)
    _drain(sched, nodes, running)
    mir = sched.mirror
    assert mir._adopt_ports == {8080: 0, 9090: 1}
    prev, _, _ = mir.emit([], pending_all_plain=True, prev=None)
    rebuilds = mir.ctr_rebuilds.total()
    # 8080 retires; a pending pod brings 9999 — live ports {9090, 9999}
    # re-sort into the SAME two slots, so 9090's column moves 1 -> 0
    mir.apply_pod_event("DELETED", p8080)
    wpod = Pod(name="new", namespace="d", host_ports=[9999])
    snap, delta, rebuilt = mir.emit([wpod], pending_all_plain=False, prev=prev)
    # verify_interval=1 cross-checked this very emit bitwise: a wrong
    # remap would have flushed and reported rebuilt=True
    assert not rebuilt
    assert mir.ctr_rebuilds.total() == rebuilds
    assert mir.ctr_extensions.value(kind="port-remap") == 1
    assert mir._adopt_ports == {9090: 0, 9999: 1}
    assert delta is not None  # no static leaf moved: the delta survives
    i = mir._node_index[p9090.node_name]
    req = np.asarray(snap.requested)
    assert req[i, mir._port0 + 0] == 1.0  # 9090 now occupies slot 0
    assert req[i, mir._port0 + 1] == 0.0
    # slot GROWTH (a third live port) still flushes: widths change
    wider = Pod(name="wide", namespace="d", host_ports=[7070, 7071])
    _, _, rebuilt = mir.emit([wpod, wider], pending_all_plain=False, prev=None)
    assert rebuilt
    assert mir.ctr_rebuilds.value(reason="port-churn") >= 1


def test_mirror_selector_extension_zone_topology():
    """An in-place selector extension with REAL matches over a label
    topology: the new column's domain counts are filled from the running
    set and domain_id is patched — a static leaf the delta format cannot
    carry, so that one emit degrades to a full upload (delta=None) while
    the mirror itself never rebuilds."""
    from kubernetes_scheduler_tpu.host.snapshot import selector_key
    from kubernetes_scheduler_tpu.host.types import Pod, PodAffinityTerm

    nodes, advisor = gen_host_cluster(16, seed=0, constraints=True)
    running: list = []
    sched = _mk_sched(nodes, CoalescingAdvisor(advisor), running, mirror=True)
    # plain pods (no constraints): the mirror adopts with ZERO selectors,
    # but every generated pod carries an "app: svc-<i>" label to match
    for pod in gen_host_pods(8, seed=1):
        sched.submit(pod)
    _drain(sched, nodes, running)
    mir = sched.mirror
    assert mir._adopt_n_sel == 0
    prev, _, _ = mir.emit([], pending_all_plain=True, prev=None)
    rebuilds = mir.ctr_rebuilds.total()
    term = PodAffinityTerm(
        match_labels={"app": "svc-1"},
        topology_key="topology.kubernetes.io/zone",
        anti=True,
    )
    wpod = Pod(name="z", namespace="d", pod_affinity=[term])
    snap, delta, rebuilt = mir.emit([wpod], pending_all_plain=False, prev=prev)
    assert not rebuilt  # bitwise-verified in-emit (verify_interval=1)
    assert mir.ctr_rebuilds.total() == rebuilds
    assert mir.ctr_extensions.value(kind="selector") == 1
    sid = mir.builder.selectors[selector_key(term)]
    counts = np.asarray(snap.domain_counts)
    # the running svc-1 pod really counted into the new column
    assert counts[:, sid].sum() > 0
    # zone domains: grouped rows share their first index, so domain_id
    # departs from the hostname default (each node its own index)
    dom = np.asarray(snap.domain_id)
    assert (dom[:, sid] != np.arange(len(nodes))).any()
    assert delta is None  # domain_id moved: full upload this once
    snap2, delta2, rebuilt2 = mir.emit(
        [wpod], pending_all_plain=False, prev=snap
    )
    assert not rebuilt2
    assert delta2 is not None  # the degradation was one emit, not sticky


def test_mirror_bound_pod_event_dedups_by_identity():
    nodes, advisor = gen_host_cluster(8, seed=0)
    running: list = []
    sched = _mk_sched(nodes, CoalescingAdvisor(advisor), running, mirror=True)
    for pod in gen_host_pods(4, seed=1):
        sched.submit(pod)
    _drain(sched, nodes, running)
    mir = sched.mirror
    n_running = len(mir.running)
    # the informer echoing the scheduler's own bind (same object) no-ops
    mir.apply_pod_event("MODIFIED", running[0])
    assert len(mir.running) == n_running
    assert mir.verify()


def test_mirror_binding_parity_windows_backlog():
    """The deep-backlog path (_run_backlog -> schedule_windows, with
    the windows-resident delta surface) consumes mirror emits too."""
    kw = dict(max_windows_per_cycle=4, resident_state=True)
    a, ba = _run_workload(mirror=False, **kw)
    b, bb = _run_workload(mirror=True, **kw)
    assert ba and ba == bb
    assert not b.mirror.ctr_verify_failures._series
    assert (
        a.totals["delta_uploads"], a.totals["full_uploads"],
    ) == (b.totals["delta_uploads"], b.totals["full_uploads"])


def test_mirror_binding_parity_sharded_resident():
    """The acceptance matrix's sharded column: the mesh-sharded resident
    engine consumes mirror-emitted deltas unchanged (shard_snapshot_delta
    routes them inside ShardedEngine) — bindings bitwise mirror-on vs
    mirror-off on the 8-device topology."""
    a, ba = _run_workload(
        mirror=False, pipeline_depth=1, resident_state=True,
        sharded_engine=True,
    )
    b, bb = _run_workload(
        mirror=True, pipeline_depth=1, resident_state=True,
        sharded_engine=True,
    )
    assert ba and ba == bb
    assert not b.mirror.ctr_verify_failures._series
    assert b.totals["sharded_cycles"] > 0
    assert b.totals["delta_uploads"] > 0
    assert b.totals["shard_delta_bytes"] > 0  # routed mirror deltas


# ---- advisor coalescing ---------------------------------------------------


def test_coalescing_advisor_reports_only_changes():
    utils = {"a": NodeUtil(cpu_pct=1.0), "b": NodeUtil(cpu_pct=2.0)}
    adv = CoalescingAdvisor(StaticAdvisor(utils))
    first = adv.fetch_changed()
    assert set(first) == {"a", "b"}
    assert adv.fetch_changed() == {}
    utils["a"].cpu_pct = 5.0  # in-place mutation is seen (value compare)
    assert set(adv.fetch_changed()) == {"a"}
    del utils["b"]  # a vanished node degrades to a zeros record
    changed = adv.fetch_changed()
    assert set(changed) == {"b"} and changed["b"].cpu_pct == 0.0


def test_background_advisor_fetch_changed_accumulates_off_cycle():
    utils = {"a": NodeUtil(cpu_pct=1.0)}
    clock = [0.0]
    adv = BackgroundAdvisor(
        StaticAdvisor(utils), interval=5.0, max_staleness=60.0,
        clock=lambda: clock[0], start_thread=False,
    )
    assert set(adv.fetch_changed()) == {"a"}  # first drain: everything
    assert adv.fetch_changed() == {}          # no refresh since
    utils["a"] = NodeUtil(cpu_pct=9.0)
    adv._refresh_once()                       # the background thread's diff
    changed = adv.fetch_changed()
    assert set(changed) == {"a"} and changed["a"].cpu_pct == 9.0
    assert adv.fetch_changed() == {}


# ---- event-driven cycle trigger -------------------------------------------


def test_cycle_trigger_no_lost_wakeup():
    trig = CycleTrigger()
    trig.notify()  # lands BEFORE the wait — must not be lost
    t0 = time.perf_counter()
    assert trig.wait(5.0) is True
    assert time.perf_counter() - t0 < 1.0
    # drained: a second wait times out (the watchdog path)
    assert trig.wait(0.02) is False


def test_cycle_trigger_cross_thread_wakeup():
    trig = CycleTrigger()

    def poke():
        time.sleep(0.05)
        trig.notify()

    t = threading.Thread(target=poke)
    t.start()
    t0 = time.perf_counter()
    assert trig.wait(5.0) is True
    assert time.perf_counter() - t0 < 2.0
    t.join()


def test_scheduler_submit_and_mirror_events_notify_trigger():
    nodes, advisor = gen_host_cluster(8, seed=0)
    running: list = []
    sched = _mk_sched(
        nodes, CoalescingAdvisor(advisor), running, mirror=True,
        cycle_trigger="event",
    )
    assert sched.trigger is not None
    before = sched.trigger.notifies
    for pod in gen_host_pods(2, seed=1):
        sched.submit(pod)
    assert sched.trigger.notifies == before + 2
    _drain(sched, nodes, running)
    before = sched.trigger.notifies
    sched.mirror.apply_util_events({nodes[0].name: NodeUtil(cpu_pct=42.0)})
    assert sched.trigger.notifies == before + 1
    # trigger mode never changes decisions: watchdog timeout still fires
    assert sched.trigger.wait(0.01) in (True, False)


def test_bad_cycle_trigger_rejected():
    nodes, advisor = gen_host_cluster(4, seed=0)
    with pytest.raises(ValueError, match="cycle_trigger"):
        _mk_sched(nodes, advisor, [], mirror=False, cycle_trigger="nope")


def test_cycle_trigger_event_default_parity_with_tick():
    """cycle_trigger now defaults to "event": the default config binds
    bitwise identically to the tick driver (the trigger only decides
    WHEN the loop wakes, never what a cycle decides)."""
    assert SchedulerConfig().cycle_trigger == "event"
    a, ba = _run_workload(mirror=True)  # default config: event
    b, bb = _run_workload(mirror=True, cycle_trigger="tick")
    assert ba and ba == bb
    assert a.trigger is not None and b.trigger is None


# ---- selector pre-size + spread intake (warm-restart satellites) ----------


def test_mirror_spread_selector_bound_intake_extends_in_place():
    """A BOUND pod arriving via the informer with a fresh topology-
    spread selector — EITHER whenUnsatisfiable variant (DoNotSchedule
    hard, ScheduleAnyway soft) — extends the selector table in place
    instead of flushing the mirror, and the filled columns verify
    bitwise against a fresh rebuild."""
    from kubernetes_scheduler_tpu.host.snapshot import selector_key
    from kubernetes_scheduler_tpu.host.types import Pod, SpreadConstraint

    nodes, advisor = gen_host_cluster(16, seed=0, constraints=True)
    running: list = []
    sched = _mk_sched(nodes, CoalescingAdvisor(advisor), running, mirror=True)
    # a constraints workload, so the selector bucket has PADDING room:
    # in-place extension is only possible inside the current
    # power-of-two width (a crossing is a legitimate flush)
    for pod in gen_host_pods(90, seed=1, constraints=True):
        sched.submit(pod)
    _drain(sched, nodes, running)
    mir = sched.mirror
    assert len(mir.builder.selectors) + 2 <= mir.builder._selector_slots()
    mir.emit([], pending_all_plain=True, prev=None)
    rebuilds = mir.ctr_rebuilds.total()
    ext0 = mir.ctr_extensions.value(kind="selector")
    for i, soft in enumerate((False, True)):  # hard, then soft
        sc = SpreadConstraint(
            match_labels={"spread-test": f"v{i}"},
            topology_key="topology.kubernetes.io/zone",
            soft=soft,
        )
        bound = Pod(
            name=f"spread-{i}", namespace="d",
            topology_spread=[sc], node_name=nodes[0].name,
        )
        mir.apply_pod_event("ADDED", bound)
        assert selector_key(sc) in mir.builder.selectors
    assert mir.ctr_extensions.value(kind="selector") == ext0 + 2
    assert mir.ctr_rebuilds.total() == rebuilds
    assert mir.verify()


def test_mirror_presize_skips_early_bucket_crossings():
    """mirror_initial_selectors (fed from `trace stats`
    peak_selector_slots on a warm restart) floors the power-of-two
    selector bucket: the presized run never pays the early crossing
    flushes, and bindings stay bitwise identical to the unsized run."""
    kw = dict(constraints=True, resident_state=True, pipeline_depth=1)
    a, ba = _run_workload(mirror=True, **kw)
    peak = a.builder._selector_slots()
    assert peak >= 2  # the workload really crossed selector buckets
    # the unsized run pays flush-to-full rebuilds at the crossings
    assert a.mirror.ctr_rebuilds.value(reason="layout-drift") >= 1
    b, bb = _run_workload(mirror=True, mirror_initial_selectors=peak, **kw)
    assert ba and ba == bb
    assert b.builder._selector_slots() == peak
    # with the bucket pre-sized the width never moves mid-run: the
    # crossing flushes (and their XLA recompiles) disappear
    assert b.mirror.ctr_rebuilds.value(reason="layout-drift") == 0


# ---- scenario harness integration -----------------------------------------


@pytest.mark.parametrize("name", ["burst", "node-flap", "anti-affinity-pack"])
def test_scenario_mirror_matches_rebuild(tmp_path, name):
    """Mirror-on and mirror-off scenario runs produce the same journaled
    bindings (ScenarioWorld drives node/pod events through the mirror)."""
    from kubernetes_scheduler_tpu.sim import scenarios

    def binds(mirror, sub):
        journal = str(tmp_path / f"{name}-{sub}")
        cfg = scenarios.scenario_config(
            {"snapshot_mirror": True, "mirror_verify_interval": 1}
            if mirror
            else {}
        )
        scenarios.run(name, n_nodes=16, seed=0, trace_path=journal, config=cfg)
        from kubernetes_scheduler_tpu.trace.recorder import read_journal

        out = []
        for rec in read_journal(journal):
            out.extend(tuple(b) for b in rec.get("bindings") or ())
        return out

    off = binds(False, "off")
    on = binds(True, "on")
    assert off and off == on


def test_scenario_mirror_replay_pin_e2e(tmp_path):
    """PARITY round 16: a mirror-on scenario journal replays with zero
    binding diffs (mirror-emitted deltas satisfy the recorder chain)."""
    from kubernetes_scheduler_tpu.sim import scenarios
    from kubernetes_scheduler_tpu.trace.replay import replay_journal

    journal = str(tmp_path / "flap-mirror")
    cfg = scenarios.scenario_config(
        {
            "snapshot_mirror": True,
            "mirror_verify_interval": 1,
            "resident_state": True,
            "pipeline_depth": 1,
        }
    )
    summary = scenarios.run(
        "node-flap", n_nodes=16, seed=0, trace_path=journal, config=cfg
    )
    assert summary["pods_bound"] > 0
    assert summary["fallback_cycles"] == 0
    assert summary["delta_uploads"] > 0  # mirror deltas actually shipped
    report = replay_journal(journal)
    assert report.replayed > 0
    assert report.binding_diffs == 0, report.to_dict()
