"""graftmodel tests: the checker core (partial-order-reduction
soundness against known full-interleaving state counts, counterexample
determinism, writes-declaration validation, budget handling), the
shipped protocol models (exhausted, zero violations, anchors bound),
the mutation harness asserted mutant by mutant, and the CLI contract
(exit 0 / 1-on-violation / 3-on-blown-budget, JSON artifact)."""

import json

import pytest

from kubernetes_scheduler_tpu.analysis.model import (
    Convergence,
    Invariant,
    ProtocolModel,
    Transition,
    check_model,
)
from kubernetes_scheduler_tpu.analysis.model import mutants as mutants_mod
from kubernetes_scheduler_tpu.analysis.model.__main__ import main as model_main
from kubernetes_scheduler_tpu.analysis.model.checker import _explore
from kubernetes_scheduler_tpu.analysis.model.protocols import build_models

# ---- checker core ---------------------------------------------------------


def _counter_model(n=3, invariants=(), convergences=()):
    """Two independent per-process counters 0..n: the full interleaving
    lattice has EXACTLY (n+1)^2 reachable states — the analytic pin the
    POR soundness test compares against."""
    t = (
        Transition(
            name="inc_x", process="px",
            guard=lambda s: s["x"] < n,
            effect=lambda s: {"x": s["x"] + 1},
            reads=frozenset({"x"}), writes=frozenset({"x"}),
        ),
        Transition(
            name="inc_y", process="py",
            guard=lambda s: s["y"] < n,
            effect=lambda s: {"y": s["y"] + 1},
            reads=frozenset({"y"}), writes=frozenset({"y"}),
        ),
    )
    return ProtocolModel(
        name="counters", description="two independent counters",
        init={"x": 0, "y": 0}, transitions=t,
        invariants=tuple(invariants), convergences=tuple(convergences),
    )


def test_por_visits_every_state_of_known_lattice():
    res = check_model(_counter_model(3))
    assert res.exhausted and res.ok
    # sleep sets prune TRANSITIONS, never states: all (3+1)^2 states
    assert res.states == 16
    # and the reduction actually reduced something
    assert res.transitions_slept > 0


@pytest.mark.parametrize(
    "model", build_models(), ids=lambda m: m.name
)
def test_por_state_set_equals_full_interleaving(model):
    """POR soundness on every SHIPPED model: the reduced exploration
    reaches exactly the states the unreduced one does."""
    full = _explore(model, por=False, record_edges=False,
                    max_states=200_000, deadline=None)
    red = _explore(model, por=True, record_edges=False,
                   max_states=200_000, deadline=None)
    assert full.exhausted and red.exhausted
    # every reachable state is still visited — the soundness claim
    # (sleep sets prune transitions, and may re-expand a state under
    # incomparable sleep sets, so FIRED counts are not comparable)
    assert set(red.states) == set(full.states)


def test_undeclared_write_is_an_error_not_an_unsoundness():
    lying = ProtocolModel(
        name="liar", description="", init={"x": 0, "y": 0},
        transitions=(
            Transition(
                name="sneak", process="p",
                guard=lambda s: s["x"] == 0,
                effect=lambda s: {"x": 1, "y": 1},  # y undeclared
                reads=frozenset({"x"}), writes=frozenset({"x"}),
            ),
        ),
    )
    with pytest.raises(ValueError, match="undeclared variables.*'y'"):
        check_model(lying)


def test_invariant_counterexample_renders_schedule():
    res = check_model(_counter_model(2, invariants=(
        Invariant("x-bounded", lambda s: s["x"] < 2, "x reached 2"),
    )))
    (v,) = res.violations
    assert v.kind == "invariant" and v.name == "x-bounded"
    assert v.schedule[0].startswith("schedule (2 events")
    assert v.schedule[1:3] == ["1. inc_x", "2. inc_x"]
    assert "reaches {" in v.schedule[-1]


def test_convergence_livelock_renders_lasso():
    toggle = ProtocolModel(
        name="toggler", description="", init={"x": 0},
        transitions=(
            Transition(
                name="flip", process="p", guard=lambda s: True,
                effect=lambda s: {"x": 1 - s["x"]},
                reads=frozenset({"x"}), writes=frozenset({"x"}),
            ),
        ),
        convergences=(
            Convergence("settles", trigger=lambda s: True,
                        goal=lambda s: s["x"] == 2),
        ),
    )
    res = check_model(toggle)
    (v,) = res.violations
    assert v.kind == "convergence"
    assert any("livelock cycle" in line for line in v.schedule)


def test_convergence_dead_end_renders():
    one_shot = ProtocolModel(
        name="oneshot", description="", init={"x": 0},
        transitions=(
            Transition(
                name="step", process="p", guard=lambda s: s["x"] == 0,
                effect=lambda s: {"x": 1},
                reads=frozenset({"x"}), writes=frozenset({"x"}),
            ),
        ),
        convergences=(
            Convergence("settles", trigger=lambda s: True,
                        goal=lambda s: s["x"] == 2),
        ),
    )
    res = check_model(one_shot)
    (v,) = res.violations
    assert any("dead end at" in line for line in v.schedule)


def test_state_budget_reports_unexhausted():
    res = check_model(_counter_model(10), max_states=5)
    assert not res.exhausted and not res.ok
    assert any(v.kind == "budget" for v in res.violations)


def test_counterexample_deterministic_across_runs():
    a = mutants_mod.run_mutant("partial-probe")
    b = mutants_mod.run_mutant("partial-probe")
    assert [(v.kind, v.name, v.schedule) for v in a.violations] == [
        (v.kind, v.name, v.schedule) for v in b.violations
    ]
    assert a.states == b.states
    assert a.transitions_fired == b.transitions_fired


# ---- the shipped models hold at HEAD --------------------------------------


@pytest.mark.parametrize(
    "model", build_models(), ids=lambda m: m.name
)
def test_shipped_model_exhausts_clean(model):
    res = check_model(model)
    assert res.exhausted, f"{model.name} blew its budget"
    assert res.violations == [], "\n".join(
        v.render() for v in res.violations
    )


# ---- the mutation harness: every seeded bug caught, by name ---------------

_EXPECTED_CATCH = {
    "invalidate-keeps-latches": "downgrade-relearned",
    "invalidate-keeps-wire-cache": "no-marker-without-latch",
    "partial-probe": "latches-resolved-together",
    "delta-across-layout-churn": "resident-state-faithful",
    "defer-restores-to-back": "deferred-gang-leads-next-pop",
    "fail-keeps-resident-commit": "failure-invalidates-resident",
    "dispatch-scores-stale-batch": "stale-spec-batch-never-scored",
    "unfenced-replica-bind": "no-double-bind",
    "shared-delta-unfenced": "shared-delta-fenced",
    "ladder-skips-rung": "never-skips-a-rung",
    "promote-without-probe": "recovery-re-probes",
}


def test_every_mutant_has_an_expectation():
    assert set(_EXPECTED_CATCH) == set(mutants_mod.MUTANTS)


@pytest.mark.parametrize("name", list(mutants_mod.MUTANTS))
def test_mutant_caught_with_rendered_schedule(name):
    res = mutants_mod.run_mutant(name)
    assert res.exhausted
    assert res.violations, f"mutant `{name}` SURVIVED"
    assert _EXPECTED_CATCH[name] in {v.name for v in res.violations}
    caught = [v for v in res.violations if v.name == _EXPECTED_CATCH[name]]
    assert any(
        line.startswith("schedule (") for v in caught for line in v.schedule
    ), f"mutant `{name}` caught without a rendered event schedule"


# ---- anchors: the drift layer ---------------------------------------------


def _index():
    from kubernetes_scheduler_tpu.analysis.model.runner import _index_for

    return _index_for(None)


def test_shipped_anchors_bind():
    from kubernetes_scheduler_tpu.analysis.model.anchors import (
        verify_model_anchors,
    )

    index = _index()
    for model in build_models():
        vs = verify_model_anchors(index, model)
        assert vs == [], "\n".join(v.format() for v in vs)


def test_anchor_drift_detected():
    from kubernetes_scheduler_tpu.analysis.model.anchors import (
        Anchor,
        verify_anchor,
    )

    index = _index()
    client = "kubernetes_scheduler_tpu/bridge/client.py"
    # missing def
    vs = verify_anchor(index, "m", "t", Anchor(client, "RemoteEngine.gone"))
    assert len(vs) == 1 and "no longer exists" in vs[0].message
    # present def, vanished fragment
    vs = verify_anchor(index, "m", "t", Anchor(
        client, "RemoteEngine._invalidate_session",
        must_contain=("FRAGMENT_THE_REFACTOR_DROPPED",),
    ))
    assert len(vs) == 1 and "no longer contains" in vs[0].message
    # present def, vanished call edge
    vs = verify_anchor(index, "m", "t", Anchor(
        client, "RemoteEngine._invalidate_session",
        calls=("helper_nobody_calls",),
    ))
    assert len(vs) == 1 and "no longer calls" in vs[0].message


def test_anchor_drift_fails_the_lint_layer(monkeypatch):
    """Moving the code out from under a model is a `protocol-model`
    lint finding, end to end through the runner."""
    import dataclasses

    from kubernetes_scheduler_tpu.analysis.model import protocols, runner
    from kubernetes_scheduler_tpu.analysis.model.anchors import Anchor

    def drifted():
        m = protocols.client_session_model()
        old = m.transitions[0]
        bad = dataclasses.replace(
            old,
            anchors=(Anchor(
                "kubernetes_scheduler_tpu/bridge/client.py",
                "RemoteEngine._probe_capabilities",
                must_contain=("THE_CODE_MOVED",),
            ),),
        )
        return (protocols.replace_transition(m, old.name, bad),)

    monkeypatch.setattr(runner, "build_models", drifted)
    vs = runner.check_protocol_layer(budget_seconds=30.0)
    assert any(
        v.rule == "protocol-model" and "THE_CODE_MOVED" in v.message
        for v in vs
    )


# ---- the lint layer & CLI -------------------------------------------------


def test_protocol_layer_clean_at_head():
    from kubernetes_scheduler_tpu.analysis.model.runner import (
        check_protocol_layer,
    )

    vs = check_protocol_layer(budget_seconds=60.0)
    assert vs == [], "\n".join(v.format() for v in vs)


def test_model_cli_json_artifact_and_exit_codes(tmp_path, capsys):
    art = tmp_path / "model.json"
    rc = model_main(["--json-artifact", str(art), "--format", "json"])
    capsys.readouterr()
    assert rc == 0
    doc = json.loads(art.read_text())
    assert {m["name"] for m in doc["models"]} == {
        "client-session", "gang-queue-front", "gang-queue-native",
        "pipeline-slot", "replica-bind", "degradation-ladder",
    }
    assert all(m["exhausted"] and not m["violations"]
               for m in doc["models"])
    assert doc["mutants"] and all(
        d["caught"] for d in doc["mutants"].values()
    )
    assert doc["anchor_drift"] == []


def test_model_cli_budget_exit_code(capsys):
    # a 5-state cap cannot exhaust any shipped model: exit 3, and the
    # un-exhausted proof is reported as a budget violation, not hidden
    rc = model_main(["--max-states", "5", "--no-mutants"])
    out = capsys.readouterr().out
    assert rc == 3
    assert "NOT EXHAUSTED" in out


def test_model_cli_sarif(capsys):
    from kubernetes_scheduler_tpu.analysis.sarif import validate_sarif

    rc = model_main(["--format", "sarif", "--no-mutants"])
    doc = json.loads(capsys.readouterr().out)
    validate_sarif(doc)
    assert rc == 0
