"""CLI surface (cmd/scheduler/main.go + pkg/register analogs) and the
object-level simulators behind it."""

import json

import pytest

from kubernetes_scheduler_tpu import register
from kubernetes_scheduler_tpu.cli import build_parser, main
from kubernetes_scheduler_tpu.host.plugins import ScalarYodaPlugin
from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster, gen_host_pods


def test_register_gate():
    assert register.YODA in register.registered_plugins()
    plugin = register.make_plugin(register.YODA, utils={})
    assert isinstance(plugin, ScalarYodaPlugin)
    with pytest.raises(ValueError, match="unknown plugin"):
        register.make_plugin("nope")
    # later registration shadows (app.WithPlugin override semantics)
    register.register_plugin("custom", lambda **kw: ScalarYodaPlugin(utils={}))
    assert "custom" in register.registered_plugins()


def test_host_generators_shapes():
    nodes, advisor = gen_host_cluster(7, gpu=True, constraints=True)
    assert len(nodes) == 7
    assert len(advisor.fetch()) == 7
    assert any(n.cards for n in nodes)
    pods = gen_host_pods(13, constraints=True)
    assert len(pods) == 13
    assert all(p.annotations.get("diskIO") for p in pods)


def test_cli_config_roundtrip(capsys, tmp_path):
    main(["config", "--policy", "free_capacity", "--batch-window", "64"])
    out = json.loads(capsys.readouterr().out)
    assert out["policy"] == "free_capacity"
    assert out["batch_window"] == 64
    # file + flag override layering
    cfg_file = tmp_path / "cfg.json"
    cfg_file.write_text(json.dumps({"policy": "balanced_diskio", "batch_window": 8}))
    main(["config", "--config", str(cfg_file), "--batch-window", "16"])
    out = json.loads(capsys.readouterr().out)
    assert out["policy"] == "balanced_diskio"
    assert out["batch_window"] == 16


def test_cli_policies_lists_all(capsys):
    main(["policies"])
    out = capsys.readouterr().out
    for name in ("balanced_cpu_diskio", "balanced_diskio", "free_capacity", "card"):
        assert name in out
    assert "yoda-tpu" in out


def test_cli_scheduler_end_to_end(capsys):
    rc = main(
        [
            "scheduler", "--nodes", "12", "--pods", "30",
            "--batch-window", "10", "--constraints",
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["pods_bound"] + out["pods_unschedulable"] == 30
    # deep-queue batching: the 3-window backlog schedules in ONE cycle
    # (max_windows_per_cycle default 8); it must never take more cycles
    # than the window count
    assert 1 <= out["cycles"] <= 3
    assert out["fallback_cycles"] == 0


def test_cli_scheduler_no_tpu_fallback(capsys):
    main(
        [
            "scheduler", "--nodes", "6", "--pods", "8",
            "--batch-window", "8", "--no-tpu",
        ]
    )
    out = json.loads(capsys.readouterr().out)
    assert out["fallback_cycles"] == out["cycles"] >= 1
    assert out["pods_bound"] + out["pods_unschedulable"] == 8


def test_parser_rejects_unknown_subcommand():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["frobnicate"])


def test_shipped_manifest_host_sidecar_options_consistent():
    """The deploy manifest's host ConfigMap and sidecar args must form a
    working pair: the host config parses, and every option the host will
    send (policy/assigner/normalizer/fused/auction knobs) matches what
    the sidecar bakes — otherwise the sidecar's fail-loud option pinning
    rejects every cycle in production."""
    import os

    import yaml

    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    path = os.path.join(os.path.dirname(__file__), "..", "deploy",
                        "yoda-tpu-scheduler.yaml")
    docs = list(yaml.safe_load_all(open(path)))
    cm = next(d for d in docs if d.get("kind") == "ConfigMap")
    cfg = SchedulerConfig.from_dict(
        json.loads(cm["data"]["scheduler-config.json"])
    )
    dep = next(d for d in docs if d.get("kind") == "Deployment")
    sidecar = next(
        c for c in dep["spec"]["template"]["spec"]["containers"]
        if c["name"] == "tpu-engine"
    )
    args = sidecar["args"]

    def flag(name, default=None):
        for a in args:
            if a == name:
                return True
            if a.startswith(name + "="):
                return a.split("=", 1)[1]
        return default

    # the sharded sidecar pins these; the host sends its config values.
    # Non-default choices must be EXPLICIT in the manifest args — the
    # test does not mirror server.py's argparse defaults, so an implicit
    # default could silently drift from what this compares against.
    assert flag("--policy", "balanced_cpu_diskio") == cfg.policy
    assert flag("--assigner") == cfg.assigner, (
        "manifest must state --assigner explicitly"
    )
    assert flag("--normalizer") == cfg.normalizer, (
        "manifest must state --normalizer explicitly"
    )
    if flag("--fused", False):
        # host only sends fused=True under this exact gate
        assert cfg.feature_gates.fused_kernel
        assert cfg.policy == "balanced_cpu_diskio"
        assert cfg.normalizer == "none"
    if cfg.assigner == "auction":
        pf = flag("--auction-price-frac")
        rounds = flag("--auction-rounds")
        assert pf is not None and rounds is not None, (
            "manifest must pin the auction knobs explicitly"
        )
        assert float(pf) == cfg.auction_price_frac
        assert int(rounds) == cfg.auction_rounds

    # RBAC: per-rule (apiGroup, resource) -> verbs, so a grant moved to
    # the wrong group or stripped of a needed verb fails here instead of
    # as runtime Forbidden errors
    role = next(d for d in docs if d.get("kind") == "ClusterRole")
    verbs: dict[tuple, set] = {}
    for rule in role["rules"]:
        for g in rule.get("apiGroups", []):
            for r in rule.get("resources", []):
                verbs.setdefault((g, r), set()).update(rule.get("verbs", []))

    def granted(group, resource, *need):
        have = verbs.get((group, resource), set())
        assert set(need) <= have, (group, resource, need, have)

    granted("", "nodes", "list", "watch")
    granted("", "pods", "list", "watch", "delete")   # delete = evictor
    granted("", "pods/binding", "create")
    granted("", "persistentvolumes", "list", "watch")
    granted("", "persistentvolumeclaims", "list", "watch")
    granted("policy", "poddisruptionbudgets", "list", "watch")
    granted("coordination.k8s.io", "leases", "create", "get", "update")
