"""Observability: metric aggregation, Prometheus exposition, labeled
histograms, per-cycle spans (Chrome trace events + merge), concurrent
scrapes against a live scheduler, and telemetry-on/off binding parity."""

import json
import threading
import urllib.request

import pytest

from kubernetes_scheduler_tpu.host.observe import (
    Counter,
    Gauge,
    Histogram,
    MetricsExporter,
    SpanRecorder,
    render_prometheus,
    summarize,
)
from kubernetes_scheduler_tpu.host.scheduler import CycleMetrics


def make_metrics():
    return [
        CycleMetrics(pods_in=10, pods_bound=9, pods_unschedulable=1,
                     cycle_seconds=0.10, engine_seconds=0.04),
        CycleMetrics(pods_in=20, pods_bound=20, pods_unschedulable=0,
                     cycle_seconds=0.30, engine_seconds=0.10,
                     used_fallback=True),
        CycleMetrics(),  # empty cycle: excluded from aggregates
    ]


def test_summarize():
    s = summarize(make_metrics())
    assert s["cycles_total"] == 2
    assert s["pods_bound_total"] == 29
    assert s["pods_unschedulable_total"] == 1
    assert s["fallback_cycles_total"] == 1
    assert abs(s["scheduling_pods_per_sec"] - 29 / 0.4) < 1e-6
    assert s["bind_latency_p99_seconds"] == 0.30
    assert s["batch_size_mean"] == 15.0


def test_render_prometheus_format():
    text = render_prometheus(make_metrics())
    assert "# TYPE yoda_tpu_pods_bound_total counter" in text
    assert "# TYPE yoda_tpu_bind_latency_p99_seconds gauge" in text
    assert "yoda_tpu_pods_bound_total 29" in text
    # every sample line parses as "name value"
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            name, value = line.split()
            float(value)


def test_render_prometheus_unknown_extra_does_not_crash():
    """Regression: an `extra` key with no _HELP entry used to KeyError
    the whole /metrics render; it now falls back to an empty HELP line
    and still emits the sample."""
    text = render_prometheus(
        make_metrics(), extra={"mystery_metric_total": 3}
    )
    assert "# HELP yoda_tpu_mystery_metric_total" in text
    assert "yoda_tpu_mystery_metric_total 3" in text
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            name, value = line.split()
            float(value)


def test_metrics_exporter_http():
    class FakeScheduler:
        metrics = make_metrics()

    exporter = MetricsExporter(FakeScheduler())
    # loopback bind (the configurable-host satellite): tests must not
    # open 0.0.0.0 listeners
    port = exporter.serve(0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            body = r.read().decode()
        assert "yoda_tpu_pods_bound_total 29" in body
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert r.read() == b"ok\n"
    finally:
        exporter.close()


# ---- labeled collectors ---------------------------------------------------


def test_histogram_render_cumulative_buckets():
    h = Histogram(
        "step_duration_seconds", "step time", labels=("rpc",),
        buckets=(0.01, 0.1, 1.0),
    )
    h.observe(0.005, rpc="a")
    h.observe(0.05, rpc="a")
    h.observe(0.05, rpc="a")
    h.observe(5.0, rpc="a")   # over the top bucket -> +Inf only
    h.observe(0.1, rpc="b")   # == bound lands in that bucket (le)
    lines = h.render()
    text = "\n".join(lines)
    assert "# TYPE yoda_tpu_step_duration_seconds histogram" in text
    assert 'yoda_tpu_step_duration_seconds_bucket{rpc="a",le="0.01"} 1' in text
    assert 'yoda_tpu_step_duration_seconds_bucket{rpc="a",le="0.1"} 3' in text
    assert 'yoda_tpu_step_duration_seconds_bucket{rpc="a",le="1"} 3' in text
    assert 'yoda_tpu_step_duration_seconds_bucket{rpc="a",le="+Inf"} 4' in text
    assert 'yoda_tpu_step_duration_seconds_count{rpc="a"} 4' in text
    assert 'yoda_tpu_step_duration_seconds_bucket{rpc="b",le="0.1"} 1' in text
    # sums are per-series
    assert 'yoda_tpu_step_duration_seconds_sum{rpc="b"} 0.1' in text


def test_counter_and_gauge_render():
    c = Counter("rpcs_served_total", "rpcs", labels=("rpc",))
    c.inc(rpc="health")
    c.inc(3, rpc="schedule_batch")
    text = "\n".join(c.render())
    assert 'yoda_tpu_rpcs_served_total{rpc="health"} 1' in text
    assert 'yoda_tpu_rpcs_served_total{rpc="schedule_batch"} 3' in text
    g = Gauge("resident_sessions_count", "sessions")
    g.set(2)
    text = "\n".join(g.render())
    assert "# TYPE yoda_tpu_resident_sessions_count gauge" in text
    assert "yoda_tpu_resident_sessions_count 2" in text


def test_histogram_concurrent_observe_and_render():
    """The buckets are mutated from the scheduling thread while scrapes
    render: no torn series, final counts exact."""
    h = Histogram("cycle_duration_seconds", "cycles", labels=("path",))
    stop = threading.Event()
    rendered = []

    def scrape():
        while not stop.is_set():
            rendered.append(h.render())

    t = threading.Thread(target=scrape)
    t.start()
    for i in range(2000):
        h.observe(0.001 * (i % 7), path="serial")
    stop.set()
    t.join(timeout=10)
    text = "\n".join(h.render())
    assert 'yoda_tpu_cycle_duration_seconds_count{path="serial"} 2000' in text
    assert rendered  # scrapes actually interleaved


# ---- span layer -----------------------------------------------------------


def test_span_recorder_chrome_events(tmp_path):
    from kubernetes_scheduler_tpu.trace.spans import read_spans

    rec = SpanRecorder(str(tmp_path), process="host")
    ss = rec.begin()
    assert ss.trace_id == 1
    with ss.span("snapshot_build"):
        pass
    ss.add("engine_step", 1.0, 1.5, resident=False)
    rec.flush(ss, seq=7)
    ss2 = rec.begin()
    assert ss2.trace_id == 2  # monotonic
    rec.close()

    events = [ev for ev in read_spans(str(tmp_path)) if ev["ph"] == "X"]
    assert len(events) == 2
    for ev in events:
        assert ev["args"]["trace_id"] == 1
        assert ev["args"]["seq"] == 7  # flight-recorder cross-link
        assert ev["dur"] >= 0
    names = {ev["name"] for ev in events}
    assert names == {"snapshot_build", "engine_step"}
    assert rec.spans_written == 2
    assert rec.bytes_written > 0


def test_span_writer_rotation_and_budget(tmp_path):
    from kubernetes_scheduler_tpu.trace.spans import (
        SpanWriter,
        read_spans,
        span_files,
    )

    w = SpanWriter(str(tmp_path), file_bytes=600, max_bytes=2000)
    for i in range(40):
        w.append([{"name": "s", "ph": "X", "ts": i, "dur": 1,
                   "pid": 1, "tid": 0, "args": {"trace_id": i}}])
    w.close()
    files = span_files(str(tmp_path))
    assert len(files) > 1  # rotated
    import os

    assert sum(os.path.getsize(f) for f in files) <= 2600  # budget held
    # surviving files all parse
    events = [ev for ev in read_spans(str(tmp_path)) if ev["ph"] == "X"]
    assert events and all(ev["name"] == "s" for ev in events)


def test_span_file_torn_tail_recovers(tmp_path):
    from kubernetes_scheduler_tpu.trace.spans import (
        SpanWriter,
        read_span_file,
        span_files,
    )

    w = SpanWriter(str(tmp_path))
    w.append([{"name": "good", "ph": "X", "ts": 1, "dur": 1, "pid": 1,
               "tid": 0, "args": {"trace_id": 1}}])
    w.close()
    fp = span_files(str(tmp_path))[0]
    with open(fp, "a") as f:
        f.write('{"name": "torn", "ph"')
    events = [ev for ev in read_span_file(fp) if ev["ph"] == "X"]
    assert [ev["name"] for ev in events] == ["good"]


def test_spans_merge_joins_on_trace_id(tmp_path):
    from kubernetes_scheduler_tpu.trace.spans import merge_spans

    host = SpanRecorder(str(tmp_path / "host"), process="host")
    side = SpanRecorder(str(tmp_path / "side"), process="sidecar")
    for tid in (1, 2, 3):
        ss = host.begin()
        ss.add("cycle", 0.0, 1.0)
        host.flush(ss)
    for tid in (2, 3, 9):  # 9 only on the sidecar side
        ss = side.begin(tid)
        ss.add("device_step", 0.2, 0.8, rpc="schedule_batch")
        side.flush(ss, seq=tid)
    host.close()
    side.close()
    out = tmp_path / "merged.json"
    report = merge_spans(
        str(tmp_path / "host"), str(tmp_path / "side"), str(out)
    )
    assert report["joined_trace_ids"] == 2
    assert report["host_trace_ids"] == 3
    assert report["sidecar_trace_ids"] == 3
    merged = json.loads(out.read_text())
    assert len(merged["traceEvents"]) == report["merged_events"]
    # both process_name metadata tracks survive the merge
    names = {
        ev["args"]["name"]
        for ev in merged["traceEvents"]
        if ev.get("ph") == "M"
    }
    assert names == {"host", "sidecar"}


def test_spans_merge_cli(tmp_path):
    from kubernetes_scheduler_tpu.cli import main

    host = SpanRecorder(str(tmp_path / "host"), process="host")
    ss = host.begin()
    ss.add("cycle", 0.0, 1.0)
    host.flush(ss)
    host.close()
    side = SpanRecorder(str(tmp_path / "side"), process="sidecar")
    ss = side.begin(1)
    ss.add("device_step", 0.2, 0.8)
    side.flush(ss)
    side.close()
    out = str(tmp_path / "merged.json")
    rc = main([
        "spans", "merge", str(tmp_path / "host"), str(tmp_path / "side"),
        "--out", out,
    ])
    assert rc == 0
    assert json.load(open(out))["traceEvents"]
    # disjoint ids on non-empty sides -> non-zero exit (broken join)
    side2 = SpanRecorder(str(tmp_path / "side2"), process="sidecar")
    ss = side2.begin(999)
    ss.add("device_step", 0.2, 0.8)
    side2.flush(ss)
    side2.close()
    rc = main([
        "spans", "merge", str(tmp_path / "host"), str(tmp_path / "side2"),
        "--out", str(tmp_path / "merged2.json"),
    ])
    assert rc == 1


# ---- live scheduler: spans wired into both drivers, scrape concurrency ----


def _make_sched(tmp_path, *, pipeline_depth=0, span=True, trace=False):
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.sim.host_gen import (
        gen_host_cluster,
        gen_host_pods,
    )
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes, advisor = gen_host_cluster(12, seed=0)
    running: list = []
    cfg = SchedulerConfig(
        batch_window=16,
        max_windows_per_cycle=1,
        min_device_work=1,
        adaptive_dispatch=False,
        pipeline_depth=pipeline_depth,
        initial_backoff_seconds=3600.0,
        max_backoff_seconds=3600.0,
        span_path=str(tmp_path / f"spans{pipeline_depth}") if span else None,
        trace_path=str(tmp_path / f"journal{pipeline_depth}") if trace else None,
    )
    sched = Scheduler(
        cfg,
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )
    for pod in gen_host_pods(48, seed=1):
        sched.submit(pod)
    return sched, running


@pytest.mark.parametrize("depth", [0, 1])
def test_cycle_spans_written_by_both_drivers(tmp_path, depth):
    from kubernetes_scheduler_tpu.trace.spans import read_spans

    sched, running = _make_sched(tmp_path, pipeline_depth=depth, trace=True)
    sched.run_until_empty(max_cycles=16)
    sched.spans.close()
    sched.recorder.close()
    events = [
        ev
        for ev in read_spans(str(tmp_path / f"spans{depth}"))
        if ev["ph"] == "X"
    ]
    assert events
    by_name = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev)
    # snapshot_mirror is default-on: the state stage is event_apply +
    # mirror_emit (snapshot_build only appears on the flush/rebuild path)
    for want in ("queue_pop", "state_fetch", "event_apply", "mirror_emit",
                 "engine_step", "bind", "cycle", "recorder_write"):
        assert want in by_name, (want, sorted(by_name))
    if depth == 1:
        assert "host_overlap" in by_name
    # trace ids are monotonic and shared across one cycle's spans
    ids = sorted({ev["args"]["trace_id"] for ev in events})
    assert ids == list(range(1, len(ids) + 1))
    # every span carries the cycle's flight-recorder seq, and the seqs
    # pair with the journal records (the replay cross-link)
    from kubernetes_scheduler_tpu.trace.recorder import read_journal

    rec_seqs = {
        r["seq"] for r in read_journal(str(tmp_path / f"journal{depth}"))
    }
    for ev in events:
        assert ev["args"]["seq"] in rec_seqs
    # device-step spans specifically carry the seq (the acceptance gate)
    assert all("seq" in ev["args"] for ev in by_name["engine_step"])


@pytest.mark.parametrize("depth", [0, 1])
def test_concurrent_scrapes_mid_cycle(tmp_path, depth):
    """Hammer /metrics from several threads while the scheduler drains:
    every response parses, no torn histogram series, and the final
    scrape agrees with the scheduler's totals (metrics_snapshot and the
    histogram buckets are thread-safe in both drivers)."""
    sched, running = _make_sched(tmp_path, pipeline_depth=depth)
    exporter = MetricsExporter(sched)
    port = exporter.serve(0, host="127.0.0.1")
    bodies, errors = [], []
    stop = threading.Event()

    def scrape():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10
                ) as r:
                    bodies.append(r.read().decode())
            except Exception as e:  # pragma: no cover - the failure signal
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=scrape) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        sched.run_until_empty(max_cycles=16)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        exporter.close()
        if sched.spans is not None:
            sched.spans.close()
    assert not errors, errors
    assert bodies
    for body in bodies:
        for line in body.strip().splitlines():
            if not line.startswith("#"):
                name, value = line.rsplit(" ", 1)
                float(value)
    # final state: histogram totals equal the recorded cycles
    final = "\n".join(
        c
        for collector in sched.prom_collectors
        for c in collector.render()
    )
    path = "pipelined" if depth else "serial"
    want = sum(1 for m in sched.metrics)
    assert (
        f'yoda_tpu_cycle_duration_seconds_count{{path="{path}"}} {want}'
        in final
    )


def test_telemetry_parity_bindings_bitidentical(tmp_path):
    """PARITY.md: telemetry-on (spans + exporter scraping mid-drain)
    vs telemetry-off bindings are bit-identical — spans only read
    clocks."""

    def run(span, depth):
        sched, running = _make_sched(
            tmp_path / f"p{int(span)}{depth}", pipeline_depth=depth,
            span=span,
        )
        exporter = None
        if span:
            exporter = MetricsExporter(sched)
            exporter.serve(0, host="127.0.0.1")
        sched.run_until_empty(max_cycles=16)
        if exporter is not None:
            exporter.close()
        if sched.spans is not None:
            sched.spans.close()
        return [
            (b.pod.namespace, b.pod.name, b.node_name)
            for b in sched.binder.bindings
        ]

    for depth in (0, 1):
        (tmp_path / f"p0{depth}").mkdir()
        (tmp_path / f"p1{depth}").mkdir()
        assert run(True, depth) == run(False, depth)


def test_live_sidecar_exporter_concurrent_scrape():
    """The sidecar's own /metrics under concurrent scrapes while RPCs
    are in flight: rpc counters + device-step histograms appear and
    every response parses (the live-sidecar half of the thread-safety
    satellite)."""
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server
    from kubernetes_scheduler_tpu.host.observe import HttpMetricsServer
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    server, port, service = make_server("127.0.0.1:0")
    server.start()
    exporter = HttpMetricsServer(
        service.render_metrics, profile=service.arm_profile
    )
    mport = exporter.serve(0, host="127.0.0.1")
    engine = RemoteEngine(f"127.0.0.1:{port}")
    bodies, errors = [], []
    stop = threading.Event()

    def scrape():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/metrics", timeout=10
                ) as r:
                    bodies.append(r.read().decode())
            except Exception as e:  # pragma: no cover
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=scrape) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        snapshot = gen_cluster(8, seed=0)
        pods = gen_pods(8, seed=1)
        engine.set_trace_id(41, 5)
        for _ in range(3):
            engine.schedule_batch(snapshot, pods)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        engine.close()
        exporter.close()
        server.stop(grace=None)
    assert not errors, errors
    final = service.render_metrics()
    assert 'yoda_tpu_rpcs_served_total{rpc="schedule_batch"} 3' in final
    assert (
        'yoda_tpu_device_step_duration_seconds_count{rpc="schedule_batch"} 3'
        in final
    )
    for body in bodies:
        for line in body.strip().splitlines():
            if not line.startswith("#"):
                name, value = line.rsplit(" ", 1)
                float(value)
