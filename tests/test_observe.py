"""Observability: metric aggregation, Prometheus exposition, spans."""

import json
import urllib.request

from kubernetes_scheduler_tpu.host.observe import (
    CycleTracer,
    MetricsExporter,
    render_prometheus,
    summarize,
)
from kubernetes_scheduler_tpu.host.scheduler import CycleMetrics


def make_metrics():
    return [
        CycleMetrics(pods_in=10, pods_bound=9, pods_unschedulable=1,
                     cycle_seconds=0.10, engine_seconds=0.04),
        CycleMetrics(pods_in=20, pods_bound=20, pods_unschedulable=0,
                     cycle_seconds=0.30, engine_seconds=0.10,
                     used_fallback=True),
        CycleMetrics(),  # empty cycle: excluded from aggregates
    ]


def test_summarize():
    s = summarize(make_metrics())
    assert s["cycles_total"] == 2
    assert s["pods_bound_total"] == 29
    assert s["pods_unschedulable_total"] == 1
    assert s["fallback_cycles_total"] == 1
    assert abs(s["scheduling_pods_per_sec"] - 29 / 0.4) < 1e-6
    assert s["bind_latency_p99_seconds"] == 0.30
    assert s["batch_size_mean"] == 15.0


def test_render_prometheus_format():
    text = render_prometheus(make_metrics())
    assert "# TYPE yoda_tpu_pods_bound_total counter" in text
    assert "# TYPE yoda_tpu_bind_latency_p99_seconds gauge" in text
    assert "yoda_tpu_pods_bound_total 29" in text
    # every sample line parses as "name value"
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            name, value = line.split()
            float(value)


def test_metrics_exporter_http():
    class FakeScheduler:
        metrics = make_metrics()

    exporter = MetricsExporter(FakeScheduler())
    port = exporter.serve(0)
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
            body = r.read().decode()
        assert "yoda_tpu_pods_bound_total 29" in body
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            assert r.read() == b"ok\n"
    finally:
        exporter.close()


def test_cycle_tracer_spans():
    lines = []
    tracer = CycleTracer(sink=lines.append)
    with tracer.span("snapshot"):
        pass
    with tracer.span("engine"):
        pass
    tracer.emit(cycle=1, pods=5)
    rec = json.loads(lines[0])
    assert rec["cycle"] == 1
    assert "span_snapshot_seconds" in rec and "span_engine_seconds" in rec
    # spans reset between cycles
    tracer.emit(cycle=2)
    assert "span_engine_seconds" not in json.loads(lines[1])
