"""Capstone property sweep: random clusters exercising EVERY constraint
family at once — taints/tolerations, OR-of-ANDs node affinity, namespace-
scoped inter-pod (anti)affinity, hard topology spread, cordons, priorities
— through the full host pipeline (SnapshotBuilder -> schedule_batch), with
every binding validated against pure-Python final-state oracles.

Final-state checks are sound for the per-placement families too: anti-
affinity and spread are enforced against live counts at placement time,
and both are monotone (counts only grow, the spread min only rises), so a
valid placement sequence implies a valid final state.
"""

import dataclasses

import numpy as np
import pytest

from kubernetes_scheduler_tpu.engine import schedule_batch
from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder
from kubernetes_scheduler_tpu.host.types import (
    Container,
    MatchExpression,
    Node,
    Pod,
    PodAffinityTerm,
    SpreadConstraint,
    Taint,
    Toleration,
    labels_match,
)
from tests import oracle

ZONES = ["za", "zb", "zc"]
NAMESPACES = ["default", "prod"]


def gen_cluster(rng, n):
    nodes = []
    for i in range(n):
        labels = {"topology.kubernetes.io/zone": ZONES[i % len(ZONES)]}
        if rng.random() < 0.5:
            labels["disk"] = rng.choice(["ssd", "hdd"])
        taints = []
        if rng.random() < 0.2:
            taints.append(Taint(key="dedicated",
                                value=rng.choice(["x", "y"]),
                                effect="NoSchedule"))
        nodes.append(Node(
            name=f"n{i}", labels=labels, taints=taints,
            allocatable={"cpu": 8000.0, "memory": 2**33, "pods": 110},
        ))
    return nodes


def gen_pod(rng, i, spread_groups=None):
    labels = {}
    if rng.random() < 0.6:
        labels["app"] = rng.choice(["web", "db"])
    kw = dict(
        name=f"p{i}",
        namespace=rng.choice(NAMESPACES),
        labels=labels,
        containers=[Container(requests={"cpu": float(rng.integers(100, 800)),
                                        "memory": float(2**20)})],
        annotations={"diskIO": str(rng.integers(0, 20))},
    )
    if rng.random() < 0.3:
        # mix Exists and value-bound Equal tolerations: an Equal for the
        # wrong taint value must NOT admit (full upstream semantics)
        if rng.random() < 0.5:
            kw["tolerations"] = [Toleration(key="dedicated",
                                            operator="Exists")]
        else:
            kw["tolerations"] = [Toleration(key="dedicated",
                                            value=rng.choice(["x", "y"]),
                                            operator="Equal")]
    if rng.random() < 0.4:
        # OR-of-ANDs: zone in {x} OR (zone in {y} AND disk=ssd)
        z1, z2 = rng.choice(ZONES, 2, replace=False)
        kw["node_affinity"] = [
            MatchExpression(key="topology.kubernetes.io/zone", operator="In",
                            values=[z1], term=0),
            MatchExpression(key="topology.kubernetes.io/zone", operator="In",
                            values=[z2], term=1),
            MatchExpression(key="disk", operator="In", values=["ssd"], term=1),
        ]
    terms = []
    if rng.random() < 0.3 and labels.get("app"):
        terms.append(PodAffinityTerm(
            match_labels={"app": labels["app"]}, anti=True,
            topology_key="topology.kubernetes.io/zone",
            namespaces=[kw["namespace"]],
        ))
    if terms:
        kw["pod_affinity"] = terms
    # spread constraints attach to WHOLE (namespace, app) groups: the
    # final-state oracle is only sound when every matcher is constrained
    # (upstream DoNotSchedule binds only pods that DECLARE the
    # constraint — an unconstrained matcher may legally raise the skew
    # after a constrained pod placed)
    if (
        spread_groups
        and labels.get("app")
        and (kw["namespace"], labels["app"]) in spread_groups
    ):
        kw["topology_spread"] = [SpreadConstraint(
            match_labels={"app": labels["app"]},
            topology_key="topology.kubernetes.io/zone",
            max_skew=2, namespaces=[kw["namespace"]],
        )]
    if rng.random() < 0.5:
        kw["labels"] = {**labels, "scv/priority": str(rng.integers(0, 5))}
    return Pod(**kw)


def gen_utils(rng, nodes):
    """Random advisor utilization block, shared by every sweep so the
    families exercise one input distribution."""
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil

    return {nd.name: NodeUtil(cpu_pct=float(rng.uniform(0, 80)),
                              disk_io=float(rng.uniform(0, 40)))
            for nd in nodes}


def gen_scenario(rng, n, n_running):
    """Shared fixture recipe: cluster, spread-group membership, pending
    pod factory inputs, placed running pods, and advisor utils — one
    definition so the capstone sweep and the windows-carry sweep cannot
    diverge in what they exercise."""
    nodes = gen_cluster(rng, n)
    spread_groups = {
        (ns, app)
        for ns in NAMESPACES
        for app in ("web", "db")
        if rng.random() < 0.5
    }
    running = []
    for i in range(n_running):
        rp = gen_pod(rng, 100 + i, spread_groups)
        rp.node_name = nodes[int(rng.integers(0, n))].name
        running.append(rp)
    return nodes, spread_groups, running, gen_utils(rng, nodes)


def zone_of(node):
    return node.labels["topology.kubernetes.io/zone"]


@pytest.mark.parametrize("assigner", ["greedy", "auction"])
@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_all_families_against_final_state_oracle(seed, assigner):
    rng = np.random.default_rng(1000 + seed)
    n, p = 24, 20
    nodes, spread_groups, running, utils = gen_scenario(rng, n, 6)
    pods = [gen_pod(rng, i, spread_groups) for i in range(p)]

    b = SnapshotBuilder()
    snap = b.build_snapshot(nodes, utils, running, pending_pods=pods)
    batch = b.build_pod_batch(pods)
    res = schedule_batch(snap, batch, assigner=assigner,
                     affinity_aware=True, soft=True)
    idx = np.asarray(res.node_idx)[:p]

    placed = [
        (pod, nodes[int(j)]) for pod, j in zip(pods, idx) if 0 <= j < n
    ]
    assert placed, "sweep is vacuous if nothing schedules"

    # 1. capacity: aggregate requests fit allocatable
    used = {nd.name: 0.0 for nd in nodes}
    for rp in running:
        used[rp.node_name] += rp.containers[0].requests["cpu"]
    for pod, nd in placed:
        used[nd.name] += pod.containers[0].requests["cpu"]
    for nd in nodes:
        assert used[nd.name] <= nd.allocatable["cpu"] + 1e-6, nd.name

    # 2. taints via the full-semantics oracle (tests/oracle.py uses the
    # snapshot encodings: effect 1=NoSchedule; op 0=Exists, 1=Equal)
    for pod, nd in placed:
        taints = [(hash(t.key), hash(t.value), 1) for t in nd.taints]
        tols = [
            (None if tol.key is None else hash(tol.key),
             hash(tol.value),
             0 if tol.operator == "Exists" else 1,
             0)
            for tol in pod.tolerations
        ]
        assert oracle.taint_fit_oracle(taints, tols), (pod.name, nd.name)

    # 3. OR-of-ANDs node affinity via the oracle
    for pod, nd in placed:
        by_term = {}
        for e in pod.node_affinity:
            by_term.setdefault(e.term, []).append(e)
        terms = [
            [(e.key, {"In": 0, "NotIn": 1, "Exists": 2,
                      "DoesNotExist": 3}[e.operator], e.values)
             for e in exprs]
            for exprs in by_term.values()
        ]
        # oracle speaks interned-id-free dicts: use string keys/values
        assert oracle.node_affinity_terms_oracle(nd.labels, terms), (
            pod.name, nd.name, terms, nd.labels)

    # final placement sets per (namespace, zone)
    def members(namespace, zone):
        out = []
        for rp in running:
            nd = next(x for x in nodes if x.name == rp.node_name)
            if rp.namespace == namespace and zone_of(nd) == zone:
                out.append(rp)
        for pod, nd in placed:
            if pod.namespace == namespace and zone_of(nd) == zone:
                out.append(pod)
        return out

    # 4. hard anti-affinity final state: no OTHER matcher of the selector
    # in the pod's zone within the scoped namespace
    for pod, nd in placed:
        for term in pod.pod_affinity:
            if term.preferred or not term.anti:
                continue
            for other in members(term.namespaces[0], zone_of(nd)):
                if other is pod:
                    continue
                assert not labels_match(
                    other.labels, term.match_labels, term.match_expressions
                ), (pod.name, other.name, zone_of(nd))

    # 5. hard spread final state: count - min over zones <= maxSkew
    for pod, nd in placed:
        for sc in pod.topology_spread:
            if sc.soft:
                continue
            counts = {
                z: sum(
                    1 for m in members(sc.namespaces[0], z)
                    if labels_match(m.labels, sc.match_labels,
                                    sc.match_expressions)
                )
                for z in ZONES
            }
            skew = counts[zone_of(nd)] - min(counts.values())
            assert skew <= sc.max_skew, (pod.name, counts)


@pytest.mark.parametrize("assigner", ["greedy", "auction"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_windows_carry_matches_sequential_rebuild(seed, assigner):
    """The deep-backlog scan (schedule_windows: capacity + (anti)affinity
    domain counts folded BETWEEN windows on device) must make exactly the
    decisions of sequential per-window schedule_batch dispatches where the
    host re-snapshots between windows with the prior windows' placements
    as running pods — the production one-window-per-cycle shape. Pins
    fold_window_counts/free_after against the from-scratch rebuild."""
    from kubernetes_scheduler_tpu.engine import schedule_windows, stack_windows

    rng = np.random.default_rng(2000 + seed)
    n, w, n_windows = 16, 8, 3
    p = w * n_windows
    nodes, spread_groups, running, utils = gen_scenario(rng, n, 4)
    pods = [gen_pod(rng, i, spread_groups) for i in range(p)]
    kw = dict(assigner=assigner, normalizer="none",
              affinity_aware=True, soft=True)

    # (a) one deep dispatch, carries on device
    b1 = SnapshotBuilder()
    snap = b1.build_snapshot(nodes, utils, running, pending_pods=pods)
    batch = b1.build_pod_batch(pods)
    wres = schedule_windows(snap, stack_windows(batch, w), **kw)
    deep_idx = np.asarray(wres.node_idx).reshape(-1)[:p]

    # (b) sequential per-window dispatches, host re-snapshot between
    b2 = SnapshotBuilder()
    run2 = list(running)
    seq_idx = []
    for k in range(n_windows):
        win = pods[k * w:(k + 1) * w]
        # fresh Pod objects: the deep path's builder cached rows on the
        # originals; cloning guards against accidental cache coupling
        win = [dataclasses.replace(pd) for pd in win]
        s2 = b2.build_snapshot(nodes, utils, run2, pending_pods=win)
        r2 = schedule_batch(s2, b2.build_pod_batch(win), **kw)
        idx2 = np.asarray(r2.node_idx)[:w]
        seq_idx.extend(int(j) for j in idx2)
        for pd, j in zip(win, idx2):
            if 0 <= j < n:
                placed = dataclasses.replace(pd, node_name=nodes[int(j)].name)
                run2.append(placed)
    assert deep_idx.tolist() == seq_idx, (deep_idx.tolist(), seq_idx)
    assert any(0 <= j < n for j in seq_idx), "sweep is vacuous"


@pytest.mark.parametrize("seed", [0, 1])
def test_incremental_builder_churn_sweep_matches_fresh(seed):
    """Cache-soundness sweep: one long-lived SnapshotBuilder fed
    informer-style churn — nodes added/removed/replaced (new objects,
    changed labels/taints), the running list both appended in place and
    rebuilt wholesale, constrained and plain pods mixed — must produce
    snapshots identical to a FRESH builder's full rebuild every cycle.
    Pins the identity-keyed caches (_node_static, _acc_cache,
    _ports_prefix, _dc_prefix, per-pod byte records) through every
    invalidation path at once."""
    rng = np.random.default_rng(3000 + seed)
    nodes = gen_cluster(rng, 10)
    spread_groups = {("default", "web"), ("prod", "db")}
    running: list = []
    inc = SnapshotBuilder()
    next_node = 10

    def churn_node(name):
        nd = gen_cluster(rng, 1)[0]
        nd.name = name
        # gen_cluster gives index-0 the first zone; re-roll so churn
        # keeps the zone set diverse instead of drifting toward za
        nd.labels["topology.kubernetes.io/zone"] = rng.choice(ZONES)
        return nd

    for cycle in range(12):
        # node churn: add / remove / replace-with-modified-object
        ev = rng.random()
        if ev < 0.25 and len(nodes) < 16:
            nodes.append(churn_node(f"n{next_node}"))
            next_node += 1
        elif ev < 0.4 and len(nodes) > 6:
            gone = nodes.pop(int(rng.integers(0, len(nodes))))
            running = [rp for rp in running if rp.node_name != gone.name]
        elif ev < 0.6:
            i = int(rng.integers(0, len(nodes)))
            # same name, NEW object + fresh labels/taints
            nodes[i] = churn_node(nodes[i].name)
        # running-list churn: informer resync rebuilds the list object
        if rng.random() < 0.3:
            running = list(running)
        pods = [gen_pod(rng, 1000 * cycle + i, spread_groups)
                for i in range(6)]
        utils = gen_utils(rng, nodes)
        s_inc = inc.build_snapshot(nodes, utils, running, pending_pods=pods)
        b_inc = inc.build_pod_batch(pods)
        fresh = SnapshotBuilder()
        s_new = fresh.build_snapshot(nodes, utils, running, pending_pods=pods)
        b_new = fresh.build_pod_batch(pods)
        # interner ids may differ between builders (append-only across
        # the incremental builder's lifetime), so compare the
        # id-independent arrays exactly and the id-carrying ones by
        # shape-safe DECISION equality below
        for name in ("allocatable", "requested", "node_mask", "disk_io",
                     "cpu_pct", "mem_pct", "net_up", "net_down"):
            a = np.asarray(getattr(s_inc, name))
            b = np.asarray(getattr(s_new, name))
            assert a.shape == b.shape, (cycle, name, a.shape, b.shape)
            np.testing.assert_allclose(
                a, b, rtol=1e-6, err_msg=f"cycle {cycle}: {name}"
            )
        # decision parity: the engine over each builder's arrays must
        # agree (covers labels/taints/selector tables whose interned ids
        # legitimately differ)
        r_inc = schedule_batch(s_inc, b_inc, assigner="greedy",
                               affinity_aware=True, soft=True)
        r_new = schedule_batch(s_new, b_new, assigner="greedy",
                               affinity_aware=True, soft=True)
        idx_i = np.asarray(r_inc.node_idx)[:6]
        idx_n = np.asarray(r_new.node_idx)[:6]
        np.testing.assert_array_equal(idx_i, idx_n, err_msg=f"cycle {cycle}")
        for pd, j in zip(pods, idx_i):
            if 0 <= j < len(nodes):
                running.append(
                    dataclasses.replace(pd, node_name=nodes[int(j)].name)
                )
    assert running, "sweep is vacuous if nothing ever places"

