"""Shadow-mode serving (host/shadow.py): the read-only rescoring loop.

The contract under test is PARITY.md round 21: a ShadowScheduler fed
the soak journal through a candidate configured IDENTICALLY to the
primary must diff to zero — bitwise reconstruction plus a deterministic
engine leaves no room for drift — while a genuinely different candidate
produces a non-zero, run-stable decision diff. The isolation half of
the contract is pinned from both sides: a wedged candidate trips the
breaker and tailing continues (the shadow outlives its candidate), and
a live primary's journal is bitwise unchanged by a shadow tailing it
(the primary never feels the shadow)."""

import threading
import time

import pytest

from kubernetes_scheduler_tpu.host.scheduler import SchedulerConfig
from kubernetes_scheduler_tpu.host.shadow import (
    MODES,
    ShadowScheduler,
    candidate_kw,
)
from kubernetes_scheduler_tpu.sim import scenarios
from kubernetes_scheduler_tpu.sim.scenarios import SCENARIOS, scenario_config
from kubernetes_scheduler_tpu.trace import inspect as tinspect
from kubernetes_scheduler_tpu.trace.recorder import last_journal_seq


def _soak_config(**overrides) -> SchedulerConfig:
    """The exact config run_scenario uses for the soak program — the
    'identical candidate' of the parity contract (overrides carve out
    the divergent-candidate variants)."""
    kw = dict(SCENARIOS["soak"].config_overrides)
    kw.update(overrides)
    return scenario_config(kw)


@pytest.fixture(scope="module")
def soak_journal(tmp_path_factory):
    """One recorded soak journal shared by the module: 48 device-path
    cycles across several rotated files (the soak's smoke-size
    trace_file_bytes forces rotation, so catch-up crosses boundaries)."""
    path = str(tmp_path_factory.mktemp("shadow") / "journal")
    summary = scenarios.run("soak", n_nodes=16, seed=0, trace_path=path)
    assert summary["pods_bound"] > 0
    assert summary["fallback_cycles"] == 0
    return path, summary


def test_shadow_identical_config_zero_divergence(soak_journal, tmp_path):
    journal, primary = soak_journal
    shadow = ShadowScheduler(
        journal, _soak_config(), span_path=str(tmp_path / "spans")
    )
    try:
        summary = shadow.run()
    finally:
        shadow.close()
    assert summary["records_applied"] == primary["cycles"]
    assert summary["cycles"] == {"scored": summary["records_applied"]}
    assert summary["pods_compared"] > 0
    assert summary["bindings_changed"] == 0
    assert summary["divergence_ratio"] == 0.0
    assert summary["gangs_diverged"] == 0
    assert summary["score_delta_mean"] == 0.0
    assert summary["candidate_errors"] == 0
    assert summary["breaker_state"] == "closed"
    assert summary["unanchored_skips"] == 0
    # the candidate actually ran (latency diff is real data)
    assert summary["candidate_engine_seconds"] > 0
    assert summary["recorded_engine_seconds"] > 0
    assert summary["latency_ratio"] > 0
    # catch-up crossed the soak's rotation boundaries
    assert summary["tail"]["rotations_followed"] >= 1
    assert summary["tail"]["records_yielded"] == summary["records_applied"]
    # the shadow's own span stream carries the shipped stage names
    from kubernetes_scheduler_tpu.trace.spans import (
        read_span_file,
        span_files,
    )

    names = {
        ev["name"]
        for fp in span_files(str(tmp_path / "spans"))
        for ev in read_span_file(fp)
        if ev.get("ph") == "X"
    }
    assert {"cycle", "reconstruct", "candidate_step", "decision_diff"} <= names


def test_shadow_modes_agree_on_decisions(soak_journal):
    journal, _ = soak_journal
    results = {}
    for mode in MODES:
        shadow = ShadowScheduler(journal, _soak_config(), mode=mode)
        summary = shadow.run()
        results[mode] = {
            k: summary[k]
            for k in (
                "records_applied", "cycles", "pods_compared",
                "bindings_changed", "gangs_diverged",
            )
        }
    assert results["serial"] == results["pipelined"]
    assert results["serial"]["bindings_changed"] == 0


def test_shadow_divergent_candidate_is_deterministic(soak_journal):
    journal, _ = soak_journal

    def once():
        shadow = ShadowScheduler(
            journal, _soak_config(policy="least_allocated")
        )
        s = shadow.run()
        return {
            k: s[k]
            for k in (
                "records_applied", "pods_compared", "bindings_changed",
                "divergence_ratio", "gangs_diverged", "score_delta_mean",
            )
        }

    s1, s2 = once(), once()
    # a different policy genuinely moves pods...
    assert s1["bindings_changed"] > 0
    assert s1["divergence_ratio"] > 0
    # ...and the candidate scores its own placements higher than the
    # primary's on the rows it moved (its units, its opinion)
    assert s1["score_delta_mean"] > 0
    # ...by exactly the same amount every run: the diff is evidence,
    # not noise
    assert s1 == s2


def test_shadow_breaker_guards_wedged_candidate(soak_journal):
    class WedgedEngine:
        def schedule_windows(self, *a, **kw):
            raise RuntimeError("candidate wedged")

        def schedule_batch(self, *a, **kw):
            raise RuntimeError("candidate wedged")

    journal, primary = soak_journal
    cfg = _soak_config()
    shadow = ShadowScheduler(journal, cfg, engine=WedgedEngine())
    summary = shadow.run()  # must not raise: tailing outlives the candidate
    assert summary["records_applied"] == primary["cycles"]
    # failures counted until the breaker opened, then cycles skipped
    assert summary["candidate_errors"] >= cfg.breaker_failure_threshold
    assert summary["breaker_skips"] > 0
    assert summary["breaker_state"] == "open"
    assert summary["cycles"].get("scored", 0) == 0
    assert (
        summary["cycles"]["error"] + summary["cycles"]["breaker_open"]
        == summary["records_applied"]
    )
    # records still folded while the breaker was open: the delta chain
    # stayed anchored, so nothing went unanchored
    assert summary["unanchored_skips"] == 0
    assert summary["bindings_changed"] == 0


def test_shadow_resume_seq_skips_replayed_records(soak_journal):
    journal, _ = soak_journal
    last = last_journal_seq(journal)
    assert last is not None
    shadow = ShadowScheduler(journal, _soak_config(), resume_seq=last)
    summary = shadow.run()
    # everything at or below the watermark is filtered, nothing scored
    assert summary["records_applied"] == 0
    assert summary["tail"]["records_filtered"] > 0
    assert summary["cycles"] == {}


def test_shadow_rejects_unknown_mode(tmp_path):
    with pytest.raises(ValueError, match="unknown shadow mode"):
        ShadowScheduler(str(tmp_path / "j"), _soak_config(), mode="turbo")


def test_candidate_kw_swaps_scoring_surface_only():
    base = SchedulerConfig()
    recorded = {
        "policy": "card",
        "assigner": base.assigner,
        "normalizer": "min_max",
        "fused": True,
        "auction_rounds": 7,
        "auction_price_frac": 0.5,
    }
    cfg = SchedulerConfig(policy="balanced_cpu_diskio", normalizer="none")
    kw = candidate_kw(recorded, cfg)
    assert kw["policy"] == "balanced_cpu_diskio"
    assert kw["normalizer"] == "none"
    assert kw["auction_rounds"] == cfg.auction_rounds
    assert kw["auction_price_frac"] == cfg.auction_price_frac
    # fused survives only inside the candidate's fusable domain
    assert kw["fused"] is True
    kw2 = candidate_kw(recorded, SchedulerConfig(policy="least_allocated"))
    assert kw2["fused"] is False
    # the recorded kw is input, not scratch space
    assert recorded["policy"] == "card" and recorded["fused"] is True


def test_shadow_exporter_renders_shipped_metrics(soak_journal):
    journal, _ = soak_journal
    shadow = ShadowScheduler(journal, _soak_config())
    shadow.run()
    body = shadow._render()
    for name in (
        "shadow_records_applied_total",
        "shadow_cycles_total",
        "shadow_bindings_changed_total",
        "shadow_pods_compared_total",
        "shadow_divergence_ratio",
        "shadow_latency_ratio",
        "shadow_candidate_step_duration_seconds",
        "shadow_rotations_followed_total",
    ):
        assert name in body, name


def test_shadow_divergent_diff_matches_through_live_sidecar(soak_journal):
    """The candidate engine can be a live bridge sidecar: the shadow's
    decision diff through the wire is identical to the in-process one
    (the diff is a property of the candidate config, not the engine
    residency)."""
    pytest.importorskip("grpc")
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server

    journal, _ = soak_journal
    keys = (
        "records_applied", "pods_compared", "bindings_changed",
        "divergence_ratio", "gangs_diverged", "score_delta_mean",
    )
    local = ShadowScheduler(
        journal, _soak_config(policy="least_allocated")
    ).run()
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        remote = ShadowScheduler(
            journal, _soak_config(policy="least_allocated"), engine=client
        ).run()
    finally:
        client.close()
        server.stop(grace=None)
    assert local["bindings_changed"] > 0
    assert {k: remote[k] for k in keys} == {k: local[k] for k in keys}


def test_shadow_on_vs_off_bitwise_e2e(tmp_path):
    """PARITY.md round 21, the in-process half: a primary tailed LIVE
    by a shadow writes a journal bitwise identical to an undisturbed
    run — the shadow never perturbs a single decision — while the
    shadow scores every cycle with zero divergence as they land."""
    journal_off = str(tmp_path / "journal-off")
    baseline = scenarios.run(
        "soak", n_nodes=16, seed=0, trace_path=journal_off
    )

    journal = str(tmp_path / "journal")
    live: dict = {}

    def primary():
        live["summary"] = scenarios.run(
            "soak", n_nodes=16, seed=0, trace_path=journal
        )

    t = threading.Thread(target=primary, daemon=True)
    t.start()
    from kubernetes_scheduler_tpu.trace.recorder import journal_files

    deadline = time.monotonic() + 120
    while not journal_files(journal):
        assert time.monotonic() < deadline, "live journal never appeared"
        assert t.is_alive() or "summary" in live
        time.sleep(0.05)
    shadow = ShadowScheduler(journal, _soak_config())
    summary = shadow.run(
        follow=True, poll_interval_s=0.05, idle_timeout_s=20
    )
    t.join(timeout=120)
    assert not t.is_alive()
    assert live["summary"]["cycles"] == baseline["cycles"]

    # the primary never felt the shadow: bitwise-equal journals
    report = tinspect.diff(journal_off, journal)
    assert report["differences"] == 0, report
    assert report["extra_records_a"] == 0, report
    assert report["extra_records_b"] == 0, report
    assert report["records_compared"] == baseline["cycles"], report

    # and the shadow kept up live: every cycle scored, zero divergence
    assert summary["records_applied"] == live["summary"]["cycles"]
    assert summary["cycles"] == {"scored": summary["records_applied"]}
    assert summary["bindings_changed"] == 0
    assert summary["divergence_ratio"] == 0.0
    assert summary["tail"]["rotations_followed"] >= 1
