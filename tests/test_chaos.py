"""graftchaos: deterministic fault injection (sim/faults.py), the
unified retry/backoff/breaker layer, and the degradation ladder
(host/resilience.py) — plus the compound-downgrade coverage the PR-3/
PR-13 interaction never had: capability downgrade + mirror resync +
pipeline flush landing in the SAME cycle window."""

import numpy as np
import pytest

from kubernetes_scheduler_tpu.engine import LocalEngine
from kubernetes_scheduler_tpu.host import NodeUtil, Scheduler, StaticAdvisor
from kubernetes_scheduler_tpu.host.resilience import (
    BackoffPolicy,
    CircuitBreaker,
    DegradationLadder,
)
from kubernetes_scheduler_tpu.sim.faults import (
    FaultError,
    FaultInjector,
    FaultPartition,
    FaultPlan,
    FaultTimeout,
    FaultWindow,
    FaultyAdvisor,
    FaultyEngine,
    InformerGate,
)
from kubernetes_scheduler_tpu.sim.scenarios import SCENARIOS, SimClock, run_scenario
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig
from tests.test_pipeline import make_node, make_pod


# ---- resilience primitives -------------------------------------------------


def test_backoff_deterministic_jitter():
    p = BackoffPolicy(initial=0.5, max_delay=8.0, multiplier=2.0)
    # same (key, attempt) -> same delay, bit for bit; keys de-phase
    assert p.delay(3, key="advisor") == p.delay(3, key="advisor")
    assert p.delay(3, key="advisor") != p.delay(3, key="bridge:a")
    # exponential growth under the cap, jitter only shaves (<= 25%)
    for attempt in range(8):
        base = min(0.5 * 2**attempt, 8.0)
        d = p.delay(attempt, key="k")
        assert 0.75 * base <= d <= base


def test_breaker_lifecycle_single_probe_per_window():
    clk = [0.0]
    moves = []
    b = CircuitBreaker(
        "engine", failure_threshold=2, recovery_window_s=5.0,
        clock=lambda: clk[0],
        on_transition=lambda name, state: moves.append((name, state)),
    )
    assert b.allow() and b.state() == "closed"
    b.record_failure()
    assert b.state() == "closed" and b.allow()  # under threshold
    b.record_failure()
    assert b.state() == "open" and not b.allow()
    clk[0] = 4.9
    assert not b.allow()  # window not elapsed
    clk[0] = 5.0
    assert b.allow() and b.state() == "half-open"
    assert not b.allow()  # ONE probe per window
    b.record_failure()    # probe failed: re-open, window restarts
    assert b.state() == "open" and not b.allow()
    clk[0] = 10.5
    assert b.allow()
    b.record_success()
    assert b.state() == "closed" and b.allow()
    assert moves == [
        ("engine", "open"), ("engine", "half-open"),
        ("engine", "open"), ("engine", "half-open"), ("engine", "closed"),
    ]
    assert b.transition_counts == {"open": 2, "half-open": 2, "closed": 1}


def test_breaker_leaked_probe_expires_and_peek_is_side_effect_free():
    clk = [0.0]
    b = CircuitBreaker(
        "engine", failure_threshold=1, recovery_window_s=5.0,
        clock=lambda: clk[0],
    )
    b.record_failure()
    clk[0] = 5.0
    assert b.allow()  # half-open probe issued...
    # ...and its outcome never recorded (the caller's cycle took a path
    # with no record_* — the wedged-half-open class): after a full
    # recovery window the probe is presumed lost and a fresh one admits
    clk[0] = 9.0
    assert not b.allow()
    clk[0] = 10.0
    assert b.allow()
    b.record_success()
    assert b.state() == "closed"
    # peek() predicts allow() without consuming the probe
    b2 = CircuitBreaker(
        "bridge:x", failure_threshold=1, recovery_window_s=5.0,
        clock=lambda: clk[0],
    )
    b2.record_failure()
    assert not b2.peek()
    clk[0] = 20.0
    assert b2.peek() and b2.peek()  # no side effects
    assert b2.state() == "open"     # peek never transitions
    assert b2.allow() and b2.state() == "half-open"
    assert not b2.peek()            # fresh probe outstanding


def test_ladder_one_rung_probe_promote_and_gauge():
    lad = DegradationLadder()
    assert lad.fully_recovered() and lad.degraded() == ()
    # demote moves exactly one rung per call; bottom is sticky
    assert lad.demote("engine", reason="outage", seq=3)
    assert lad.rung("engine") == "local" and lad.depth("engine") == 1
    assert not lad.demote("engine", reason="again", seq=4)  # already bottom
    assert lad.degraded() == ("engine",)
    assert lad.reasons["engine"] == "outage" and lad.entry_seq["engine"] == 3
    # promote without probe is flagged but never climbs un-probed:
    # the implicit probe event is recorded first
    assert lad.promote("engine", seq=5)
    actions = [e["action"] for e in lad.events]
    assert actions == ["demote", "probe", "promote"]
    assert lad.fully_recovered()
    # the exported gauge carries one sample per subsystem
    text = "\n".join(lad.gauge.render())
    assert 'degradation_rung{subsystem="engine"} 0' in text
    assert 'degradation_rung{subsystem="mirror"} 0' in text


def test_fault_plan_windows_flap_and_kinds():
    clk = [0.0]
    plan = FaultPlan((
        FaultWindow(boundary="engine", kind="flap", start=2, end=8, period=2),
        FaultWindow(boundary="advisor", kind="error", start=4, end=6),
        FaultWindow(boundary="engine", kind="timeout", start=10, end=11),
        FaultWindow(boundary="informer", kind="partition", start=1, end=3),
    ))
    inj = FaultInjector(plan, clock=lambda: clk[0])
    inj.check("engine")  # t=0: nothing active
    clk[0] = 2.0  # flap phase 0: fails
    with pytest.raises(FaultError):
        inj.check("engine")
    clk[0] = 3.0  # flap phase 1: passes
    inj.check("engine")
    clk[0] = 4.0
    with pytest.raises(FaultError):
        inj.check("engine")
    with pytest.raises(FaultError):
        inj.check("advisor")
    clk[0] = 10.5
    with pytest.raises(FaultTimeout):
        inj.check("engine")
    clk[0] = 1.5
    with pytest.raises(FaultPartition):
        inj.check("informer")
    assert inj.summary() == {
        "advisor:error": 1, "engine:flap": 2, "engine:timeout": 1,
        "informer:partition": 1,
    }
    assert not inj.quiesced()
    clk[0] = 11.0
    assert inj.quiesced()
    # declaration errors are loud
    with pytest.raises(ValueError):
        FaultWindow(boundary="nowhere", kind="error", start=0, end=1)
    with pytest.raises(ValueError):
        FaultWindow(boundary="engine", kind="gremlins", start=0, end=1)
    with pytest.raises(ValueError):
        FaultWindow(boundary="engine", kind="error", start=2, end=2)


def test_informer_gate_partition_buffers_error_drops():
    clk = [0.0]
    plan = FaultPlan((
        FaultWindow(boundary="informer", kind="partition", start=1, end=3),
        FaultWindow(boundary="informer", kind="error", start=5, end=6),
    ))
    gate = InformerGate(FaultInjector(plan, clock=lambda: clk[0]))
    got = []
    gate.deliver(got.append, "a")
    assert got == ["a"]
    clk[0] = 1.5  # partition: buffered
    gate.deliver(got.append, "b")
    gate.deliver(got.append, "c")
    assert got == ["a"] and gate.flush() == 0  # still partitioned
    clk[0] = 3.0
    assert gate.flush() == 2
    assert got == ["a", "b", "c"]  # arrival order preserved
    clk[0] = 5.5  # error: dropped outright
    gate.deliver(got.append, "d")
    assert got == ["a", "b", "c"] and gate.dropped == 1


def test_faulty_advisor_and_engine_wrappers():
    clk = [0.0]
    plan = FaultPlan((
        FaultWindow(boundary="advisor", kind="error", start=1, end=2),
        FaultWindow(boundary="engine", kind="error", start=1, end=2),
    ))
    inj = FaultInjector(plan, clock=lambda: clk[0])
    adv = FaultyAdvisor(StaticAdvisor({"n0": NodeUtil(cpu_pct=5.0)}), inj)
    eng = FaultyEngine(LocalEngine(), inj)
    assert adv.fetch()["n0"].cpu_pct == 5.0
    assert adv.fetch_changed() == {"n0": adv.inner.utils["n0"]}
    assert adv.fetch_changed() == {}  # coalescing: nothing moved
    assert eng.supports_resident() in (True, False)  # delegation works
    clk[0] = 1.0
    with pytest.raises(FaultError):
        adv.fetch()
    with pytest.raises(FaultError):
        eng.schedule_batch(None, None)
    # health probes OBSERVE the outage instead of raising
    assert eng.healthy() is False and eng.health_info() is None
    assert inj.injected[("engine", "health-observed")] == 2


# ---- satellite 1: health-probe classification + breaker feed ---------------


def test_health_probe_classifies_and_feeds_breaker():
    grpc = pytest.importorskip("grpc")
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine

    client = RemoteEngine("127.0.0.1:1", deadline_seconds=1.0)
    try:
        class _Rpc(grpc.RpcError):
            def __init__(self, code):
                self._code = code

            def code(self):
                return self._code

            def details(self):
                return ""

        calls = {"n": 0}

        def dead_health(request, timeout=None, **kw):
            calls["n"] += 1
            raise _Rpc(
                grpc.StatusCode.DEADLINE_EXCEEDED
                if calls["n"] == 1
                else grpc.StatusCode.UNAVAILABLE
            )

        client._health = dead_health
        client.breaker.failure_threshold = 2
        assert client.healthy() is False       # deadline-exceeded
        assert client.health_info() is None    # transport-down -> opens
        assert client.breaker.state() == "open"
        # open breaker: answered without touching the wire
        assert client.healthy() is False and calls["n"] == 2
        series = dict(client.ctr_health_failures._series)
        assert series == {
            ("deadline",): 1, ("transport",): 1, ("breaker-open",): 1,
        }
    finally:
        client.close()


def test_call_with_retry_blocked_by_open_breaker():
    pytest.importorskip("grpc")
    from kubernetes_scheduler_tpu.bridge.client import (
        EngineUnavailable,
        RemoteEngine,
    )

    client = RemoteEngine("127.0.0.1:1", deadline_seconds=1.0)
    try:
        client.breaker.record_failure()
        client.breaker.record_failure()
        client.breaker.record_failure()
        assert client.breaker.state() == "open"
        with pytest.raises(EngineUnavailable, match="circuit open"):
            client._call_with_retry(lambda *a, **kw: None, None)
    finally:
        client.close()


# ---- scheduler integration: stale grace, backoff hold, breaker -------------


class _FlakyAdvisor:
    def __init__(self, utils):
        self.utils = utils
        self.fail = False
        self.calls = 0

    def fetch(self):
        self.calls += 1
        if self.fail:
            raise RuntimeError("prometheus down")
        return self.utils


def _mini_cluster():
    nodes = [make_node("n0"), make_node("n1")]
    utils = {
        nd.name: NodeUtil(cpu_pct=10.0, disk_io=2.0) for nd in nodes
    }
    return nodes, utils


def _mini_sched(advisor, nodes, clk, **cfg_kw):
    cfg = SchedulerConfig(
        batch_window=8, min_device_work=0, adaptive_dispatch=False,
        normalizer="none", **cfg_kw,
    )
    return Scheduler(
        cfg, advisor=advisor,
        list_nodes=lambda: nodes, list_running_pods=lambda: [],
        queue_clock=clk,
    )


def test_stale_ttl_grace_serves_lastgood_then_requeues():
    nodes, utils = _mini_cluster()
    adv = _FlakyAdvisor(utils)
    clk = SimClock()
    s = _mini_sched(adv, nodes, clk, advisor_stale_ttl_s=5.0)
    s.submit(make_pod("a", cpu=100, annotations={"diskIO": "2"}))
    m0 = s.run_cycle()
    assert m0.pods_bound == 1 and not m0.advisor_stale
    # outage inside the TTL: the cycle is SERVED (marked stale), the
    # window never stalls
    adv.fail = True
    clk.advance(2.0)
    s.submit(make_pod("b", cpu=100, annotations={"diskIO": "2"}))
    m1 = s.run_cycle()
    assert m1.pods_bound == 1 and m1.advisor_stale and not m1.fetch_failed
    assert s.totals["advisor_stale_cycles"] == 1
    # past the TTL: the outage path engages (requeue + backoff)
    clk.advance(10.0)
    s.submit(make_pod("c", cpu=100, annotations={"diskIO": "2"}))
    m2 = s.run_cycle()
    assert m2.fetch_failed and m2.pods_bound == 0
    # recovery: fetch heals, the requeued pod binds
    adv.fail = False
    clk.advance(20.0)
    m3 = s.run_cycle()
    assert m3.pods_bound == 1 and not m3.fetch_failed and not m3.advisor_stale
    assert s.advisor_breaker.state() == "closed"


def test_stale_grace_sees_own_binds_never_overcommits():
    """Grace-mode cycles read the LIVE cluster lists: pods the
    scheduler binds during the outage must consume capacity in later
    grace cycles (a frozen running snapshot would double-book)."""
    # one small node: capacity for exactly two 1000m pods
    nodes = [make_node("tiny", cpu=2000.0)]
    utils = {"tiny": NodeUtil(cpu_pct=10.0, disk_io=2.0)}
    adv = _FlakyAdvisor(utils)
    clk = SimClock()
    running: list = []
    cfg = SchedulerConfig(
        batch_window=1, max_windows_per_cycle=1, min_device_work=0,
        adaptive_dispatch=False, normalizer="none",
        advisor_stale_ttl_s=60.0,
    )
    s = Scheduler(
        cfg, advisor=adv,
        list_nodes=lambda: nodes, list_running_pods=lambda: list(running),
        queue_clock=clk,
    )

    def cycle(name):
        s.submit(make_pod(name, cpu=1000, annotations={"diskIO": "2"}))
        m = s.run_cycle()
        for b in s.binder.bindings[len(running):]:
            running.append(b.pod)
        clk.advance(1.0)
        return m

    assert cycle("warm").pods_bound == 1
    adv.fail = True  # outage: every cycle below runs on stale utils
    m1, m2 = cycle("g1"), cycle("g2")
    assert m1.advisor_stale and m2.advisor_stale
    # g1 bound (second slot); g2 must SEE g1 in the live running list
    # and be rejected — with a frozen snapshot both would bind
    assert m1.pods_bound == 1
    assert m2.pods_bound == 0 and m2.pods_unschedulable == 1


def test_advisor_outage_attempts_follow_backoff_not_every_cycle():
    nodes, utils = _mini_cluster()
    adv = _FlakyAdvisor(utils)
    clk = SimClock()
    s = _mini_sched(adv, nodes, clk)
    adv.fail = True
    # 12 cycles over 1.2 virtual seconds: the old loop would fetch
    # every cycle; the backoff hold paces attempts (first failure arms
    # a >= 0.375s hold, the next a longer one)
    for i in range(12):
        s.submit(make_pod(f"p{i}", cpu=100, annotations={"diskIO": "2"}))
        m = s.run_cycle()
        assert m.fetch_failed
        clk.advance(0.1)
    assert adv.calls <= 4
    assert s.totals["fetch_failures"] == 12  # every cycle still surfaced


class _FlakyEngine:
    """LocalEngine wrapper with a host-controlled failure flag and a
    dispatch counter (how often the device path was actually tried)."""

    def __init__(self):
        self.inner = LocalEngine()
        self.fail = False
        self.dispatches = 0

    def schedule_batch(self, snapshot, pods, **kw):
        self.dispatches += 1
        if self.fail:
            raise RuntimeError("device wedged")
        return self.inner.schedule_batch(snapshot, pods, **kw)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def test_engine_breaker_opens_skips_then_probes_back():
    nodes, utils = _mini_cluster()
    eng = _FlakyEngine()
    clk = SimClock()
    s = Scheduler(
        SchedulerConfig(
            batch_window=8, min_device_work=0, adaptive_dispatch=False,
            normalizer="none", policy="least_allocated",
            breaker_failure_threshold=2, breaker_recovery_window_s=4.0,
        ),
        advisor=StaticAdvisor(utils), engine=eng,
        list_nodes=lambda: nodes, list_running_pods=lambda: [],
        queue_clock=clk,
    )

    def cycle(i):
        s.submit(make_pod(f"p{i}", cpu=100, annotations={"diskIO": "2"}))
        m = s.run_cycle()
        clk.advance(1.0)
        return m

    assert not cycle(0).used_fallback
    eng.fail = True
    m1, m2 = cycle(1), cycle(2)
    assert m1.used_fallback and m2.used_fallback
    assert s.engine_breaker.state() == "open"
    assert s.ladder.rung("engine") == "local"
    before = eng.dispatches
    m3 = cycle(3)  # breaker open: scalar outright, engine NOT called
    assert m3.used_fallback and eng.dispatches == before
    # policy="least_allocated" has a scalar mirror: no policy mismatch,
    # the policy rung never moves — only the engine rung is degraded
    assert not m3.policy_mismatch and s.ladder.depth("policy") == 0
    assert m3.degraded == ("engine",)
    # the engine heals; the half-open probe (one per window) retests
    eng.fail = False
    clk.advance(4.0)
    m4 = cycle(4)
    assert not m4.used_fallback
    assert s.engine_breaker.state() == "closed"
    assert s.ladder.fully_recovered()
    assert s.totals["degraded_cycles"] >= 3
    # the transition counter saw the full open -> half-open -> closed arc
    series = dict(s.ctr_breaker._series)
    assert series[("engine", "open")] >= 1
    assert series[("engine", "half-open")] >= 1
    assert series[("engine", "closed")] >= 1


# ---- chaos scenarios: determinism ------------------------------------------


def test_chaos_scenario_deterministic_same_seed():
    a = run_scenario(SCENARIOS["compound-storm"](n_nodes=16), seed=3)
    b = run_scenario(SCENARIOS["compound-storm"](n_nodes=16), seed=3)
    for key in (
        "cycles", "pods_bound", "fallback_cycles", "fetch_failures",
        "advisor_stale_cycles", "degraded_cycles", "faults_injected",
        "mirror_verify_failures", "delta_uploads", "full_uploads",
        "breaker_transitions",
    ):
        assert a[key] == b[key], key
    assert a["recovered"] and b["recovered"]
    c = run_scenario(SCENARIOS["compound-storm"](n_nodes=16), seed=4)
    assert c["pods_bound"] != a["pods_bound"] or c["cycles"] != a["cycles"]


def test_disk_full_journal_drops_records_but_replays(tmp_path):
    from kubernetes_scheduler_tpu.trace.replay import replay_journal

    journal = str(tmp_path / "disk-full")
    s = run_scenario(
        SCENARIOS["disk-full-journal"](n_nodes=16), seed=0,
        trace_path=journal,
    )
    assert s["trace_records_dropped"] > 0  # the fault actually bit
    assert s["recovered"]
    report = replay_journal(journal)
    assert report.replayed > 0 and report.binding_diffs == 0


# ---- satellite 3: compound downgrade in ONE cycle window -------------------


class _FailingHandle:
    def result(self):
        raise RuntimeError("sidecar replaced mid-stream")


class _DowngradingEngine:
    """Capability-downgrade emulation: armed, the next dispatch fails
    like a replaced sidecar (the PR-3 class) and the engine comes back
    CAPABILITY-DOWNGRADED — supports_resident() False for the next
    `blind_calls` probes (the re-probe window) before re-learning. The
    async surface fails at FORCE time (the pipelined completion stage,
    where the in-flight window's speculative successor must flush)."""

    def __init__(self):
        self.inner = LocalEngine()
        self.arm_failure = False
        self.blind_calls = 0
        # non-resident async never engages: the resident surface below
        # is the one under test (the scheduler feature-probes getattr)
        self.schedule_batch_async = None

    def supports_resident(self):
        if self.blind_calls > 0:
            self.blind_calls -= 1
            return False
        return self.inner.supports_resident()

    def _downgrade(self):
        self.arm_failure = False
        self.blind_calls = 2
        self.inner.invalidate_resident()

    def schedule_batch(self, snapshot, pods, **kw):
        return self._dispatch(
            self.inner.schedule_batch, snapshot, pods, **kw
        )

    def schedule_resident(self, snapshot, pods, **kw):
        return self._dispatch(
            self.inner.schedule_resident, snapshot, pods, **kw
        )

    def schedule_resident_async(self, snapshot, pods, **kw):
        from kubernetes_scheduler_tpu.engine import PendingSchedule

        if self.arm_failure:
            self._downgrade()
            return _FailingHandle()
        return PendingSchedule(
            self.inner.schedule_resident(snapshot, pods, **kw)
        )

    def _dispatch(self, fn, *a, **kw):
        if self.arm_failure:
            self._downgrade()
            raise RuntimeError("sidecar replaced mid-stream")
        return fn(*a, **kw)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _compound_downgrade_run(depth: int):
    # enough nodes that a changed-rows delta beats the full snapshot
    # under the bytes rule (the padded row floor dominates tiny meshes)
    nodes = [make_node(f"n{i}") for i in range(48)]
    utils = {
        nd.name: NodeUtil(cpu_pct=10.0, disk_io=2.0) for nd in nodes
    }
    eng = _DowngradingEngine()
    clk = SimClock()
    running: list = []
    s = Scheduler(
        SchedulerConfig(
            # one-pod windows (cap = batch_window x max_windows = 1): a
            # second queued pod is a PREFETCHED successor window, so the
            # failing cycle has real speculative state to flush
            batch_window=1, max_windows_per_cycle=1,
            min_device_work=0, adaptive_dispatch=False,
            normalizer="none", resident_state=True, snapshot_mirror=True,
            mirror_verify_interval=1, pipeline_depth=depth,
            breaker_failure_threshold=2, breaker_recovery_window_s=2.0,
        ),
        advisor=StaticAdvisor(utils), engine=eng,
        list_nodes=lambda: nodes, list_running_pods=lambda: running,
        queue_clock=clk,
    )

    def cycle(i, *, extra=False):
        s.submit(make_pod(f"p{i}", cpu=50, annotations={"diskIO": "1"}))
        if extra:
            s.submit(
                make_pod(f"x{i}", cpu=50, annotations={"diskIO": "1"})
            )
        m = s.run_cycle()
        for b in s.binder.bindings[len(running):]:
            running.append(b.pod)
        clk.advance(1.0)
        return m

    warm = [cycle(i) for i in range(3)]
    assert s.totals["delta_uploads"] >= 1  # resident path engaged
    verify_before = int(s.mirror.ctr_verify_failures._series.get((), 0))
    # THE compound window: capability downgrade + engine failure AND a
    # mirror corruption land in the SAME cycle (the extra pod is the
    # successor window the pipelined driver prefetches in-flight)
    eng.arm_failure = True
    assert s.mirror.inject_corruption(leaf="net_up", row=1)
    m = cycle(3, extra=True)
    assert m.used_fallback  # engine failure -> scalar for this window
    # mirror resync in the same window: the corrupt state was detected
    # bitwise and rebuilt BEFORE it could serve a decision
    assert int(s.mirror.ctr_verify_failures._series.get((), 0)) == (
        verify_before + 1
    )
    if depth:
        assert m.pipeline_flushes >= 1  # speculative state discarded
    # all three subsystems sat degraded in the same window
    assert {"engine", "mirror", "resident"} <= set(m.degraded)
    # recovery: capability re-learned, delta path resumes, rungs climb
    deltas_before = s.totals["delta_uploads"]
    out = [cycle(i) for i in range(4, 10)]
    # drain the straggler windows (one-pod window cap): the extra
    # successor pod from the compound cycle is still queued behind them
    for _ in range(8):
        if len(s.queue) == 0 and s._prefetched is None:
            break
        out.append(s.run_cycle())
        for b in s.binder.bindings[len(running):]:
            running.append(b.pod)
        clk.advance(1.0)
    assert all(not mm.used_fallback for mm in out[1:])
    assert s.totals["delta_uploads"] > deltas_before
    assert s.ladder.fully_recovered(), s.ladder.snapshot()
    assert s.engine_breaker.state() == "closed"
    assert s.mirror.verify() is True
    return [
        (b.pod.name, b.node_name) for b in s.binder.bindings
    ], warm + [m] + out


def test_compound_downgrade_same_cycle_serial():
    binds, metrics = _compound_downgrade_run(depth=0)
    assert len(binds) == 11  # 10 per-cycle pods + the extra successor


def test_compound_downgrade_same_cycle_pipelined():
    binds_p, _ = _compound_downgrade_run(depth=1)
    binds_s, _ = _compound_downgrade_run(depth=0)
    # serial/pipelined parity holds THROUGH the compound failure window
    assert binds_p == binds_s and len(binds_p) == 11


def test_compound_downgrade_live_sidecar(tmp_path):
    """The live-bridge variant (slow): a REAL capability downgrade —
    the sidecar stops advertising field_cache/resident_state mid-stream
    — composed with a mirror corruption resync and the pipelined
    driver's flush, then full recovery once the sidecar upgrades
    back."""
    pytest.importorskip("grpc")
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server
    from kubernetes_scheduler_tpu.sim.host_gen import (
        gen_host_cluster,
        gen_host_pods,
    )

    server, port, service = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    nodes, advisor = gen_host_cluster(48, seed=0)
    running: list = []
    s = Scheduler(
        SchedulerConfig(
            batch_window=32, max_windows_per_cycle=1,
            min_device_work=0, adaptive_dispatch=False,
            normalizer="none", resident_state=True, snapshot_mirror=True,
            mirror_verify_interval=1, pipeline_depth=1,
        ),
        advisor=advisor, engine=client,
        list_nodes=lambda: nodes, list_running_pods=lambda: running,
    )

    def drain(n_pods, seed):
        for pod in gen_host_pods(n_pods, seed=seed):
            s.submit(pod)
        out = []
        seen = len(s.binder.bindings)
        for _ in range(32):
            if len(s.queue) == 0 and s._prefetched is None:
                break
            out.append(s.run_cycle())
            for b in s.binder.bindings[seen:]:
                running.append(b.pod)
            seen = len(s.binder.bindings)
        return out

    try:
        m1 = drain(64, seed=1)
        assert s.totals["delta_uploads"] >= 1
        assert client._resident_cap is True
        # the compound window: capability downgrade + mirror corruption
        service.field_cache_enabled = False
        service.resident_enabled = False
        assert s.mirror.inject_corruption(leaf="net_up", row=2)
        m2 = drain(64, seed=2)
        # the client re-learned the downgrade (no livelock on rejected
        # deltas), the mirror resynced, and every pod still bound
        assert client._resident_cap is False
        assert int(s.mirror.ctr_verify_failures._series.get((), 0)) >= 1
        assert sum(m.pods_bound for m in m1 + m2) == 128
        # the sidecar upgrades back: capabilities re-learned upward
        service.field_cache_enabled = True
        service.resident_enabled = True
        client._invalidate_session()
        m3 = drain(32, seed=3)
        assert sum(m.pods_bound for m in m3) == 32
        assert client._resident_cap is True
        assert s.mirror.verify() is True
    finally:
        client.close()
        server.stop(grace=None)
