"""Host layer: advisor join semantics, queue, snapshot builder, full loop."""

import numpy as np
import pytest

from kubernetes_scheduler_tpu.host import (
    Card,
    Container,
    Node,
    NodeUtil,
    Pod,
    PrometheusAdvisor,
    Scheduler,
    SchedulingQueue,
    SnapshotBuilder,
    StaticAdvisor,
    Taint,
)
from kubernetes_scheduler_tpu.host.types import (
    MatchExpression,
    PodAffinityTerm,
    Toleration,
    parse_cpu_milli,
    parse_quantity,
)
from kubernetes_scheduler_tpu.ops.resources import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
)
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig


def make_node(name, cpu=8000, mem=32 * 2**30, **kw):
    return Node(
        name=name,
        allocatable={"cpu": cpu, "memory": mem, "pods": 110},
        **kw,
    )


def make_pod(name, cpu=500, mem=2**30, **kw):
    return Pod(
        name=name,
        containers=[Container(requests={"cpu": cpu, "memory": mem})],
        **kw,
    )


def test_quantity_parsing():
    assert parse_quantity("2Gi") == 2 * 2**30
    assert parse_quantity("500m") == 0.5
    assert parse_quantity("1.5") == 1.5
    assert parse_cpu_milli("500m") == 500
    assert parse_cpu_milli(2) == 2000


def test_advisor_join_and_soft_failures():
    """Join by kubernetes_io_hostname with instance fallback
    (advisor.go:199-202); net series errors degrade to zeros
    (advisor.go:219,242); cpu series errors propagate."""

    def transport(url, form):
        q = form["query"]
        if "container_cpu" in q:
            return {
                "data": {
                    "result": [
                        {"metric": {"kubernetes_io_hostname": "n1"}, "value": [0, "55.5"]},
                        {"metric": {"instance": "n2"}, "value": [0, "10"]},
                        {"metric": {}, "value": [0, "99"]},  # unjoinable: skipped
                    ]
                }
            }
        if "node_disk" in q:
            return {
                "data": {"result": [
                    {"metric": {"kubernetes_io_hostname": "n1"}, "value": [0, "12.5"]},
                ]}
            }
        if "transmit" in q or "receive" in q:
            raise OSError("network io query failed")
        return {"data": {"result": []}}

    adv = PrometheusAdvisor("example:9090", transport=transport)
    utils = adv.fetch()
    assert utils["n1"].cpu_pct == 55.5
    assert utils["n1"].disk_io == 12.5
    assert utils["n1"].net_up == 0.0  # soft-failed
    assert utils["n2"].cpu_pct == 10.0  # instance fallback

    def hard_fail(url, form):
        raise OSError("prometheus down")

    with pytest.raises(OSError):
        PrometheusAdvisor("example:9090", transport=hard_fail).fetch()


def test_queue_priority_and_backoff():
    now = [0.0]
    q = SchedulingQueue(clock=lambda: now[0])
    q.push(make_pod("low", ))
    q.push(make_pod("high", labels={"scv/priority": "9"}))
    q.push(make_pod("mid", labels={"scv/priority": "5"}))
    assert [p.name for p in q.pop_window(10)] == ["high", "mid", "low"]

    p = make_pod("retry")
    q.requeue_unschedulable(p)
    assert q.pop_window(10) == []          # still backing off (1s)
    now[0] = 1.1
    assert [x.name for x in q.pop_window(10)] == ["retry"]
    # second failure: 2s backoff
    q.requeue_unschedulable(p)
    now[0] = 2.0
    assert q.pop_window(10) == []
    now[0] = 3.2
    assert [x.name for x in q.pop_window(10)] == ["retry"]
    # backoff is capped at max_backoff
    for _ in range(10):
        q.requeue_unschedulable(p)
        assert q._backoff[0][0] - now[0] <= 10.0 + 1e-9
        q._backoff.clear()


def test_snapshot_builder_resource_math():
    b = SnapshotBuilder()
    nodes = [make_node("n1"), make_node("n2", cpu=4000)]
    running = [make_pod("r1", cpu=1000, mem=2**30)]
    running[0].node_name = "n1"
    # a pod with no requests gets the non-zero defaults
    empty = Pod(name="empty", containers=[Container()])
    snap = b.build_snapshot(nodes, {"n1": NodeUtil(cpu_pct=50)}, running)
    batch = b.build_pod_batch([make_pod("p1", cpu=250), empty])

    assert snap.allocatable.shape[0] == 8  # bucketed
    assert float(snap.allocatable[0, 0]) == 8000
    assert float(snap.requested[0, 0]) == 1000
    assert float(snap.requested[0, 2]) == 1  # pod count
    assert float(snap.cpu_pct[0]) == 50
    assert float(batch.request[0, 0]) == 250
    assert float(batch.request[1, 0]) == DEFAULT_MILLI_CPU_REQUEST
    assert float(batch.request[1, 1]) == DEFAULT_MEMORY_REQUEST


def test_snapshot_builder_gpu_and_scv_labels():
    b = SnapshotBuilder()
    node = make_node("g1")
    node.cards = [Card(clock=1500, free_memory=16000), Card(clock=2000, free_memory=8000, health="Unhealthy")]
    snap = b.build_snapshot([node], {}, [])
    assert snap.cards.shape[1] == 2
    assert bool(snap.card_healthy[0, 0]) and not bool(snap.card_healthy[0, 1])

    pods = [
        make_pod("nogpu"),
        make_pod("implicit", labels={"scv/memory": "8000"}),     # wants 1 card
        make_pod("explicit", labels={"scv/number": "2", "scv/clock": "1500"}),
        make_pod("garbage", labels={"scv/number": "xyz"}),       # strconv -> 0
    ]
    batch = b.build_pod_batch(pods)
    assert batch.want_number.tolist()[:4] == [0, 1, 2, 0]
    assert float(batch.want_memory[1]) == 8000
    assert float(batch.want_memory[2]) == -1  # label absent
    assert float(batch.want_clock[2]) == 1500


def test_node_affinity_or_terms_end_to_end():
    """Upstream OR-of-ANDs through the full host pipeline: a pod whose
    FIRST term fails everywhere but whose second term matches one node
    must schedule there (the round-3 conversion truncated to terms[0],
    over-constraining exactly this pod)."""
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.host.types import MatchExpression

    b = SnapshotBuilder()
    nodes = [
        make_node("ssd", labels={"disk": "ssd"}),
        make_node("hdd", labels={"disk": "hdd"}),
    ]
    two_terms = Pod(
        name="or-pod",
        containers=[Container()],
        node_affinity=[
            MatchExpression(key="disk", operator="In", values=["nvme"], term=0),
            MatchExpression(key="disk", operator="In", values=["hdd"], term=1),
        ],
    )
    one_term = Pod(
        name="and-pod",
        containers=[Container()],
        node_affinity=[
            MatchExpression(key="disk", operator="In", values=["nvme"], term=0),
        ],
    )
    snap = b.build_snapshot(nodes, {}, [])
    batch = b.build_pod_batch([two_terms, one_term])
    res = schedule_batch(snap, batch)
    feas = np.asarray(res.feasible)
    assert feas[0, :2].tolist() == [False, True]
    assert int(res.node_idx[0]) == 1
    assert not feas[1, :2].any() and int(res.node_idx[1]) == -1


def test_match_fields_metadata_name_affinity():
    """matchFields (metadata.name selectors) schedule via the synthetic
    per-node `metadata.name` label: NotIn excludes a node by name, In
    pins to it — through the ordinary expression kernel."""
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.host.types import MatchExpression

    b = SnapshotBuilder()
    nodes = [make_node("alpha"), make_node("beta")]
    pin = Pod(
        name="pin", containers=[Container()],
        node_affinity=[
            MatchExpression(key="metadata.name", operator="In", values=["beta"])
        ],
    )
    avoid = Pod(
        name="avoid", containers=[Container()],
        node_affinity=[
            MatchExpression(key="metadata.name", operator="NotIn", values=["beta"])
        ],
    )
    snap = b.build_snapshot(nodes, {}, [])
    batch = b.build_pod_batch([pin, avoid])
    res = schedule_batch(snap, batch)
    feas = np.asarray(res.feasible)
    assert feas[0, :2].tolist() == [False, True]
    assert feas[1, :2].tolist() == [True, False]
    assert int(res.node_idx[0]) == 1 and int(res.node_idx[1]) == 0


def test_spread_selector_match_expressions():
    """Spread selectors with matchExpressions count running pods via full
    label-selector semantics (round-3 conversion silently dropped them)."""
    from kubernetes_scheduler_tpu.host.types import MatchExpression, SpreadConstraint

    b = SnapshotBuilder()
    nodes = [make_node("n1"), make_node("n2")]
    tiers = []
    for name, node, tier in [("a", "n1", "web"), ("b", "n1", "db"), ("c", "n2", "web")]:
        pd = make_pod(name, labels={"tier": tier})
        pd.node_name = node
        tiers.append(pd)
    pending = [
        Pod(
            name="spread-expr",
            containers=[Container()],
            topology_spread=[
                SpreadConstraint(
                    match_labels={},
                    match_expressions=[
                        MatchExpression(key="tier", operator="In", values=["web"])
                    ],
                    max_skew=1,
                )
            ],
        )
    ]
    snap = b.build_snapshot(nodes, {}, tiers, pending_pods=pending)
    batch = b.build_pod_batch(pending)
    sid = int(batch.spread_sel[0, 0])
    assert sid >= 0
    counts = np.asarray(snap.domain_counts)
    # hostname domains: n1 has one web pod, n2 has one web pod (db ignored)
    assert counts[0, sid] == 1.0 and counts[1, sid] == 1.0


def test_soft_spread_schedule_anyway_steers_not_filters():
    """ScheduleAnyway spread: the engine prefers the least-loaded domain
    but never filters — even when every domain violates maxSkew."""
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.host.types import SpreadConstraint

    b = SnapshotBuilder()
    nodes = [make_node("busy"), make_node("idle")]
    web_pods = []
    for i in range(3):
        pd = make_pod(f"web{i}", labels={"app": "web"})
        pd.node_name = "busy"
        web_pods.append(pd)
    pending = [
        Pod(
            name="soft-spread",
            containers=[Container()],
            labels={"app": "web"},
            topology_spread=[
                SpreadConstraint(match_labels={"app": "web"}, soft=True)
            ],
        )
    ]
    snap = b.build_snapshot(nodes, {}, web_pods, pending_pods=pending)
    batch = b.build_pod_batch(pending)
    assert int(batch.soft_spread_sel[0, 0]) >= 0
    assert int(batch.spread_sel[0, 0]) == -1  # not a hard constraint
    res = schedule_batch(snap, batch, soft=True)
    # both nodes stay feasible (soft, never filters); the empty domain wins
    assert bool(np.asarray(res.feasible)[0, :2].all())
    assert int(res.node_idx[0]) == 1

    # with every node in one crowded domain, the pod still schedules
    crowded = [make_pod(f"w{i}", labels={"app": "web"}) for i in range(2)]
    for pd in crowded:
        pd.node_name = "busy"
    one = [make_node("busy")]
    b2 = SnapshotBuilder()
    snap2 = b2.build_snapshot(one, {}, crowded, pending_pods=pending)
    batch2 = b2.build_pod_batch(pending)
    res2 = schedule_batch(snap2, batch2, soft=True)
    assert int(res2.node_idx[0]) == 0


def test_soft_spread_through_scheduler_loop():
    """The full host loop must turn a ScheduleAnyway constraint into a
    soft score term (the cycle's soft gate has to see soft spread — a
    window with ONLY a soft spread constraint still needs soft=True)."""
    from kubernetes_scheduler_tpu.host.types import SpreadConstraint

    nodes = [make_node("busy", cpu=8000), make_node("idle", cpu=8000)]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    crowd = make_pod("w0", cpu=100, labels={"app": "web"})
    crowd.node_name = "busy"
    spreader = Pod(
        name="spreader",
        containers=[Container(requests={"cpu": 100.0})],
        labels={"app": "web"},
        topology_spread=[
            SpreadConstraint(match_labels={"app": "web"}, soft=True)
        ],
    )
    s = make_sched(nodes, [crowd], utils)
    s.submit(spreader)
    m = s.run_cycle()
    assert m.pods_bound == 1 and not m.used_fallback
    assert s.binder.bindings[0].node_name == "idle"


def test_running_required_attract_term_does_not_crash_snapshot():
    """A RUNNING pod's required (non-anti, non-preferred) affinity term
    is not a selector the engine consumes — it must not mint a fresh
    selector id mid-count (review finding r4: post-sizing interning
    crashed build_snapshot with an IndexError when the running pod's
    term key differed from every pending pod's, e.g. by namespace)."""
    from kubernetes_scheduler_tpu.host.types import PodAffinityTerm

    b = SnapshotBuilder()
    nodes = [make_node("n0")]
    runner = make_pod("runner", labels={"app": "web"})
    runner.namespace = "other"
    runner.node_name = "n0"
    runner.pod_affinity = [
        PodAffinityTerm(match_labels={"app": "cache"}, namespaces=["other"])
    ]
    pending = [
        Pod(
            name="p",
            containers=[Container()],
            pod_affinity=[
                PodAffinityTerm(match_labels={"app": "web"}, anti=True,
                                namespaces=["default"])
            ],
        )
    ]
    snap = b.build_snapshot(nodes, {}, [runner], pending_pods=pending)
    assert np.asarray(snap.domain_counts).shape[0] >= 1
    # the running pod's required attract term registered no selector
    assert len(b.selectors) == 1


def test_pod_affinity_namespace_scoping():
    """Upstream inter-pod selectors match only the scoped namespaces: a
    running matcher in ANOTHER namespace must not trip an anti-affinity
    term scoped to the pod's own namespace, while an explicit
    cross-namespace list does see it."""
    from kubernetes_scheduler_tpu.engine import schedule_batch
    from kubernetes_scheduler_tpu.host.types import PodAffinityTerm

    nodes = [make_node("n0"), make_node("n1")]
    alien = make_pod("alien", labels={"app": "web"})
    alien.namespace = "other"
    alien.node_name = "n0"

    def pending(namespaces):
        return Pod(
            name="avoider",
            namespace="default",
            containers=[Container()],
            pod_affinity=[
                PodAffinityTerm(
                    match_labels={"app": "web"}, anti=True,
                    namespaces=namespaces,
                )
            ],
        )

    # scoped to own namespace: the other-namespace matcher is invisible
    b = SnapshotBuilder()
    own = pending(["default"])
    snap = b.build_snapshot(nodes, {}, [alien], pending_pods=[own])
    res = schedule_batch(snap, b.build_pod_batch([own]))
    assert int(res.node_idx[0]) >= 0  # schedulable anywhere

    # explicit cross-namespace scope: n0's domain is forbidden
    b2 = SnapshotBuilder()
    wide = pending(["default", "other"])
    snap2 = b2.build_snapshot(nodes, {}, [alien], pending_pods=[wide])
    res2 = schedule_batch(snap2, b2.build_pod_batch([wide]))
    assert int(res2.node_idx[0]) == 1, "n0 holds the cross-ns matcher"

    # None = all namespaces (host-API convenience): also forbidden
    b3 = SnapshotBuilder()
    allns = pending(None)
    snap3 = b3.build_snapshot(nodes, {}, [alien], pending_pods=[allns])
    res3 = schedule_batch(snap3, b3.build_pod_batch([allns]))
    assert int(res3.node_idx[0]) == 1


def test_domain_counts_topology_aggregation():
    b = SnapshotBuilder()
    nodes = [
        make_node("a1", labels={"zone": "za"}),
        make_node("a2", labels={"zone": "za"}),
        make_node("b1", labels={"zone": "zb"}),
    ]
    web = make_pod("web", labels={"app": "web"})
    web.node_name = "a1"
    pending = [
        Pod(
            name="wants-web-zone",
            containers=[Container()],
            pod_affinity=[PodAffinityTerm({"app": "web"}, topology_key="zone")],
        ),
        Pod(
            name="avoids-web-host",
            containers=[Container()],
            pod_affinity=[PodAffinityTerm({"app": "web"}, anti=True)],
        ),
    ]
    snap = b.build_snapshot(nodes, {}, [web], pending_pods=pending)
    batch = b.build_pod_batch(pending)
    counts = np.asarray(snap.domain_counts)
    # zone selector: both za nodes see the count, zb none
    zone_sid = int(batch.affinity_sel[0, 0])
    assert counts[:3, zone_sid].tolist() == [1.0, 1.0, 0.0]
    # hostname selector: only a1
    host_sid = int(batch.anti_affinity_sel[1, 0])
    assert counts[:3, host_sid].tolist() == [1.0, 0.0, 0.0]


def make_sched(nodes, running, utils, *, engine_override=None, **cfg):
    # min_device_work=0: tests drive the batched path on tiny clusters that
    # adaptive dispatch would otherwise (correctly) route to the scalar path
    cfg.setdefault("min_device_work", 0)
    cfg.setdefault("batch_window", 64)
    config = SchedulerConfig(**cfg)
    return Scheduler(
        config,
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        engine=engine_override,
    )


def test_scheduler_end_to_end_batched_vs_scalar():
    """Full loop: batched and scalar paths bind every pod and agree on
    placements for untruncated scores... the scalar path reproduces the
    reference's uint64 truncation, so compare binding feasibility, not
    exact node choice."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(6)]
    utils = {
        f"n{i}": NodeUtil(cpu_pct=10 * i, mem_pct=30, disk_io=5 * i)
        for i in range(6)
    }
    pods = [
        make_pod(f"p{i}", cpu=500, annotations={"diskIO": "10"},
                 labels={"scv/priority": str(i % 3)})
        for i in range(10)
    ]

    s_batch = make_sched(nodes, [], utils)
    for p in pods:
        s_batch.submit(p)
    m = s_batch.run_cycle()
    assert m.pods_in == 10 and m.pods_bound == 10 and not m.used_fallback

    pods2 = [
        make_pod(f"q{i}", cpu=500, annotations={"diskIO": "10"},
                 labels={"scv/priority": str(i % 3)})
        for i in range(10)
    ]
    config = SchedulerConfig.from_dict(
        {"batch_window": 64, "feature_gates": {"tpu_batch_score": False}}
    )
    s_scalar = Scheduler(
        config,
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    for p in pods2:
        s_scalar.submit(p)
    m2 = s_scalar.run_cycle()
    assert m2.pods_bound == 10 and m2.used_fallback


def test_scheduler_unschedulable_requeues_with_backoff():
    nodes = [make_node("tiny", cpu=100, mem=2**20)]
    s = make_sched(nodes, [], {"tiny": NodeUtil()})
    s.submit(make_pod("huge", cpu=99999, mem=2**40))
    m = s.run_cycle()
    assert m.pods_unschedulable == 1 and m.pods_bound == 0
    assert len(s.queue) == 1  # waiting in backoff
    assert s.queue.pop_window(10) == []  # not ready yet


def test_scheduler_constraints_respected_in_loop():
    nodes = [
        make_node("tainted", taints=[Taint(key="gpu", value="yes")]),
        make_node("plain", labels={"disk": "ssd"}),
    ]
    utils = {n.name: NodeUtil(cpu_pct=50, disk_io=10) for n in nodes}
    tolerant = make_pod("tolerant", annotations={"diskIO": "5"})
    tolerant.tolerations = [Toleration(key="gpu", operator="Exists")]
    tolerant.node_affinity = [MatchExpression("disk", "NotIn", ["ssd"])]
    picky = make_pod("picky", annotations={"diskIO": "5"})
    picky.node_affinity = [MatchExpression("disk", "In", ["ssd"])]

    s = make_sched(nodes, [], utils)
    s.submit(tolerant)
    s.submit(picky)
    s.run_cycle()
    bound = {b.pod.name: b.node_name for b in s.binder.bindings}
    assert bound == {"tolerant": "tainted", "picky": "plain"}


def test_adaptive_dispatch_tiny_cycle_uses_scalar():
    """Below min_device_work a constraint-free cycle runs the scalar host
    path (device dispatch latency dominates tiny problems); pods with
    constraint families the scalar path lacks stay on the device."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(3)]
    utils = {f"n{i}": NodeUtil(cpu_pct=10, disk_io=5) for i in range(3)}
    s = make_sched(nodes, [], utils, min_device_work=1 << 20)
    s.submit(make_pod("p0", cpu=100, annotations={"diskIO": "5"}))
    m = s.run_cycle()
    assert m.pods_bound == 1 and m.used_fallback  # scalar dispatch

    from kubernetes_scheduler_tpu.host.types import PodAffinityTerm

    s2 = make_sched(nodes, [], utils, min_device_work=1 << 20)
    pod = make_pod("p1", cpu=100)
    pod.pod_affinity = [
        PodAffinityTerm(match_labels={"app": "x"}, topology_key="zone", anti=True)
    ]
    s2.submit(pod)
    m2 = s2.run_cycle()
    assert m2.pods_bound == 1 and not m2.used_fallback  # device dispatch


def test_backlog_cycle_schedules_all_windows_in_one_dispatch():
    """A deep queue pops max_windows_per_cycle windows and schedules them
    through ONE engine.schedule_windows dispatch; placements must be
    feasible and capacity-consistent, and every pod handled."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(6)]
    utils = {
        f"n{i}": NodeUtil(cpu_pct=10 * i, disk_io=5) for i in range(6)
    }
    calls = []

    class CountingEngine:
        def __init__(self):
            from kubernetes_scheduler_tpu.engine import LocalEngine

            self._inner = LocalEngine()

        def schedule_batch(self, *a, **kw):
            calls.append("batch")
            return self._inner.schedule_batch(*a, **kw)

        def schedule_windows(self, *a, **kw):
            calls.append("windows")
            return self._inner.schedule_windows(*a, **kw)

        def healthy(self):
            return True

    s = make_sched(nodes, [], utils, batch_window=8, engine_override=CountingEngine())
    for i in range(30):
        s.submit(make_pod(f"p{i}", cpu=500, annotations={"diskIO": "5"}))
    m = s.run_cycle()
    assert m.pods_in == 30 and m.pods_bound == 30
    assert calls == ["windows"]  # one dispatch for the whole backlog
    # capacity consistent: per-node sum of bound requests <= allocatable
    used = {}
    for b in s.binder.bindings:
        used[b.node_name] = used.get(b.node_name, 0) + 500
    assert all(v <= 8000 for v in used.values())

    # max_windows_per_cycle=1 restores the one-window-per-cycle shape
    s2 = make_sched(nodes, [], utils, batch_window=8, max_windows_per_cycle=1)
    for i in range(30):
        s2.submit(make_pod(f"q{i}", cpu=500, annotations={"diskIO": "5"}))
    ms = s2.run_until_empty()
    assert len(ms) == 4  # 8+8+8+6
    assert sum(c.pods_bound for c in ms) == 30


def test_backlog_degrades_to_per_window_on_unimplemented():
    """A version-skewed engine whose windows surface answers
    NotImplementedError must degrade to per-window schedule_batch
    dispatches (same decisions), NEVER to the scalar fallback, and stop
    popping deep windows afterwards."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(4)]
    utils = {f"n{i}": NodeUtil(cpu_pct=10, disk_io=5) for i in range(4)}
    calls = []

    class SkewedEngine:
        def __init__(self):
            from kubernetes_scheduler_tpu.engine import LocalEngine

            self._inner = LocalEngine()

        def schedule_batch(self, *a, **kw):
            calls.append("batch")
            return self._inner.schedule_batch(*a, **kw)

        def schedule_windows(self, *a, **kw):
            calls.append("windows")
            raise NotImplementedError("old sidecar")

        def healthy(self):
            return True

    s = make_sched(nodes, [], utils, batch_window=8,
                   engine_override=SkewedEngine())
    for i in range(20):
        s.submit(make_pod(f"p{i}", cpu=100, annotations={"diskIO": "5"}))
    m = s.run_cycle()
    assert m.pods_bound == 20 and not m.used_fallback
    assert calls == ["windows", "batch", "batch", "batch"]  # 8+8+4 chunks
    assert not s._engine_windows_ok
    # subsequent cycles pop only one window
    for i in range(20):
        s.submit(make_pod(f"q{i}", cpu=100, annotations={"diskIO": "5"}))
    m2 = s.run_cycle()
    assert m2.pods_in == 8


def test_backlog_degradation_carries_capacity_between_chunks():
    """The per-window degradation loop must see earlier chunks' binds:
    scheduling each chunk against the cycle-start running list would
    over-commit full nodes up to max_windows_per_cycle-fold."""
    nodes = [make_node(f"n{i}", cpu=1000) for i in range(2)]
    utils = {f"n{i}": NodeUtil(cpu_pct=10, disk_io=5) for i in range(2)}

    class SkewedEngine:
        def __init__(self):
            from kubernetes_scheduler_tpu.engine import LocalEngine

            self._inner = LocalEngine()

        def schedule_batch(self, *a, **kw):
            return self._inner.schedule_batch(*a, **kw)

        def schedule_windows(self, *a, **kw):
            raise NotImplementedError("old sidecar")

        def healthy(self):
            return True

    s = make_sched(nodes, [], utils, batch_window=2,
                   engine_override=SkewedEngine())
    for i in range(6):
        s.submit(make_pod(f"p{i}", cpu=900, annotations={"diskIO": "5"}))
    m = s.run_cycle()
    # two nodes of 1000 fit exactly one 900-cpu pod each — ever
    assert m.pods_bound == 2, m
    assert m.pods_unschedulable == 4
    used = {}
    for b in s.binder.bindings:
        used[b.node_name] = used.get(b.node_name, 0) + 900
    assert all(v <= 1000 for v in used.values())


def test_failed_device_cycle_feeds_adaptive_model():
    """A device-path failure must still produce a device observation
    (including the failure's cost): otherwise the learned model never
    sees the degraded path and keeps routing cycles into it forever."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(3)]
    utils = {f"n{i}": NodeUtil(cpu_pct=10, disk_io=5) for i in range(3)}
    s = make_sched(nodes, [], utils, adaptive_dispatch=True)

    def boom(*a, **k):
        raise RuntimeError("device path down")

    s._run_batched = boom
    # burn the one jit-compile warmup observation the model discards
    s._dispatch.observe(True, 10, 0.5)
    before = s._dispatch.device.n_obs
    s.submit(make_pod("p0", cpu=100, annotations={"diskIO": "5"}))
    m = s.run_cycle()
    assert m.pods_bound == 1 and m.used_fallback
    assert s._dispatch.device.n_obs == before + 1


def test_fallback_honors_free_capacity_policy():
    """An engine failure under policy=free_capacity must degrade to the
    SAME policy (round-3 verdict: the fallback always scored with the
    yoda formula). free_capacity prefers the least-utilized node; the
    yoda formula with these inputs prefers a balanced one — the binding
    tells us which formula ran."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(3)]
    # n2 is clearly least utilized -> free_capacity picks n2.
    utils = {
        "n0": NodeUtil(cpu_pct=20, mem_pct=80, disk_io=10),
        "n1": NodeUtil(cpu_pct=50, mem_pct=50, disk_io=20),
        "n2": NodeUtil(cpu_pct=5, mem_pct=5, disk_io=0),
    }
    ref = make_sched(nodes, [], utils, policy="free_capacity")
    ref.submit(make_pod("probe", cpu=100, annotations={"diskIO": "10"}))
    m0 = ref.run_cycle()
    assert m0.pods_bound == 1 and not m0.used_fallback
    want = ref.binder.bindings[0].node_name

    s = make_sched(nodes, [], utils, policy="free_capacity")

    def boom(*a, **k):
        raise RuntimeError("device path down")

    s._run_batched = boom
    s.submit(make_pod("p0", cpu=100, annotations={"diskIO": "10"}))
    m = s.run_cycle()
    assert m.pods_bound == 1 and m.used_fallback
    assert not m.policy_mismatch
    assert s.totals["fallback_policy_mismatch"] == 0
    bound = {b.pod.name: b.node_name for b in s.binder.bindings}
    assert bound["p0"] == want == "n2", (bound, want)


def test_fallback_honors_card_policy():
    """policy=card fallback: GPU predicates filter and the card formula
    scores — matching the engine path's decision."""
    from kubernetes_scheduler_tpu.host.types import Card

    weak = make_node("weak", cpu=8000)
    weak.cards = [Card(clock=1000, free_memory=4000, core=100)]
    strong = make_node("strong", cpu=8000)
    strong.cards = [
        Card(clock=1000, free_memory=16000, core=500),
        Card(clock=1000, free_memory=16000, core=500),
    ]
    none = make_node("none", cpu=8000)
    nodes = [weak, strong, none]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    gpu_pod = lambda name: make_pod(  # noqa: E731
        name, cpu=100, labels={"scv/number": "2", "scv/memory": "8000"}
    )
    ref = make_sched(nodes, [], utils, policy="card")
    ref.submit(gpu_pod("probe"))
    m0 = ref.run_cycle()
    assert m0.pods_bound == 1 and not m0.used_fallback
    want = ref.binder.bindings[0].node_name
    assert want == "strong"

    s = make_sched(nodes, [], utils, policy="card")

    def boom(*a, **k):
        raise RuntimeError("device path down")

    s._run_batched = boom
    s.submit(gpu_pod("p0"))
    m = s.run_cycle()
    assert m.pods_bound == 1 and m.used_fallback and not m.policy_mismatch
    bound = {b.pod.name: b.node_name for b in s.binder.bindings}
    assert bound["p0"] == "strong", bound


def test_fallback_honors_balanced_diskio_policy():
    """An engine failure under policy=balanced_diskio must degrade to the
    SAME variance-minimization formula (round-4 verdict: this was the one
    heuristic policy without a scalar mirror). The winning node under
    balanced_diskio differs from the yoda formula's pick on these inputs,
    so the binding tells us which formula ran."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(4)]
    utils = {
        "n0": NodeUtil(cpu_pct=10, disk_io=40),
        "n1": NodeUtil(cpu_pct=90, disk_io=5),
        "n2": NodeUtil(cpu_pct=45, disk_io=22),
        "n3": NodeUtil(cpu_pct=30, disk_io=31),
    }
    pod = lambda name: make_pod(  # noqa: E731
        name, cpu=100, annotations={"diskIO": "12"}
    )
    ref = make_sched(nodes, [], utils, policy="balanced_diskio")
    ref.submit(pod("probe"))
    m0 = ref.run_cycle()
    assert m0.pods_bound == 1 and not m0.used_fallback
    want = ref.binder.bindings[0].node_name

    s = make_sched(nodes, [], utils, policy="balanced_diskio")

    def boom(*a, **k):
        raise RuntimeError("device path down")

    s._run_batched = boom
    s.submit(pod("p0"))
    m = s.run_cycle()
    assert m.pods_bound == 1 and m.used_fallback
    assert not m.policy_mismatch
    assert s.totals["fallback_policy_mismatch"] == 0
    bound = {b.pod.name: b.node_name for b in s.binder.bindings}
    assert bound["p0"] == want, (bound, want)


def test_scalar_balanced_diskio_matches_oracle():
    """The scalar mirror reproduces the independent loop-by-loop oracle
    (algorithm.go:121-176) node for node, sentinel seeds included."""
    from kubernetes_scheduler_tpu.host.plugins import CycleState, ScalarYodaPlugin
    from tests.oracle import balanced_diskio_oracle

    disk_io = [40.0, 5.0, 22.0, 31.0]
    cpu_pct = [10.0, 90.0, 45.0, 30.0]
    nodes = [make_node(f"n{i}") for i in range(4)]
    utils = {
        f"n{i}": NodeUtil(cpu_pct=cpu_pct[i], disk_io=disk_io[i])
        for i in range(4)
    }
    plugin = ScalarYodaPlugin(utils, policy="balanced_diskio")
    pod = make_pod("p", cpu=100, annotations={"diskIO": "12"})
    state = CycleState()
    plugin.pre_score(state, pod, nodes)
    got = [plugin.score(state, pod, n, all_nodes=nodes) for n in nodes]
    # all Mj lie in (0, 1e6) on these inputs, so the engine's sentinel
    # seeds (m_max >= 0, m_min <= 1e6) don't bind and the oracle's plain
    # min-max rescale is the exact expected value
    import math

    want = balanced_diskio_oracle(disk_io, cpu_pct, 12.0)
    assert all(math.isclose(g, w, rel_tol=1e-9) for g, w in zip(got, want)), (
        got,
        want,
    )


def test_fallback_policy_mismatch_counter():
    """A policy with no scalar mirror (learned — its scores live in device
    parameters) still binds under fallback but flags the mismatch in
    metrics. With all four heuristic policies mirrored, learned is the
    only mismatch case left."""
    from kubernetes_scheduler_tpu.host.observe import render_prometheus

    nodes = [make_node(f"n{i}", cpu=8000) for i in range(2)]
    utils = {f"n{i}": NodeUtil(cpu_pct=10, disk_io=5) for i in range(2)}
    s = make_sched(nodes, [], utils, policy="learned")

    def boom(*a, **k):
        raise RuntimeError("device path down")

    s._run_batched = boom
    s.submit(make_pod("p0", cpu=100, annotations={"diskIO": "5"}))
    m = s.run_cycle()
    assert m.pods_bound == 1 and m.used_fallback and m.policy_mismatch
    assert s.totals["fallback_policy_mismatch"] == 1
    text = render_prometheus(*s.metrics_snapshot())
    assert "fallback_policy_mismatch_total 1" in text


def test_running_avoider_forces_engine_path_and_blocks_domain():
    """Adaptive dispatch must consider RUNNING pods: a running pod with a
    required anti-affinity term (an avoider) forbids matching pending pods
    from its domain — engine-only reverse InterPodAffinity. The scalar
    path would silently drop it, so the cycle must route to the engine
    even below min_device_work, and the avoider's node must be refused."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(3)]
    # make the avoider's node n0 the score-optimal target so the test
    # fails loud (pod lands on n0) if the engine path is skipped
    utils = {
        "n0": NodeUtil(cpu_pct=10, disk_io=2),
        "n1": NodeUtil(cpu_pct=80, disk_io=40),
        "n2": NodeUtil(cpu_pct=85, disk_io=45),
    }
    guard = make_pod("guard", cpu=100, node_name="n0")
    guard.pod_affinity = [
        PodAffinityTerm(match_labels={"app": "web"}, anti=True)
    ]
    s = make_sched(nodes, [guard], utils, min_device_work=1 << 20)
    s.submit(make_pod("web-0", cpu=100, labels={"app": "web"},
                      annotations={"diskIO": "5"}))
    m = s.run_cycle()
    assert not m.used_fallback  # running avoider forced the engine path
    bound = {b.pod.name: b.node_name for b in s.binder.bindings}
    assert bound["web-0"] != "n0", bound


# ---- round-5 host fast path: byte-packed records, window flags, queue memo


def test_pod_batch_record_bytes_slots_match_scalar_slots():
    """The byte-packed slots (6: request-row f32 bytes, 7: scalar block)
    must decode to exactly the tuple slots the scalar paths read — the
    builders assemble window matrices from the bytes, the scalar
    fallback from the tuples, and they must never diverge."""
    import numpy as np

    from kubernetes_scheduler_tpu.host.snapshot import (
        _SCAL_DT,
        pod_batch_record,
    )

    pod = make_pod("p", cpu=250, annotations={"diskIO": "7"},
                   labels={"scv/priority": "3"})
    names = ("cpu", "memory", "pods")
    rec = pod_batch_record(pod, names)
    row = np.frombuffer(rec[6], np.float32)
    assert row.tolist() == [float(x) for x in rec[1]]
    scal = np.frombuffer(rec[7], _SCAL_DT)[0]
    assert float(scal["rio"]) == rec[2] == 7.0
    assert int(scal["pri"]) == rec[3] == 3
    assert int(scal["nc"]) == rec[4] == 1
    assert int(scal["fl"]) == rec[5]


def test_pod_batch_record_names_change_recomputes_row_and_bytes():
    """A column-layout change must refresh the request row AND its bytes
    form while keeping the layout-independent scalar block."""
    import numpy as np

    from kubernetes_scheduler_tpu.host.snapshot import pod_batch_record

    pod = make_pod("p", cpu=250, annotations={"diskIO": "7"})
    n1 = ("cpu", "memory", "pods")
    n2 = ("cpu", "memory", "pods", "nvidia.com/gpu")
    r1 = pod_batch_record(pod, n1)
    r2 = pod_batch_record(pod, n2)
    assert len(r2[1]) == 4 and r2[1][:3] == r1[1][:3]
    assert np.frombuffer(r2[6], np.float32).shape == (4,)
    assert r2[7] == r1[7]  # scalar block carried, not recomputed


def test_build_pod_batch_rejects_stale_recs_layout():
    """A handed-in recs list built against an older column layout must be
    discarded, not trusted (build_snapshot can grow hostPort/attach
    columns between the flag pass and the batch build)."""
    import numpy as np

    from kubernetes_scheduler_tpu.host.snapshot import (
        SnapshotBuilder,
        pod_batch_record,
    )

    b = SnapshotBuilder()
    pods = [make_pod("a", cpu=100), make_pod("b", cpu=200)]
    stale_names = ("cpu",)
    stale = [pod_batch_record(p, stale_names) for p in pods]
    batch = b.build_pod_batch(pods, recs=stale)
    req = np.asarray(batch.request)
    # correct layout: cpu at column 0, memory present, pods column = 1
    names = b.resource_names
    assert req[0, names.index("cpu")] == 100.0
    assert req[1, names.index("cpu")] == 200.0
    assert req[0, names.index("pods")] == 1.0


def test_window_flags_single_walk_and_identity_cache():
    """_window_flags computes (all plain, any soft) once per window list
    and hands its records to build_pod_batch — a second probe of the same
    list must be a cache hit (no rewalk)."""
    nodes = [make_node("n0")]
    s = make_sched(nodes, [], {"n0": NodeUtil(cpu_pct=10, disk_io=5)})
    from kubernetes_scheduler_tpu.host.types import WeightedExpression, MatchExpression

    soft_pod = make_pod("soft", preferred_node_affinity=[
        WeightedExpression(weight=1, expr=MatchExpression(
            key="zone", operator="In", values=["z1"]))
    ])
    window = [make_pod("plain"), soft_pod]
    all_plain, any_soft = s._window_flags(window)
    assert (all_plain, any_soft) == (False, True)
    assert s._window_recs(window) is not None
    # identity cache: same tuple back without recomputation
    s.__dict__["_wflags"] = (window, "ALL", "SOFT")
    assert s._window_flags(window) == ("ALL", "SOFT")
    # a different list recomputes
    assert s._window_flags([make_pod("q")]) == (True, False)


def test_running_features_record_false_preserves_steady_state_record():
    """Probing a throwaway concatenation with record=False must not evict
    the canonical list's prefix record (the reservations / per-chunk
    regression the round-5 review caught)."""
    nodes = [make_node("n0")]
    canonical = [make_pod(f"r{i}") for i in range(4)]
    s = make_sched(nodes, canonical, {"n0": NodeUtil(cpu_pct=10, disk_io=5)})
    s._running_features(canonical)
    rec = s.__dict__["_run_feat"]
    assert rec[0][0] is canonical
    throwaway = canonical + [make_pod("resv")]
    s._running_features(throwaway, record=False)
    assert s.__dict__["_run_feat"] is rec  # untouched
    # default (record=True) on the canonical list extends the record
    canonical.append(make_pod("r4"))
    s._running_features(canonical)
    assert s.__dict__["_run_feat"][0][0] is canonical


def test_queue_handle_memo_cross_queue_and_resubmission():
    """mark_scheduled_many resolves handles via the pod-side memo; pods
    whose memo points at another queue fall back to the uid path, dead
    handles are skipped, and a same-uid resubmission schedules cleanly."""
    from kubernetes_scheduler_tpu.host.queue import make_queue

    q1, q2 = make_queue(), make_queue()
    pa, pb = make_pod("qa"), make_pod("qb")
    q1.push(pa)
    q1.push(pb)
    q2.push(pb)  # pb's memo now points at q2; its q1 entry is live
    got = q1.pop_window(10)
    assert got == [pa, pb] or got == [pb, pa]
    # pb resolves via the uid fallback (memo names q2), pa via the memo;
    # never-queued pods are skipped
    q1.mark_scheduled_many([pa, pb, make_pod("never-queued")])
    assert len(q1) == 0
    # dead-handle skip: pa's handle was dropped by the mark above, but
    # its memo still names q1 — re-marking must hit the `h in pods_d`
    # guard and fall through without touching anything
    q1.mark_scheduled_many([pa])
    assert len(q1) == 0
    pa2 = make_pod("qa")  # same uid, new object
    q1.push(pa2)
    assert q1.pop_window(10) == [pa2]
    q1.mark_scheduled_many([pa2])
    assert len(q1) == 0


# ---- BackgroundAdvisor: cycle-path decoupled metrics refresh -------------


class _CountingAdvisor:
    def __init__(self):
        self.calls = 0
        self.fail = False

    def fetch(self):
        self.calls += 1
        if self.fail:
            raise RuntimeError("prometheus down")
        return {"n0": NodeUtil(cpu_pct=float(self.calls))}


def test_background_advisor_serves_snapshot_without_inner_fetch():
    from kubernetes_scheduler_tpu.host.advisor import BackgroundAdvisor

    inner = _CountingAdvisor()
    clock = [0.0]
    adv = BackgroundAdvisor(
        inner, interval=5.0, max_staleness=60.0,
        clock=lambda: clock[0], start_thread=False,
    )
    adv._refresh_once()
    assert inner.calls == 1
    # cycle fetches inside the refresh interval: no inner calls
    for _ in range(10):
        snap = adv.fetch()
    assert inner.calls == 1 and snap["n0"].cpu_pct == 1.0
    assert adv.stale_served == 0
    # older than the interval but inside the budget: served, counted
    clock[0] = 30.0
    assert adv.fetch()["n0"].cpu_pct == 1.0
    assert inner.calls == 1 and adv.stale_served == 1


def test_background_advisor_staleness_budget_and_outage_contract():
    from kubernetes_scheduler_tpu.host.advisor import BackgroundAdvisor

    inner = _CountingAdvisor()
    clock = [0.0]
    adv = BackgroundAdvisor(
        inner, interval=5.0, max_staleness=60.0,
        clock=lambda: clock[0], start_thread=False,
    )
    # startup with no snapshot: fetch() does ONE synchronous scrape
    assert adv.fetch()["n0"].cpu_pct == 1.0
    assert inner.calls == 1
    # past the staleness budget with the scraper failing: the outage
    # propagates (run_cycle's fetch-failure path requeues the window)
    clock[0] = 120.0
    inner.fail = True
    import pytest as _pytest

    with _pytest.raises(RuntimeError):
        adv.fetch()
    # recovery: next fetch scrapes fresh
    inner.fail = False
    assert adv.fetch()["n0"].cpu_pct == 3.0  # calls: 1 ok, 2 fail, 3 ok


def test_background_advisor_thread_refreshes():
    import time as _time

    from kubernetes_scheduler_tpu.host.advisor import BackgroundAdvisor

    inner = _CountingAdvisor()
    adv = BackgroundAdvisor(inner, interval=0.02, max_staleness=60.0)
    try:
        assert inner.calls == 0  # lazy: no scraping before first fetch
        adv.fetch()  # first fetch starts the refresh thread
        deadline = _time.time() + 5.0
        while inner.calls < 3 and _time.time() < deadline:
            _time.sleep(0.02)
        assert inner.calls >= 3  # the daemon thread is scraping
        assert adv.fetch()["n0"].cpu_pct >= 1.0
    finally:
        adv.close()
    settled = inner.calls
    _time.sleep(0.08)
    assert inner.calls == settled  # close() stopped the thread


def test_background_advisor_rejects_interval_above_staleness():
    import pytest as _pytest

    from kubernetes_scheduler_tpu.host.advisor import BackgroundAdvisor

    with _pytest.raises(ValueError):
        BackgroundAdvisor(
            _CountingAdvisor(), interval=120.0, max_staleness=60.0,
            start_thread=False,
        )


def test_stale_served_exported_on_metrics_endpoint():
    from kubernetes_scheduler_tpu.host.observe import render_prometheus

    text = render_prometheus([], None, {"advisor_stale_served_total": 3})
    assert "advisor_stale_served_total 3" in text
    assert "# TYPE yoda_tpu_advisor_stale_served_total counter" in text


def test_scheduler_rides_stale_advisor_through_brownout_then_requeues():
    """End-to-end degradation contract: with the background advisor's
    scraper failing, cycles keep scheduling on the last snapshot inside
    the staleness budget (stale_served ticks); past the budget the
    synchronous fallback's failure surfaces as the cycle's fetch-failure
    path — window requeued, fetch_failures counted, nothing bound."""
    from kubernetes_scheduler_tpu.host.advisor import BackgroundAdvisor

    nodes = [make_node("n0"), make_node("n1")]
    inner = _CountingAdvisor()
    clock = [0.0]
    adv = BackgroundAdvisor(
        inner, interval=5.0, max_staleness=60.0,
        clock=lambda: clock[0], start_thread=False,
    )
    adv._refresh_once()  # healthy scrape at t=0
    s = Scheduler(
        SchedulerConfig(batch_window=8, min_device_work=0,
                        adaptive_dispatch=False),
        advisor=adv,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    inner.fail = True  # Prometheus goes down right after the scrape
    clock[0] = 30.0    # inside the budget: stale snapshot serves
    s.submit(make_pod("a", cpu=100, annotations={"diskIO": "2"}))
    m1 = s.run_cycle()
    assert m1.pods_bound == 1 and not m1.fetch_failed
    assert adv.stale_served >= 1
    clock[0] = 120.0   # past the budget: outage surfaces
    s.submit(make_pod("b", cpu=100, annotations={"diskIO": "2"}))
    m2 = s.run_cycle()
    assert m2.fetch_failed and m2.pods_bound == 0
    assert m2.pods_unschedulable == 1  # window requeued with backoff
    # recovery: scraper heals, the requeued pod binds next eligible cycle
    inner.fail = False
    s.queue._clock = lambda: 1e9  # expire the backoff
    m3 = s.run_cycle()
    assert m3.pods_bound == 1 and not m3.fetch_failed
