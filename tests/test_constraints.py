"""Constraint mask kernels vs. scalar upstream-semantics oracles."""

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_scheduler_tpu.engine import make_pod_batch, make_snapshot, schedule_batch
from kubernetes_scheduler_tpu.ops import (
    node_affinity_fit,
    pod_affinity_fit,
    taint_toleration_fit,
)
from kubernetes_scheduler_tpu.ops.constraints import (
    NO_EXECUTE,
    NO_SCHEDULE,
    OP_EXISTS,
    OP_IN,
    OP_NOT_EXISTS,
    OP_NOT_IN,
    PREFER_NO_SCHEDULE,
    TOL_EQUAL,
    TOL_EXISTS,
)
from tests import oracle

RNG = np.random.default_rng(4)


def pack_taints(per_node, t_max=4):
    n = len(per_node)
    taints = np.zeros((n, t_max, 3), np.int32)
    mask = np.zeros((n, t_max), bool)
    for i, ts in enumerate(per_node):
        for j, t in enumerate(ts):
            taints[i, j] = t
            mask[i, j] = True
    return jnp.asarray(taints), jnp.asarray(mask)


def pack_tols(per_pod, l_max=4):
    p = len(per_pod)
    tols = np.zeros((p, l_max, 4), np.int32)
    mask = np.zeros((p, l_max), bool)
    for i, ls in enumerate(per_pod):
        for j, (key, value, op, effect) in enumerate(ls):
            tols[i, j] = (-1 if key is None else key, value, op, effect)
            mask[i, j] = True
    return jnp.asarray(tols), jnp.asarray(mask)


def test_taint_toleration_matches_oracle():
    # keys/values are interned ids
    node_taints = [
        [],                                     # untainted
        [(1, 1, NO_SCHEDULE)],
        [(1, 2, NO_EXECUTE), (2, 1, NO_SCHEDULE)],
        [(3, 1, PREFER_NO_SCHEDULE)],           # soft taint: never filters
        [(1, 1, NO_SCHEDULE), (1, 1, NO_EXECUTE)],
    ]
    pod_tols = [
        [],                                     # no tolerations
        [(1, 1, TOL_EQUAL, 0)],                 # tolerate key1=val1, all effects
        [(1, 0, TOL_EXISTS, 0)],                # tolerate any key1
        [(None, 0, TOL_EXISTS, 0)],             # wildcard: tolerate everything
        [(1, 1, TOL_EQUAL, NO_SCHEDULE)],       # only NoSchedule effect
        [(1, 0, TOL_EXISTS, 0), (2, 1, TOL_EQUAL, 0)],
    ]
    taints, t_mask = pack_taints(node_taints)
    tols, l_mask = pack_tols(pod_tols)
    got = np.asarray(taint_toleration_fit(taints, t_mask, tols, l_mask))
    for p, tl in enumerate(pod_tols):
        for n_, ts in enumerate(node_taints):
            assert got[p, n_] == oracle.taint_fit_oracle(ts, tl), (p, n_)


def test_taint_toleration_random_fuzz():
    keys = [1, 2, 3]
    vals = [1, 2]
    effects = [NO_SCHEDULE, PREFER_NO_SCHEDULE, NO_EXECUTE]
    node_taints = [
        [
            (int(RNG.choice(keys)), int(RNG.choice(vals)), int(RNG.choice(effects)))
            for _ in range(RNG.integers(0, 4))
        ]
        for _ in range(20)
    ]
    pod_tols = [
        [
            (
                None if RNG.random() < 0.1 else int(RNG.choice(keys)),
                int(RNG.choice(vals)),
                int(RNG.choice([TOL_EXISTS, TOL_EQUAL])),
                int(RNG.choice([0, NO_SCHEDULE, NO_EXECUTE])),
            )
            for _ in range(RNG.integers(0, 4))
        ]
        for _ in range(15)
    ]
    taints, t_mask = pack_taints(node_taints)
    tols, l_mask = pack_tols(pod_tols)
    got = np.asarray(taint_toleration_fit(taints, t_mask, tols, l_mask))
    for p, tl in enumerate(pod_tols):
        for n_, ts in enumerate(node_taints):
            # wildcard encoding: None key with op Equal is meaningless and
            # not produced by the host; skip those rows
            assert got[p, n_] == oracle.taint_fit_oracle(
                ts, [t for t in tl if not (t[0] is None and t[2] == TOL_EQUAL)]
            ), (p, n_)


def pack_node_labels(per_node, l_max=4):
    n = len(per_node)
    labels = np.zeros((n, l_max, 2), np.int32)
    mask = np.zeros((n, l_max), bool)
    for i, d in enumerate(per_node):
        for j, (k, v) in enumerate(d.items()):
            labels[i, j] = (k, v)
            mask[i, j] = True
    return jnp.asarray(labels), jnp.asarray(mask)


def pack_exprs(per_pod, e_max=3, v_max=3):
    p = len(per_pod)
    key = np.zeros((p, e_max), np.int32)
    op = np.zeros((p, e_max), np.int32)
    vals = np.zeros((p, e_max, v_max), np.int32)
    val_mask = np.zeros((p, e_max, v_max), bool)
    mask = np.zeros((p, e_max), bool)
    for i, exprs in enumerate(per_pod):
        for j, (k, o, vs) in enumerate(exprs):
            key[i, j], op[i, j], mask[i, j] = k, o, True
            for q, v in enumerate(vs):
                vals[i, j, q] = v
                val_mask[i, j, q] = True
    return tuple(map(jnp.asarray, (key, op, vals, val_mask, mask)))


def test_node_affinity_matches_oracle():
    node_labels = [
        {1: 1, 2: 1},
        {1: 2},
        {2: 3},
        {},
        {1: 1, 2: 2, 3: 1},
    ]
    pod_exprs = [
        [],                                      # no requirements
        [(1, OP_IN, [1, 2])],                    # zone in {a, b}
        [(1, OP_NOT_IN, [2])],                   # zone not b (absent ok)
        [(2, OP_EXISTS, [])],
        [(3, OP_NOT_EXISTS, [])],
        [(1, OP_IN, [1]), (2, OP_EXISTS, [])],   # conjunction
    ]
    labels, l_mask = pack_node_labels(node_labels)
    key, op, vals, val_mask, e_mask = pack_exprs(pod_exprs)
    got = np.asarray(node_affinity_fit(labels, l_mask, key, op, vals, val_mask, e_mask))
    for p, exprs in enumerate(pod_exprs):
        for n_, nl in enumerate(node_labels):
            assert got[p, n_] == oracle.node_affinity_fit_oracle(nl, exprs), (p, n_)


def test_node_affinity_or_of_ands():
    """Upstream nodeSelectorTerms semantics: AND within a term, OR across
    terms — term1-fails-term2-passes must schedule; every-term-fails must
    not; group ids need not be contiguous with expression order."""
    node_labels = [
        {1: 1},          # zone=a
        {1: 2},          # zone=b
        {1: 3, 2: 1},    # zone=c, disk=ssd
        {},
    ]
    labels, l_mask = pack_node_labels(node_labels)
    # pod 0: (zone in {a}) OR (zone in {b}) — two one-expression terms
    # pod 1: (zone in {a} AND disk exists) OR (zone in {c} AND disk exists)
    # pod 2: (zone in {9}) OR (zone in {8}) — both fail everywhere
    # pod 3: no requirements
    key, op, vals, val_mask, e_mask = pack_exprs([
        [(1, OP_IN, [1]), (1, OP_IN, [2])],
        [(1, OP_IN, [1]), (2, OP_EXISTS, []), (1, OP_IN, [3]), (2, OP_EXISTS, [])],
        [(1, OP_IN, [9]), (1, OP_IN, [8])],
        [],
    ], e_max=4, v_max=2)
    term = jnp.asarray(
        [[0, 1, 0, 0], [0, 0, 1, 1], [0, 1, 0, 0], [0, 0, 0, 0]], jnp.int32
    )
    got = np.asarray(
        node_affinity_fit(labels, l_mask, key, op, vals, val_mask, e_mask, term)
    )
    assert got.tolist() == [
        [True, True, False, False],     # a or b
        [False, False, True, False],    # (a & disk) or (c & disk) -> node 2
        [False, False, False, False],   # all terms fail
        [True, True, True, True],       # vacuous
    ]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_node_affinity_or_terms_random_oracle(seed):
    """Randomized OR-of-ANDs sweep vs the Python oracle: arbitrary term
    counts, expression mixes, duplicate keys, absent labels."""
    rng = np.random.default_rng(seed)
    n, p = 12, 10
    node_labels = [
        {int(k): int(rng.integers(0, 4)) for k in rng.choice(5, rng.integers(0, 4), replace=False)}
        for _ in range(n)
    ]
    pods_terms = []
    for _ in range(p):
        n_terms = int(rng.integers(0, 4))
        terms = []
        for _t in range(n_terms):
            n_exprs = int(rng.integers(1, 3))
            exprs = [
                (
                    int(rng.integers(0, 5)),
                    int(rng.integers(0, 4)),
                    [int(v) for v in rng.integers(0, 4, rng.integers(1, 3))],
                )
                for _ in range(n_exprs)
            ]
            terms.append(exprs)
        pods_terms.append(terms)

    e_max = max((sum(len(t) for t in ts) for ts in pods_terms), default=1) or 1
    v_max = 2
    key = np.zeros((p, e_max), np.int32)
    op = np.zeros((p, e_max), np.int32)
    vals = np.zeros((p, e_max, v_max), np.int32)
    val_mask = np.zeros((p, e_max, v_max), bool)
    e_mask = np.zeros((p, e_max), bool)
    term = np.zeros((p, e_max), np.int32)
    for i, ts in enumerate(pods_terms):
        j = 0
        for t_i, exprs in enumerate(ts):
            for k, o, vs in exprs:
                key[i, j], op[i, j], e_mask[i, j], term[i, j] = k, o, True, t_i
                for q, v in enumerate(vs):
                    vals[i, j, q] = v
                    val_mask[i, j, q] = True
                j += 1
    labels, l_mask = pack_node_labels(node_labels)
    got = np.asarray(node_affinity_fit(
        labels, l_mask, jnp.asarray(key), jnp.asarray(op), jnp.asarray(vals),
        jnp.asarray(val_mask), jnp.asarray(e_mask), jnp.asarray(term),
    ))
    for i, ts in enumerate(pods_terms):
        for n_i, nl in enumerate(node_labels):
            want = oracle.node_affinity_terms_oracle(nl, ts)
            assert got[i, n_i] == want, (seed, i, n_i, ts, nl)


def test_node_affinity_empty_term_matches_nothing():
    """An upstream term with no expressions matches no objects: encoded
    as In with an empty value set (the conversion's encoding), the term
    contributes nothing to the OR — and a pod whose ONLY term is empty is
    unschedulable."""
    labels, l_mask = pack_node_labels([{1: 1}, {}])
    # pod 0: empty term OR (zone in {1}); pod 1: only an empty term
    key, op, vals, val_mask, e_mask = pack_exprs(
        [[(0, OP_IN, []), (1, OP_IN, [1])], [(0, OP_IN, [])]],
        e_max=2, v_max=1,
    )
    term = jnp.asarray([[0, 1], [0, 0]], jnp.int32)
    got = np.asarray(
        node_affinity_fit(labels, l_mask, key, op, vals, val_mask, e_mask, term)
    )
    assert got.tolist() == [[True, False], [False, False]]


def test_node_affinity_default_term_matches_flat_and():
    """na_term of all zeros (the make_pod_batch default) must reproduce
    the single-AND-list behavior exactly."""
    node_labels = [{1: 1, 2: 1}, {1: 2}, {2: 3}, {}, {1: 1, 2: 2, 3: 1}]
    pod_exprs = [
        [],
        [(1, OP_IN, [1, 2])],
        [(1, OP_NOT_IN, [2])],
        [(2, OP_EXISTS, [])],
        [(1, OP_IN, [1]), (2, OP_EXISTS, [])],
    ]
    labels, l_mask = pack_node_labels(node_labels)
    key, op, vals, val_mask, e_mask = pack_exprs(pod_exprs)
    flat = np.asarray(
        node_affinity_fit(labels, l_mask, key, op, vals, val_mask, e_mask)
    )
    zeroed = np.asarray(
        node_affinity_fit(
            labels, l_mask, key, op, vals, val_mask, e_mask,
            jnp.zeros_like(key),
        )
    )
    np.testing.assert_array_equal(flat, zeroed)


def test_pod_affinity_fit():
    # 4 nodes, 2 selectors: selector 0 matched in domains of nodes 0,1;
    # selector 1 matched only at node 2's domain.
    counts = jnp.asarray([[2.0, 0.0], [1.0, 0.0], [0.0, 3.0], [0.0, 0.0]])
    aff = jnp.asarray([[0, -1], [-1, -1], [1, -1]], jnp.int32)
    anti = jnp.asarray([[-1, -1], [0, -1], [0, 1]], jnp.int32)
    got = np.asarray(pod_affinity_fit(counts, aff, anti))
    assert got.tolist() == [
        [True, True, False, False],    # needs sel0 nearby
        [False, False, True, True],    # repelled by sel0
        [False, False, False, False],  # needs sel1 but repels sel0&1 -> never
    ]


def test_pod_affinity_invalid_selector_id_is_unsatisfiable():
    counts = jnp.asarray([[0.0], [1.0]])  # S = 1
    aff = jnp.asarray([[3]], jnp.int32)   # id 3 out of range: host bug
    anti = jnp.asarray([[-1]], jnp.int32)
    got = np.asarray(pod_affinity_fit(counts, aff, anti))
    assert not got.any()  # surfaces as unschedulable, never aliases


def test_window_internal_anti_affinity_exact():
    """Two same-labeled pods with self anti-affinity in ONE window must land
    in different topology domains (the upstream per-pod re-snapshot
    behavior, reproduced by the greedy scan's dynamic domain counts)."""
    from kubernetes_scheduler_tpu.host import (
        Container, Node, NodeUtil, Pod, Scheduler, StaticAdvisor,
    )
    from kubernetes_scheduler_tpu.host.types import PodAffinityTerm
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes = [
        Node(name=f"n{i}", allocatable={"cpu": 8000, "memory": 32 * 2**30, "pods": 110})
        for i in range(4)
    ]
    utils = {n.name: NodeUtil(cpu_pct=50, disk_io=10) for n in nodes}

    def replica(name):
        return Pod(
            name=name,
            labels={"app": "db"},
            containers=[Container(requests={"cpu": 500})],
            annotations={"diskIO": "5"},
            pod_affinity=[PodAffinityTerm({"app": "db"}, anti=True)],
        )

    s = Scheduler(
        SchedulerConfig(batch_window=16),
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        # bound pods become running pods for the next cycle
        list_running_pods=lambda: [b.pod for b in s.binder.bindings],
    )
    for i in range(3):
        s.submit(replica(f"db-{i}"))
    m = s.run_cycle()
    assert m.pods_bound == 3
    hosts = [b.node_name for b in s.binder.bindings]
    assert len(set(hosts)) == 3, f"anti-affinity violated within window: {hosts}"
    # a 5th replica on a 4-node cluster is unschedulable
    s.submit(replica("db-3"))
    s.submit(replica("db-4"))
    m2 = s.run_cycle()
    assert m2.pods_bound == 1 and m2.pods_unschedulable == 1


def test_window_internal_positive_affinity():
    """A pod requiring affinity to a pod scheduled in the SAME window must
    co-locate with it once placed."""
    from kubernetes_scheduler_tpu.host import (
        Container, Node, NodeUtil, Pod, Scheduler, StaticAdvisor,
    )
    from kubernetes_scheduler_tpu.host.types import PodAffinityTerm
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes = [
        Node(name=f"n{i}", allocatable={"cpu": 8000, "memory": 32 * 2**30, "pods": 110})
        for i in range(4)
    ]
    utils = {n.name: NodeUtil(cpu_pct=40 + i, disk_io=10) for i, n in enumerate(nodes)}
    web = Pod(
        name="web", labels={"app": "web", "scv/priority": "9"},
        containers=[Container(requests={"cpu": 500})], annotations={"diskIO": "5"},
    )
    sidecar = Pod(
        name="sidecar",
        containers=[Container(requests={"cpu": 100})], annotations={"diskIO": "5"},
        pod_affinity=[PodAffinityTerm({"app": "web"})],
    )
    s = Scheduler(
        SchedulerConfig(batch_window=16),
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    s.submit(web)
    s.submit(sidecar)
    m = s.run_cycle()
    assert m.pods_bound == 2
    bound = {b.pod.name: b.node_name for b in s.binder.bindings}
    assert bound["sidecar"] == bound["web"]


def test_make_batch_mask_defaults_to_valid_for_provided_payloads():
    """Providing tolerations/taints/na exprs without masks must mean
    'all provided entries are real', not 'ignore the payload'."""
    n, p = 2, 1
    taints = np.asarray([[[7, 0, NO_SCHEDULE]], [[7, 0, NO_SCHEDULE]]], np.int32)
    snap = make_snapshot(
        allocatable=np.full((n, 1), 1000, np.float32),
        requested=np.zeros((n, 1), np.float32),
        disk_io=np.zeros(n), cpu_pct=np.zeros(n), mem_pct=np.zeros(n),
        taints=taints,  # no taint_mask
    )
    pods = make_pod_batch(request=np.full((p, 1), 10, np.float32))
    res = schedule_batch(snap, pods)
    # untolerated NoSchedule taints on every node -> unschedulable
    assert int(res.n_assigned) == 0

    tols = np.asarray([[[7, 0, TOL_EXISTS, 0]]], np.int32)
    pods_tol = make_pod_batch(
        request=np.full((p, 1), 10, np.float32), tolerations=tols  # no tol_mask
    )
    res2 = schedule_batch(snap, pods_tol)
    assert int(res2.n_assigned) == 1


def test_engine_with_constraints_end_to_end():
    """Taints + affinity wired through schedule_batch feasibility."""
    n, p, r = 8, 3, 2
    alloc = np.full((n, r), 10000, np.float32)
    reqd = np.zeros((n, r), np.float32)
    # nodes 0-3 tainted NoSchedule key9; nodes 4-7 labeled zone(5)=1
    node_taints = [[(9, 1, NO_SCHEDULE)]] * 4 + [[]] * 4
    node_labels = [{}] * 4 + [{5: 1}] * 4
    taints, t_mask = pack_taints(node_taints)
    labels, l_mask = pack_node_labels(node_labels)
    snapshot = make_snapshot(
        allocatable=alloc, requested=reqd,
        disk_io=np.full(n, 10.0), cpu_pct=np.full(n, 50.0),
        mem_pct=np.full(n, 50.0),
        taints=taints, taint_mask=t_mask,
        node_labels=labels, node_label_mask=l_mask,
    )
    # pod0: no tolerations, no affinity -> only untainted nodes 4-7
    # pod1: tolerates key9 -> all nodes
    # pod2: requires zone=1 -> nodes 4-7 (also untolerated -> 4-7)
    tols, tol_mask = pack_tols([[], [(9, 1, TOL_EQUAL, 0)], []])
    key, op, vals, val_mask, e_mask = pack_exprs([[], [], [(5, OP_IN, [1])]])
    pods = make_pod_batch(
        request=np.full((p, r), 100, np.float32),
        r_io=np.full(p, 10.0),
        tolerations=tols, tol_mask=tol_mask,
        na_key=key, na_op=op, na_vals=vals, na_val_mask=val_mask, na_mask=e_mask,
    )
    res = schedule_batch(snapshot, pods)
    feas = np.asarray(res.feasible)
    assert feas[0].tolist() == [False] * 4 + [True] * 4
    assert feas[1].tolist() == [True] * 8
    assert feas[2].tolist() == [False] * 4 + [True] * 4
    assert (np.asarray(res.node_idx) >= 0).all()
