"""Native host runtime tests: the C++ queue and scalar cycle must make
exactly the decisions of their pure-Python counterparts (which are
themselves golden-tested against the reference formulas)."""

import numpy as np
import pytest

from kubernetes_scheduler_tpu import native
from kubernetes_scheduler_tpu.host.advisor import NodeUtil, StaticAdvisor
from kubernetes_scheduler_tpu.host.plugins import ScalarYodaPlugin, scalar_schedule_one
from kubernetes_scheduler_tpu.host.queue import (
    NativeBackedQueue,
    SchedulingQueue,
    make_queue,
    pod_priority,
)
from kubernetes_scheduler_tpu.host.scheduler import Scheduler
from kubernetes_scheduler_tpu.host.types import Container, Node, Pod
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)

RNG = np.random.default_rng(11)


def make_pod(name, cpu=500.0, prio=0, r_io=None):
    ann = {} if r_io is None else {"diskIO": str(r_io)}
    return Pod(
        name=name,
        labels={"scv/priority": str(prio)},
        annotations=ann,
        containers=[Container(requests={"cpu": cpu, "memory": 2**30})],
    )


def make_node(name, cpu=8000.0):
    return Node(name=name, allocatable={"cpu": cpu, "memory": 2**35, "pods": 110})


# ---- queue ---------------------------------------------------------------


def test_native_queue_matches_python_ordering():
    clock_t = [0.0]
    clock = lambda: clock_t[0]  # noqa: E731
    nq = NativeBackedQueue(clock=clock)
    pq = SchedulingQueue(clock=clock)
    pods = [make_pod(f"p{i}", prio=int(RNG.integers(0, 5))) for i in range(50)]
    for p in pods:
        nq.push(p)
        pq.push(p)
    for window in (7, 13, 50):
        a = [p.name for p in nq.pop_window(window)]
        b = [p.name for p in pq.pop_window(window)]
        assert a == b
    assert len(nq) == len(pq) == 0


def test_native_queue_backoff_schedule():
    clock_t = [100.0]
    q = NativeBackedQueue(initial_backoff=1.0, max_backoff=10.0,
                          clock=lambda: clock_t[0])
    pod = make_pod("r")
    # attempts 1..5: delays 1, 2, 4, 8, 10 (capped)
    for expect_delay in (1.0, 2.0, 4.0, 8.0, 10.0, 10.0):
        q.requeue_unschedulable(pod)
        clock_t[0] += expect_delay - 0.01
        assert q.pop_window(10) == []
        clock_t[0] += 0.02
        assert [p.name for p in q.pop_window(10)] == ["r"]
    # success clears the attempt counter
    q.mark_scheduled(pod)
    q.requeue_unschedulable(pod)
    clock_t[0] += 1.01
    assert [p.name for p in q.pop_window(10)] == ["r"]


def test_native_queue_duplicate_push_survives_mark_scheduled():
    """A uid pushed twice (duplicate informer events): binding one copy
    must not make popping the second copy crash."""
    q = NativeBackedQueue(clock=lambda: 0.0)
    pod = make_pod("dup")
    q.push(pod)
    q.push(pod)
    first = q.pop_window(1)
    assert [p.name for p in first] == ["dup"]
    q.mark_scheduled(first[0])
    second = q.pop_window(10)
    assert [p.name for p in second] == ["dup"]
    q.mark_scheduled(second[0])
    assert len(q) == 0
    assert not q._pods and not q._by_uid and not q._outstanding


def test_make_queue_fallback():
    assert isinstance(make_queue(prefer_native=False), SchedulingQueue)
    assert isinstance(make_queue(prefer_native=True), NativeBackedQueue)


# ---- scalar cycle --------------------------------------------------------


def random_cluster(n, p, seed):
    rng = np.random.default_rng(seed)
    nodes = [make_node(f"n{i}", cpu=float(rng.choice([2000, 8000, 16000])))
             for i in range(n)]
    utils = {
        f"n{i}": NodeUtil(
            cpu_pct=float(rng.uniform(0, 100)),
            mem_pct=float(rng.uniform(0, 100)),
            disk_io=float(rng.uniform(0, 50)),
        )
        for i in range(n)
    }
    pods = [
        make_pod(
            f"p{i}",
            cpu=float(rng.integers(100, 3000)),
            r_io=float(rng.uniform(0, 40)) if rng.random() > 0.2 else None,
        )
        for i in range(p)
    ]
    return nodes, utils, pods


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_native_scalar_matches_python_plugin_path(seed):
    nodes, utils, pods = random_cluster(12, 30, seed)
    names = ["cpu", "memory", "pods", "storage", "ephemeral-storage"]

    # python path
    from kubernetes_scheduler_tpu.host.snapshot import (
        parse_float_or_zero,
        pod_resource_request,
    )

    plugin = ScalarYodaPlugin(utils)
    free_py = {
        n.name: {r: n.allocatable.get(r, 0.0) for r in names} for n in nodes
    }
    py_choice = []
    for pod in pods:
        plugin.cache.flush()
        py_choice.append(scalar_schedule_one(plugin, pod, nodes, free_py))

    # native path
    req = np.array(
        [[pod_resource_request(p, r) for r in names] for p in pods], np.float32
    )
    r_io = np.array(
        [parse_float_or_zero(p.annotations.get("diskIO")) for p in pods],
        np.float32,
    )
    free = np.array(
        [[n.allocatable.get(r, 0.0) for r in names] for n in nodes], np.float32
    )
    disk_io = np.array([utils[n.name].disk_io for n in nodes], np.float32)
    cpu_pct = np.array([utils[n.name].cpu_pct for n in nodes], np.float32)
    idx, free_after, bound = native.scalar_cycle(req, r_io, free, disk_io, cpu_pct)

    native_choice = [nodes[j].name if j >= 0 else None for j in idx]
    assert native_choice == py_choice
    assert bound == sum(c is not None for c in py_choice)
    # capacity bookkeeping agrees
    for j, n in enumerate(nodes):
        for k, r in enumerate(names):
            assert free_after[j, k] == pytest.approx(free_py[n.name][r], rel=1e-5)


@pytest.mark.parametrize("seed", [0, 3])
def test_scalar_cycler_matches_scalar_cycle(seed):
    rng = np.random.default_rng(seed)
    p, n, r = 9, 17, 5
    req = rng.uniform(0.0, 3.0, (p, r)).astype(np.float32)
    r_io = np.where(rng.random(p) > 0.3, rng.uniform(0, 40, p), 0).astype(
        np.float32
    )
    free = rng.uniform(1.0, 8.0, (n, r)).astype(np.float32)
    disk_io = rng.uniform(0, 60, n).astype(np.float32)
    cpu_pct = rng.uniform(0, 100, n).astype(np.float32)

    idx, free_after, bound = native.scalar_cycle(
        req, r_io, free.copy(), disk_io, cpu_pct
    )
    cyc = native.ScalarCycler(req, r_io, free, disk_io, cpu_pct)
    for _ in range(3):  # reruns are idempotent: free_in is never mutated
        got = cyc.run()
        assert got == bound
        assert np.array_equal(cyc.node_idx, idx)
        assert np.allclose(cyc.free_after, free_after)
    assert np.allclose(cyc.free, free)

    # state update between runs: drain the cluster and nothing binds
    cyc.free[:] = 0.0
    assert cyc.run() == 0
    assert np.all(cyc.node_idx == -1)


def test_scalar_cycler_shape_validation():
    with pytest.raises(ValueError):
        native.ScalarCycler(
            np.ones((2, 3)), np.ones(2), np.ones((4, 3)), np.ones(4),
            np.ones(3),
        )


def test_scalar_cycle_shape_validation():
    with pytest.raises(ValueError):
        native.scalar_cycle(
            np.ones((2, 3)), np.ones(3), np.ones((4, 3)), np.ones(4), np.ones(4)
        )


def test_aggregate_requested_matches_numpy():
    m, n, r = 200, 20, 5
    pod_node = RNG.integers(-1, n, m).astype(np.int32)
    pod_req = RNG.uniform(0, 100, (m, r)).astype(np.float32)
    got = native.aggregate_requested(pod_node, pod_req, n)
    want = np.zeros((n, r), np.float32)
    for i in range(m):
        if 0 <= pod_node[i] < n:
            want[pod_node[i]] += pod_req[i]
    np.testing.assert_allclose(got, want, rtol=1e-6)


# ---- scheduler integration ----------------------------------------------


def test_scheduler_native_scalar_path_binds():
    nodes, utils, pods = random_cluster(6, 10, 7)
    config = SchedulerConfig.from_dict(
        {"batch_window": 64, "feature_gates": {"tpu_batch_score": False}}
    )
    sched = Scheduler(
        config,
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    assert isinstance(sched.queue, NativeBackedQueue)
    for p in pods:
        sched.submit(p)
    m = sched.run_cycle()
    assert m.used_fallback and m.pods_bound == 10

    # same decisions as the pure-Python fallback
    config2 = SchedulerConfig.from_dict(
        {
            "batch_window": 64,
            "feature_gates": {"tpu_batch_score": False, "native_host": False},
        }
    )
    pods2 = [make_pod(p.name, cpu=p.containers[0].requests["cpu"],
                      r_io=p.annotations.get("diskIO")) for p in pods]
    sched2 = Scheduler(
        config2,
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    assert isinstance(sched2.queue, SchedulingQueue)
    for p in pods2:
        sched2.submit(p)
    m2 = sched2.run_cycle()
    assert m2.pods_bound == 10
    assert [b.node_name for b in sched.binder.bindings] == [
        b.node_name for b in sched2.binder.bindings
    ]
