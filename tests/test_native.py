"""Native host runtime tests: the C++ queue and scalar cycle must make
exactly the decisions of their pure-Python counterparts (which are
themselves golden-tested against the reference formulas)."""

import numpy as np
import pytest

from kubernetes_scheduler_tpu import native
from kubernetes_scheduler_tpu.host.advisor import NodeUtil, StaticAdvisor
from kubernetes_scheduler_tpu.host.plugins import ScalarYodaPlugin, scalar_schedule_one
from kubernetes_scheduler_tpu.host.queue import (
    NativeBackedQueue,
    SchedulingQueue,
    make_queue,
    pod_priority,
)
from kubernetes_scheduler_tpu.host.scheduler import Scheduler
from kubernetes_scheduler_tpu.host.types import Container, Node, Pod
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)

RNG = np.random.default_rng(11)


def make_pod(name, cpu=500.0, prio=0, r_io=None):
    ann = {} if r_io is None else {"diskIO": str(r_io)}
    return Pod(
        name=name,
        labels={"scv/priority": str(prio)},
        annotations=ann,
        containers=[Container(requests={"cpu": cpu, "memory": 2**30})],
    )


def make_node(name, cpu=8000.0):
    return Node(name=name, allocatable={"cpu": cpu, "memory": 2**35, "pods": 110})


# ---- queue ---------------------------------------------------------------


def test_native_queue_matches_python_ordering():
    clock_t = [0.0]
    clock = lambda: clock_t[0]  # noqa: E731
    nq = NativeBackedQueue(clock=clock)
    pq = SchedulingQueue(clock=clock)
    pods = [make_pod(f"p{i}", prio=int(RNG.integers(0, 5))) for i in range(50)]
    for p in pods:
        nq.push(p)
        pq.push(p)
    for window in (7, 13, 50):
        a = [p.name for p in nq.pop_window(window)]
        b = [p.name for p in pq.pop_window(window)]
        assert a == b
    assert len(nq) == len(pq) == 0


def test_native_queue_backoff_schedule():
    clock_t = [100.0]
    q = NativeBackedQueue(initial_backoff=1.0, max_backoff=10.0,
                          clock=lambda: clock_t[0])
    pod = make_pod("r")
    # attempts 1..5: delays 1, 2, 4, 8, 10 (capped)
    for expect_delay in (1.0, 2.0, 4.0, 8.0, 10.0, 10.0):
        q.requeue_unschedulable(pod)
        clock_t[0] += expect_delay - 0.01
        assert q.pop_window(10) == []
        clock_t[0] += 0.02
        assert [p.name for p in q.pop_window(10)] == ["r"]
    # success clears the attempt counter
    q.mark_scheduled(pod)
    q.requeue_unschedulable(pod)
    clock_t[0] += 1.01
    assert [p.name for p in q.pop_window(10)] == ["r"]


def test_native_queue_duplicate_push_survives_mark_scheduled():
    """A uid pushed twice (duplicate informer events): binding one copy
    must not make popping the second copy crash."""
    q = NativeBackedQueue(clock=lambda: 0.0)
    pod = make_pod("dup")
    q.push(pod)
    q.push(pod)
    first = q.pop_window(1)
    assert [p.name for p in first] == ["dup"]
    q.mark_scheduled(first[0])
    second = q.pop_window(10)
    assert [p.name for p in second] == ["dup"]
    q.mark_scheduled(second[0])
    assert len(q) == 0
    assert not q._pods and not q._by_uid and not q._outstanding


def test_mark_scheduled_many_duplicate_pod_marks_twice():
    """ADVICE r5 (low): a pod appearing twice in one batch must resolve
    its handle twice — ONE native batch call carrying the handle twice
    (harmless: the native mark is an idempotent attempts.erase), where
    the pre-fix early drop lost the second lookup mid-batch — and the
    bookkeeping still drains completely."""
    q = NativeBackedQueue(clock=lambda: 0.0)
    pod = make_pod("dup2")
    q.push(pod)
    q.push(pod)
    popped = q.pop_window(2)
    assert [p.name for p in popped] == ["dup2", "dup2"]
    batches = []
    real_batch = q._q.mark_scheduled_batch

    def recording(arr):
        batches.append(np.asarray(arr).tolist())
        return real_batch(arr)

    q._q.mark_scheduled_batch = recording
    q.mark_scheduled_many(popped)
    assert len(batches) == 1
    assert len(batches[0]) == 2 and batches[0][0] == batches[0][1]
    assert len(q) == 0
    assert not q._pods and not q._by_uid and not q._outstanding


def test_mark_scheduled_many_native_failure_keeps_bookkeeping():
    """ADVICE r5 (low): mark-then-drop ordering — when the native batch
    call raises, the Python maps must be intact so the binds can be
    re-marked (the native retry counters were never cleared)."""
    q = NativeBackedQueue(clock=lambda: 0.0)
    pod = make_pod("boom")
    q.push(pod)
    popped = q.pop_window(1)
    assert [p.name for p in popped] == ["boom"]
    real_batch = q._q.mark_scheduled_batch

    def raising(arr):
        raise RuntimeError("native batch failed")

    q._q.mark_scheduled_batch = raising
    with pytest.raises(RuntimeError, match="native batch failed"):
        q.mark_scheduled_many(popped)
    # maps untouched: the pod's handle is still resolvable
    assert q._by_uid and q._pods
    # retry succeeds and only then drops the bookkeeping
    q._q.mark_scheduled_batch = real_batch
    q.mark_scheduled_many(popped)
    assert not q._pods and not q._by_uid and not q._outstanding


def test_make_queue_fallback():
    assert isinstance(make_queue(prefer_native=False), SchedulingQueue)
    assert isinstance(make_queue(prefer_native=True), NativeBackedQueue)


# ---- scalar cycle --------------------------------------------------------


def random_cluster(n, p, seed):
    rng = np.random.default_rng(seed)
    nodes = [make_node(f"n{i}", cpu=float(rng.choice([2000, 8000, 16000])))
             for i in range(n)]
    utils = {
        f"n{i}": NodeUtil(
            cpu_pct=float(rng.uniform(0, 100)),
            mem_pct=float(rng.uniform(0, 100)),
            disk_io=float(rng.uniform(0, 50)),
        )
        for i in range(n)
    }
    pods = [
        make_pod(
            f"p{i}",
            cpu=float(rng.integers(100, 3000)),
            r_io=float(rng.uniform(0, 40)) if rng.random() > 0.2 else None,
        )
        for i in range(p)
    ]
    return nodes, utils, pods


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_native_scalar_matches_python_plugin_path(seed):
    nodes, utils, pods = random_cluster(12, 30, seed)
    names = ["cpu", "memory", "pods", "storage", "ephemeral-storage"]

    # python path
    from kubernetes_scheduler_tpu.host.snapshot import (
        parse_float_or_zero,
        pod_resource_request,
    )

    plugin = ScalarYodaPlugin(utils)
    free_py = {
        n.name: {r: n.allocatable.get(r, 0.0) for r in names} for n in nodes
    }
    py_choice = []
    for pod in pods:
        plugin.cache.flush()
        py_choice.append(scalar_schedule_one(plugin, pod, nodes, free_py))

    # native path
    req = np.array(
        [[pod_resource_request(p, r) for r in names] for p in pods], np.float32
    )
    r_io = np.array(
        [parse_float_or_zero(p.annotations.get("diskIO")) for p in pods],
        np.float32,
    )
    free = np.array(
        [[n.allocatable.get(r, 0.0) for r in names] for n in nodes], np.float32
    )
    disk_io = np.array([utils[n.name].disk_io for n in nodes], np.float32)
    cpu_pct = np.array([utils[n.name].cpu_pct for n in nodes], np.float32)
    idx, free_after, bound = native.scalar_cycle(req, r_io, free, disk_io, cpu_pct)

    native_choice = [nodes[j].name if j >= 0 else None for j in idx]
    assert native_choice == py_choice
    assert bound == sum(c is not None for c in py_choice)
    # capacity bookkeeping agrees
    for j, n in enumerate(nodes):
        for k, r in enumerate(names):
            assert free_after[j, k] == pytest.approx(free_py[n.name][r], rel=1e-5)


@pytest.mark.parametrize("seed", [0, 3])
def test_scalar_cycler_matches_scalar_cycle(seed):
    rng = np.random.default_rng(seed)
    p, n, r = 9, 17, 5
    req = rng.uniform(0.0, 3.0, (p, r)).astype(np.float32)
    r_io = np.where(rng.random(p) > 0.3, rng.uniform(0, 40, p), 0).astype(
        np.float32
    )
    free = rng.uniform(1.0, 8.0, (n, r)).astype(np.float32)
    disk_io = rng.uniform(0, 60, n).astype(np.float32)
    cpu_pct = rng.uniform(0, 100, n).astype(np.float32)

    idx, free_after, bound = native.scalar_cycle(
        req, r_io, free.copy(), disk_io, cpu_pct
    )
    cyc = native.ScalarCycler(req, r_io, free, disk_io, cpu_pct)
    for _ in range(3):  # reruns are idempotent: free_in is never mutated
        got = cyc.run()
        assert got == bound
        assert np.array_equal(cyc.node_idx, idx)
        assert np.allclose(cyc.free_after, free_after)
    assert np.allclose(cyc.free, free)

    # state update between runs: drain the cluster and nothing binds
    cyc.free[:] = 0.0
    assert cyc.run() == 0
    assert np.all(cyc.node_idx == -1)


def test_scalar_cycler_shape_validation():
    with pytest.raises(ValueError):
        native.ScalarCycler(
            np.ones((2, 3)), np.ones(2), np.ones((4, 3)), np.ones(4),
            np.ones(3),
        )


def test_scalar_cycle_shape_validation():
    with pytest.raises(ValueError):
        native.scalar_cycle(
            np.ones((2, 3)), np.ones(3), np.ones((4, 3)), np.ones(4), np.ones(4)
        )


def test_aggregate_requested_matches_numpy():
    m, n, r = 200, 20, 5
    pod_node = RNG.integers(-1, n, m).astype(np.int32)
    pod_req = RNG.uniform(0, 100, (m, r)).astype(np.float32)
    got = native.aggregate_requested(pod_node, pod_req, n)
    want = np.zeros((n, r), np.float32)
    for i in range(m):
        if 0 <= pod_node[i] < n:
            want[pod_node[i]] += pod_req[i]
    np.testing.assert_allclose(got, want, rtol=1e-6)


# ---- scheduler integration ----------------------------------------------


def test_scheduler_native_scalar_path_binds():
    nodes, utils, pods = random_cluster(6, 10, 7)
    config = SchedulerConfig.from_dict(
        {"batch_window": 64, "feature_gates": {"tpu_batch_score": False}}
    )
    sched = Scheduler(
        config,
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    assert isinstance(sched.queue, NativeBackedQueue)
    for p in pods:
        sched.submit(p)
    m = sched.run_cycle()
    assert m.used_fallback and m.pods_bound == 10

    # same decisions as the pure-Python fallback
    config2 = SchedulerConfig.from_dict(
        {
            "batch_window": 64,
            "feature_gates": {"tpu_batch_score": False, "native_host": False},
        }
    )
    pods2 = [make_pod(p.name, cpu=p.containers[0].requests["cpu"],
                      r_io=p.annotations.get("diskIO")) for p in pods]
    sched2 = Scheduler(
        config2,
        advisor=StaticAdvisor(utils),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    assert isinstance(sched2.queue, SchedulingQueue)
    for p in pods2:
        sched2.submit(p)
    m2 = sched2.run_cycle()
    assert m2.pods_bound == 10
    assert [b.node_name for b in sched.binder.bindings] == [
        b.node_name for b in sched2.binder.bindings
    ]


@pytest.mark.parametrize("seed", [3, 9])
def test_native_loop_matches_per_window_cycles(seed):
    """The fully-native tiny-cycle loop (loop.cc: queue pop -> scalar
    cycle -> bind/requeue, many cycles per foreign call) makes exactly
    the decisions of driving the same native queue + scalar cycle one
    popped window at a time from Python."""
    rng = np.random.default_rng(seed)
    m_pods, n_nodes, r = 13, 4, 3
    pod_req = rng.uniform(0.1, 1.5, (m_pods, r)).astype(np.float32)
    r_io = rng.uniform(0, 8, m_pods).astype(np.float32)
    prio = rng.integers(0, 4, m_pods).astype(np.int32)
    free = rng.uniform(1.5, 4.0, (n_nodes, r)).astype(np.float32)
    disk_io = rng.uniform(0, 50, n_nodes).astype(np.float32)
    cpu_pct = rng.uniform(0, 100, n_nodes).astype(np.float32)
    window, dt = 3, 1e-6

    loop = native.NativeLoop(
        pod_req, r_io, prio, free, disk_io, cpu_pct,
        window=window, dt_per_cycle=dt,
    )
    loop.submit_all()
    bound, cycles = loop.run(64)

    q = native.NativeQueue(initial_backoff=1.0, max_backoff=10.0)
    for h in range(m_pods):
        q.push(h, int(prio[h]))
    free2 = free.copy()
    idx2 = np.full(m_pods, -1, np.int32)
    now, bound2 = 0.0, 0
    for _ in range(cycles):
        hs = q.pop_window(window, now)
        if len(hs):
            out, free2, nb = native.scalar_cycle(
                pod_req[hs], r_io[hs], free2, disk_io, cpu_pct
            )
            bound2 += nb
            for i, h in enumerate(hs):
                idx2[h] = out[i]
                if out[i] >= 0:
                    q.mark_scheduled(int(h))
                else:
                    q.requeue_unschedulable(int(h), int(prio[h]), now)
        now += dt
    assert bound == bound2
    assert loop.node_idx.tolist() == idx2.tolist()
    np.testing.assert_allclose(loop.free, free2, rtol=1e-6)


def test_native_loop_reset_free_steady_state():
    """reset_free=True: every cycle schedules against the ORIGINAL
    capacity (the steady-state regime bench.py's tiny configs measure),
    so identical arrivals all bind to the identical node."""
    pod_req = np.full((6, 2), 1.0, np.float32)
    r_io = np.full(6, 5.0, np.float32)
    prio = np.zeros(6, np.int32)
    free = np.array([[1.5, 1.5], [8.0, 8.0]], np.float32)
    disk_io = np.array([10.0, 20.0], np.float32)
    cpu_pct = np.array([10.0, 20.0], np.float32)

    loop = native.NativeLoop(
        pod_req, r_io, prio, free, disk_io, cpu_pct,
        window=1, reset_free=True,
    )
    loop.submit_all()
    bound, cycles = loop.run(6)
    assert bound == 6 and cycles == 6
    # all six cycles saw the same capacity: same decision every time
    assert len(set(loop.node_idx.tolist())) == 1
    # without reset, the 1.5-capacity node fills and decisions shift
    loop2 = native.NativeLoop(
        pod_req, r_io, prio, free, disk_io, cpu_pct, window=1
    )
    loop2.submit_all()
    bound2, _ = loop2.run(6)
    assert np.asarray(loop2.free).min() < free.min()
