"""Weighted multi-plugin scoring (upstream framework RunScorePlugins):
the k8s 1.22 default shape scorers + the framework's weighted sum, which
the reference's deployed config produces by enabling yoda BESIDE the
defaults (/root/reference/deploy/yoda-scheduler.yaml:21-47 disables
nothing; example/config:25-27 weights yoda at 2)."""

import numpy as np
import pytest

from kubernetes_scheduler_tpu.engine import (
    PRESCALED_PLUGINS,
    combine_scores,
    compute_scores,
    make_pod_batch,
    make_snapshot,
    schedule_batch,
)
from kubernetes_scheduler_tpu.host import (
    Container,
    Node,
    NodeUtil,
    Pod,
    Scheduler,
    StaticAdvisor,
)
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

MB = 1024.0 * 1024

SP = (
    ("balanced_cpu_diskio", 2.0),
    ("least_allocated", 1.0),
    ("balanced_allocation", 1.0),
    ("image_locality", 1.0),
)
SP_CFG = [
    {"name": "balanced_cpu_diskio", "weight": 2},
    {"name": "least_allocated", "weight": 1},
    {"name": "balanced_allocation", "weight": 1},
    {"name": "image_locality", "weight": 1},
]


def tiny_snapshot():
    alloc = np.array([[1000.0, 4e9, 110], [2000.0, 8e9, 110]], np.float32)
    reqd = np.array([[200.0, 1e9, 3], [1500.0, 2e9, 5]], np.float32)
    return make_snapshot(
        alloc, reqd, np.array([5.0, 5.0]), np.array([10.0, 10.0]),
        np.array([10.0, 10.0]),
    )


def test_least_allocated_matches_hand_oracle():
    """NodeResourcesLeastAllocated: mean over cpu/memory of
    (alloc - req - pod) * 100 / alloc, 0 on overflow/zero-alloc."""
    s = tiny_snapshot()
    pb = make_pod_batch(np.array([[300.0, 1e9, 1]], np.float32))
    got = np.asarray(compute_scores(s, pb, "least_allocated"))[0]
    want0 = ((1000 - 500) * 100 / 1000 + (4e9 - 2e9) * 100 / 4e9) / 2
    want1 = ((2000 - 1800) * 100 / 2000 + (8e9 - 3e9) * 100 / 8e9) / 2
    np.testing.assert_allclose(got, [want0, want1], rtol=1e-5)
    # request overflowing a resource zeroes that resource's contribution
    pb2 = make_pod_batch(np.array([[900.0, 1e9, 1]], np.float32))
    got2 = np.asarray(compute_scores(s, pb2, "least_allocated"))[0]
    assert got2[0] == pytest.approx((0 + (4e9 - 2e9) * 100 / 4e9) / 2)


def test_balanced_allocation_matches_hand_oracle():
    """NodeResourcesBalancedAllocation: (1 - |cpuF - memF|) * 100, zero
    when any post-placement fraction reaches 1."""
    s = tiny_snapshot()
    pb = make_pod_batch(np.array([[300.0, 1e9, 1]], np.float32))
    got = np.asarray(compute_scores(s, pb, "balanced_allocation"))[0]
    want0 = (1 - abs(500 / 1000 - 2e9 / 4e9)) * 100
    want1 = (1 - abs(1800 / 2000 - 3e9 / 8e9)) * 100
    np.testing.assert_allclose(got, [want0, want1], rtol=1e-5)
    pb2 = make_pod_batch(np.array([[900.0, 1e9, 1]], np.float32))
    assert np.asarray(compute_scores(s, pb2, "balanced_allocation"))[0][0] == 0.0


def test_image_locality_matches_hand_oracle():
    """ImageLocality: sum of host-prescaled (size * spread-ratio) over
    the pod's images present on the node, ramped 23MB..1000MB per
    container and clipped to [0, 100]."""
    import jax.numpy as jnp

    s = tiny_snapshot()
    img = np.zeros((2, 2), np.float32)
    img[0, 0] = 500 * MB * 0.5  # node0 holds img0; 1 of 2 nodes -> ratio .5
    img[1, 1] = 2000 * MB * 0.5
    s = s._replace(image_scaled=jnp.asarray(img))
    pb = make_pod_batch(np.array([[100.0, 1e8, 1]], np.float32)).\
        _replace(image_ids=jnp.asarray([[0]], np.int32),
                 n_containers=jnp.asarray([1], np.int32))
    got = np.asarray(compute_scores(s, pb, "image_locality"))[0]
    want = (250 * MB - 23 * MB) / (1000 * MB - 23 * MB) * 100
    np.testing.assert_allclose(got, [want, 0.0], rtol=1e-5)
    # a huge image clips at 100; 2 containers double both thresholds
    pb2 = pb._replace(image_ids=jnp.asarray([[1]], np.int32),
                      n_containers=jnp.asarray([2], np.int32))
    got2 = np.asarray(compute_scores(s, pb2, "image_locality"))[0]
    want2 = (1000 * MB - 46 * MB) / (2000 * MB - 46 * MB) * 100
    np.testing.assert_allclose(got2, [0.0, want2], rtol=1e-5)


def test_combine_scores_weighting_and_normalization():
    """Plugins with a NormalizeScore extension (yoda) are min-maxed per
    pod before weighting; prescaled shape scorers enter raw — then the
    weighted sum, never re-normalized (the framework runtime's math)."""
    from kubernetes_scheduler_tpu.ops.normalize import min_max_normalize

    s = tiny_snapshot()
    pb = make_pod_batch(np.array([[300.0, 1e9, 1]], np.float32),
                        r_io=np.array([5.0]))
    combined = np.asarray(combine_scores(s, pb, SP))
    yoda = min_max_normalize(
        compute_scores(s, pb, "balanced_cpu_diskio"), s.node_mask
    )
    want = (
        2.0 * np.asarray(yoda)
        + np.asarray(compute_scores(s, pb, "least_allocated"))
        + np.asarray(compute_scores(s, pb, "balanced_allocation"))
        + np.asarray(compute_scores(s, pb, "image_locality"))
    )
    np.testing.assert_allclose(combined, want, rtol=1e-6)


def test_weights_change_decisions():
    """The combination is not cosmetic: a heavily weighted shape scorer
    must be able to overturn the yoda-only choice."""
    # node0 wins on yoda balance; node1 wins hugely on free share
    alloc = np.array([[2000.0, 8e9, 110], [32000.0, 128e9, 110]], np.float32)
    reqd = np.array([[1000.0, 4e9, 3], [1000.0, 4e9, 3]], np.float32)
    s = make_snapshot(
        alloc, reqd,
        np.array([10.0, 30.0]),   # disk_io: u = .2 / .6
        np.array([20.0, 60.0]),   # cpu_pct: v = .2 / .6
        np.array([50.0, 50.0]),
    )
    pb = make_pod_batch(np.array([[500.0, 1e9, 1]], np.float32),
                        r_io=np.array([10.0]))
    yoda_only = int(np.asarray(
        schedule_batch(s, pb, policy="balanced_cpu_diskio").node_idx
    )[0])
    weighted = int(np.asarray(
        schedule_batch(
            s, pb,
            score_plugins=(("balanced_cpu_diskio", 1.0),
                           ("least_allocated", 50.0)),
        ).node_idx
    )[0])
    assert yoda_only == 0 and weighted == 1


def test_sharded_combined_matches_dense():
    """Bit-identical decisions for the weighted combination on an
    8-device node-sharded mesh, both assigners."""
    import jax

    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_schedule_fn
    from kubernetes_scheduler_tpu.parallel.mesh import make_mesh
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    assert jax.device_count() == 8
    mesh = make_mesh(8)
    snap = gen_cluster(32, seed=5, constraints=True, images=True)
    pods = gen_pods(10, seed=6, constraints=True, images=True)
    for assigner in ("greedy", "auction"):
        fn = make_sharded_schedule_fn(mesh, assigner=assigner, score_plugins=SP)
        sh = fn(snap, pods)
        de = schedule_batch(
            snap, pods, score_plugins=SP, assigner=assigner,
            affinity_aware=True,
        )
        assert (
            np.asarray(sh.node_idx).tolist()
            == np.asarray(de.node_idx).tolist()
        ), assigner


def _weighted_cluster():
    nodes, utils = [], {}
    for i in range(4):
        nodes.append(Node(
            name=f"n{i}",
            allocatable={"cpu": 4000.0 + 4000 * i,
                         "memory": (16 + 16 * i) * 2.0**30, "pods": 110},
            images={"app:v1": 600 * MB} if i in (1, 2) else {},
        ))
        utils[f"n{i}"] = NodeUtil(
            cpu_pct=10 + 22 * i, disk_io=3 + 11 * i, mem_pct=15 + 18 * i
        )
    return nodes, utils


def _weighted_pod(i):
    # sized so n0 (4000m) holds two: the window must spill across nodes,
    # exercising live capacity bookkeeping against frozen score state
    return Pod(
        name=f"p{i}",
        containers=[Container(requests={"cpu": 1500.0, "memory": 6 * 2.0**30},
                              image="app:v1")],
        annotations={"diskIO": str(2 + 3 * i)},
    )


def test_scalar_fallback_mirrors_weighted_combination():
    """An engine failure under score_plugins degrades to the SAME
    weighted combination (scalar mirrors of every plugin + the
    framework's per-plugin normalization), binding pod-for-pod
    identically — and without the mismatch counter."""
    nodes, utils = _weighted_cluster()
    cfg = dict(min_device_work=0, batch_window=16, score_plugins=SP_CFG)

    def build():
        return Scheduler(
            SchedulerConfig.from_dict(dict(cfg)),
            advisor=StaticAdvisor(utils),
            list_nodes=lambda: nodes,
            list_running_pods=lambda: [],
        )

    a, b = build(), build()

    def boom(*args, **kw):
        raise RuntimeError("device path down")

    b._run_batched = boom
    for s in (a, b):
        for i in range(6):
            s.submit(_weighted_pod(i))
        s.run_cycle()
    assert not a.metrics[-1].used_fallback
    assert b.metrics[-1].used_fallback and not b.metrics[-1].policy_mismatch
    ba = {x.pod.name: x.node_name for x in a.binder.bindings}
    bb = {x.pod.name: x.node_name for x in b.binder.bindings}
    assert ba == bb and len(ba) == 6, (ba, bb)
    # the test is vacuous if every pod lands on one node — require spread
    assert len(set(ba.values())) >= 2, ba


def test_prescaled_tuples_stay_in_sync():
    """plugins.PRESCALED_SCALAR deliberately duplicates
    engine.PRESCALED_PLUGINS (the scalar path must not import jax);
    this pin is the drift guard."""
    from kubernetes_scheduler_tpu.host.plugins import (
        SCALAR_POLICIES,
        PRESCALED_SCALAR,
    )

    assert set(PRESCALED_SCALAR) == set(PRESCALED_PLUGINS)
    from kubernetes_scheduler_tpu.engine import POLICIES

    assert set(SCALAR_POLICIES) == set(POLICIES) - set()  # all mirrored


def test_config_validation():
    cfg = SchedulerConfig.from_dict({"score_plugins": SP_CFG})
    assert cfg.score_plugins_tuple() == SP
    assert SchedulerConfig().score_plugins_tuple() is None
    with pytest.raises(ValueError, match="score_plugins entries"):
        SchedulerConfig.from_dict({"score_plugins": ["nope"]})
    with pytest.raises(ValueError, match="unknown score_plugins keys"):
        SchedulerConfig.from_dict(
            {"score_plugins": [{"name": "x", "wieght": 2}]}
        )
    # weight 0 is ambiguous on the proto wire (proto3 zero = unset) and
    # silently disables locally — rejected at the config altitude
    with pytest.raises(ValueError, match="weight must be > 0"):
        SchedulerConfig.from_dict(
            {"score_plugins": [{"name": "image_locality", "weight": 0}]}
        )
    # sharded factories refuse silently-conflicting structural options
    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_schedule_fn
    from kubernetes_scheduler_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="cannot combine"):
        make_sharded_schedule_fn(
            make_mesh(8), score_plugins=SP, fused=True, normalizer="none"
        )
    with pytest.raises(ValueError, match="unknown policy"):
        combine_scores(
            tiny_snapshot(),
            make_pod_batch(np.array([[1.0, 1.0, 1]], np.float32)),
            (("nope", 1.0),),
        )


def test_builder_image_vocabulary_and_pod_ids():
    """host/snapshot: node images intern into a shared vocabulary with
    spread-ratio prescaling; pod-side ids are LOOKUP-only (an image on
    no node must not grow the table the matrix was sized against)."""
    from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder

    nodes = [
        Node(name="a", allocatable={"cpu": 1000, "memory": 2**30, "pods": 10},
             images={"app:v1": 400 * MB, "base:v2": 100 * MB}),
        Node(name="b", allocatable={"cpu": 1000, "memory": 2**30, "pods": 10},
             images={"app:v1": 400 * MB}),
    ]
    b = SnapshotBuilder()
    snap = b.build_snapshot(nodes, {}, [])
    ia, ib = b.images.id("app:v1"), b.images.id("base:v2")
    img = np.asarray(snap.image_scaled)
    assert img[0, ia] == pytest.approx(400 * MB * 1.0)   # both nodes
    assert img[1, ia] == pytest.approx(400 * MB * 1.0)
    assert img[0, ib] == pytest.approx(100 * MB * 0.5)   # one of two
    assert img[1, ib] == 0.0

    pods = [
        Pod(name="p", containers=[
            Container(requests={"cpu": 100}, image="app:v1"),
            Container(requests={"cpu": 100}, image="unseen:v9"),
        ]),
    ]
    pb = b.build_pod_batch(pods)
    ids = np.asarray(pb.image_ids)[0]
    assert ids[0] == ia and ids[1] == -1  # unseen image never interned
    assert int(np.asarray(pb.n_containers)[0]) == 2
    assert len(b.images) == 2


def test_kube_conversion_carries_images():
    from kubernetes_scheduler_tpu.kube import node_from_api, pod_from_api

    node = node_from_api({
        "metadata": {"name": "n0"},
        "status": {
            "allocatable": {"cpu": "4"},
            "images": [
                {"names": ["app@sha256:abc", "app:v1"], "sizeBytes": 1000},
                {"names": ["base:v2"], "sizeBytes": 50},
            ],
        },
    })
    assert node.images == {
        "app@sha256:abc": 1000.0, "app:v1": 1000.0, "base:v2": 50.0
    }
    pod = pod_from_api({
        "metadata": {"name": "p"},
        "spec": {"containers": [
            {"image": "app:v1",
             "resources": {"requests": {"cpu": "100m"}}},
            {},
        ]},
    })
    assert pod.containers[0].image == "app:v1"
    assert pod.containers[1].image == ""


def test_bridge_carries_score_plugins():
    """Dense sidecar: request-carried score_plugins produce the same
    decisions as the local combination; a sharded sidecar built WITHOUT
    them rejects such requests (they are baked into the compiled
    program, like policy)."""
    import pytest

    from kubernetes_scheduler_tpu.bridge.client import (
        EngineUnavailable,
        RemoteEngine,
    )
    from kubernetes_scheduler_tpu.bridge.server import make_server
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    snap = gen_cluster(16, seed=7, images=True)
    pods = gen_pods(5, seed=8, images=True)
    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        remote = client.schedule_batch(snap, pods, score_plugins=SP)
        local = schedule_batch(snap, pods, score_plugins=SP)
        assert (
            np.asarray(remote.node_idx).tolist()
            == np.asarray(local.node_idx).tolist()
        )
    finally:
        client.close()
        server.stop(grace=None)

    from kubernetes_scheduler_tpu.parallel.engine import make_sharded_schedule_fn
    from kubernetes_scheduler_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    server, port, _ = make_server(
        "127.0.0.1:0",
        sharded_fn=make_sharded_schedule_fn(mesh, score_plugins=SP),
        sharded_opts={
            "policy": "balanced_cpu_diskio",
            "normalizer": "min_max",
            "score_plugins": SP,
        },
    )
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        ok = client.schedule_batch(snap, pods, score_plugins=SP)
        want = schedule_batch(snap, pods, score_plugins=SP, affinity_aware=True)
        assert (
            np.asarray(ok.node_idx).tolist()
            == np.asarray(want.node_idx).tolist()
        )
        with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
            client.schedule_batch(snap, pods)  # built WITH, asked without
        with pytest.raises(EngineUnavailable, match="INVALID_ARGUMENT"):
            client.schedule_batch(
                snap, pods,
                score_plugins=(("balanced_cpu_diskio", 3.0),),
            )
    finally:
        client.close()
        server.stop(grace=None)
