"""Benchmark: batched TPU scheduling throughput vs. the reference design.

North-star metric (BASELINE.json): scheduling throughput at 10k nodes.
The reference publishes no numbers (BASELINE.md), so the denominator is a
faithful in-process emulation of its per-pod scheduling cycle: for every
pod, sequentially — recompute cluster utilization statistics, score every
node with the live BalancedCpuDiskIO formula, min-max normalize, pick the
best feasible node, decrement its capacity (what upstream kube-scheduler +
the yoda plugin compute per cycle, minus all of its network round-trips:
no 5.(N+1) Prometheus HTTP calls, no Redis — a strictly generous
baseline). The TPU path schedules the same pods through the batched engine
in windows, carrying capacity between windows.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_NODES = int(os.environ.get("BENCH_NODES", 10_000))
N_PODS = int(os.environ.get("BENCH_PODS", 16_384))
WINDOW = int(os.environ.get("BENCH_WINDOW", 512))
BASELINE_PODS = int(os.environ.get("BENCH_BASELINE_PODS", 64))
# 12 back-to-back backlogs per measurement: the one final sync is a pure
# tunnel round-trip (~70-90ms on the dev chip) and at 4 reps it was ~25%
# of the measured window, swinging the headline with tunnel weather; at
# 12 the measurement converges to the steady-state pipelined rate.
# suite_rate shares the knob (capped by its 65536-cell budget) — its
# small configs gain the same stability for sub-second extra wall time.
REPS = int(os.environ.get("BENCH_REPS", 12))
# fused Pallas score+feasibility kernel (identical decisions; fewer HBM passes)
FUSED = os.environ.get("BENCH_FUSED", "1") != "0"
# auction price step as a fraction of the unit score range. 1.0 is also
# the shipped host default since round 4: measured mean chosen score at
# 1.0 matches 1/16 on every suite config and never trails the greedy
# oracle (PARITY.md), so the fast step stopped being a quality trade.
PRICE_FRAC = float(os.environ.get("BENCH_PRICE_FRAC", 1.0))


def baseline_rate(snapshot, pods) -> float:
    """Pods/sec of the sequential per-pod reference design (numpy).

    Measured in steady state: tiny configs repeat the whole pod set until
    the measurement covers ~100ms of work — a single 1-pod iteration
    would time interpreter warmup, not the design."""
    alloc = np.asarray(snapshot.allocatable)
    requested0 = np.asarray(snapshot.requested)
    disk_io = np.asarray(snapshot.disk_io)
    cpu_pct = np.asarray(snapshot.cpu_pct)
    req = np.asarray(pods.request)[:BASELINE_PODS]
    r_io = np.asarray(pods.r_io)[:BASELINE_PODS]

    reps = max(1, 512 // max(len(req), 1))
    t0 = time.perf_counter()
    for _ in range(reps):
        requested = requested0.copy()
        _baseline_pass(req, r_io, alloc, requested, disk_io, cpu_pct)
    dt = time.perf_counter() - t0
    return reps * len(req) / dt


def _baseline_pass(req, r_io, alloc, requested, disk_io, cpu_pct):
    for i in range(len(req)):
        # per-cycle statistics (algorithm.go:67-89 recomputes these per pod)
        u = disk_io / 50.0
        v = cpu_pct / 100.0
        u_avg = u.mean()
        _ = ((u - u_avg) ** 2).mean()
        # live policy (algorithm.go:99-119)
        rio = r_io[i] if r_io[i] > 0 else np.inf
        beta = 1.0 / (1.0 + req[i, 0] / rio)
        alpha = 1.0 - beta
        s = 10.0 - 10.0 * np.abs(alpha * v - beta * u)
        # normalize (scheduler.go:158-183)
        hi, lo = max(s.max(), 0.0), s.min()
        if hi == lo:
            lo -= 1.0
        s = (s - lo) * 100.0 / (hi - lo)
        # feasibility + bind (upstream NodeResourcesFit + binding cycle)
        fits = ((requested + req[i]) <= alloc).all(axis=1)
        s[~fits] = -np.inf
        j = int(np.argmax(s))
        if np.isfinite(s[j]):
            requested[j] += req[i]


def tpu_rate(
    snapshot, pods, *, price_frac: float = None, affinity_aware: bool = False,
    score_plugins: tuple = None,
) -> float:
    """Pods/sec of the batched engine: the whole backlog as ONE device
    program (schedule_windows: lax.scan over capacity-carrying windows).
    Throughput is measured pipelined — REPS backlogs enqueued back-to-back,
    one final sync — the way a live scheduler overlaps cycle k+1's dispatch
    with cycle k's execution."""
    import jax
    from kubernetes_scheduler_tpu.engine import schedule_windows, stack_windows
    from kubernetes_scheduler_tpu.utils.padding import pad_pod_batch

    n_padded = -(-N_PODS // WINDOW) * WINDOW
    snapshot = jax.device_put(snapshot)
    pods_w = jax.device_put(stack_windows(pad_pod_batch(pods, n_padded), WINDOW))

    kw = dict(assigner="auction", fused=FUSED, affinity_aware=affinity_aware,
              auction_price_frac=PRICE_FRAC if price_frac is None else price_frac)
    if score_plugins:
        # weighted multi-plugin combination (no fused kernel for it)
        kw.update(score_plugins=score_plugins, fused=False)
    out = schedule_windows(snapshot, pods_w, **kw)
    # int() readback forces completion — on a tunneled device
    # block_until_ready alone does not synchronize
    assigned = int(out.n_assigned)
    if assigned == 0:
        raise RuntimeError("benchmark scheduled zero pods")
    if assigned < 0.5 * N_PODS:
        raise RuntimeError(
            f"benchmark scheduled only {assigned}/{N_PODS} pods — "
            "assignment quality regression"
        )

    t0 = time.perf_counter()
    for _ in range(REPS):
        out = schedule_windows(snapshot, pods_w, **kw)
    # scalar readback of the LAST backlog: the device stream executes
    # in order, so its completion covers all REPS executions, while the
    # enqueues still pipeline (block_until_ready does not synchronize on
    # a tunneled platform and would under-measure)
    if int(out.n_assigned) <= 0:
        raise RuntimeError("timed run scheduled zero pods")
    dt = time.perf_counter() - t0
    return REPS * N_PODS / dt


def native_rate(name: str, cfg: dict) -> dict:
    """Tiny configs through the host's adaptive dispatch target: the
    fully-native tiny-cycle loop (native/loop.cc — queue pop -> scalar
    cycle -> bind, many cycles per foreign call). The previous
    per-cycle ScalarCycler paid one ctypes dispatch per cycle (~2us,
    ~20x the C++ scheduling work — PARITY.md floor analysis); the native
    loop amortizes the dispatch across the whole cycle stream, which is
    what a resident native host process experiences."""
    from kubernetes_scheduler_tpu import native
    from kubernetes_scheduler_tpu.sim import gen_config

    snapshot, pods = gen_config(name, seed=0)
    n_pods = cfg["n_pods"]
    req = np.asarray(pods.request)[:n_pods]
    r_io = np.asarray(pods.r_io)[:n_pods]
    free = (
        np.asarray(snapshot.allocatable) - np.asarray(snapshot.requested)
    )[: cfg["n_nodes"]].astype(np.float32)
    disk_io = np.asarray(snapshot.disk_io)[: cfg["n_nodes"]]
    cpu_pct = np.asarray(snapshot.cpu_pct)[: cfg["n_nodes"]]

    # decision check at the original scale (one window through the
    # plain scalar cycle — same decisions the loop makes per cycle)
    idx, _, _ = native.scalar_cycle(req, r_io, free, disk_io, cpu_pct)

    # throughput: a stream of `reps` arrivals of the SAME workload,
    # window-sized cycles, each cycle against steady-state capacity
    # (reset_free — snapshots are rebuilt between real cycles). M pod
    # rows are the workload tiled so handle lookup stays trivial.
    reps = max(1, 200_000 // max(n_pods, 1))
    m = reps * n_pods
    loop = native.NativeLoop(
        np.tile(req, (reps, 1)), np.tile(r_io, reps),
        np.zeros(m, np.int32), free, disk_io, cpu_pct,
        window=n_pods, reset_free=True,
    )
    loop.submit_all()
    t0 = time.perf_counter()
    bound, cycles = loop.run(reps)
    dt = time.perf_counter() - t0
    if cycles != reps or bound < reps * int((idx >= 0).sum()):
        raise RuntimeError(
            f"native loop anomaly: {bound} binds in {cycles}/{reps} cycles"
        )
    rate = reps * n_pods / dt
    base = baseline_rate(snapshot, pods)
    return {
        "config": name,
        "pods": n_pods,
        "nodes": cfg["n_nodes"],
        "assigner": "native-loop",
        "assigned": int((np.asarray(idx) >= 0).sum()),
        "pods_per_sec": round(rate, 1),
        "vs_baseline": round(rate / base, 2),
    }


def _mean_chosen_score(snapshot, pods_flat, idx_flat, policy) -> float:
    """Mean min-max-normalized policy score (0-100) of the assigned
    pods' chosen nodes — the in-data quality measure beside raw assigned
    counts. Not on the timed path; computed in pod CHUNKS because the
    card policy's score intermediates are [p, n, c, 6] (full-batch at
    10k x 10k exhausts HBM)."""
    import jax.numpy as jnp
    from kubernetes_scheduler_tpu.engine import compute_scores
    from kubernetes_scheduler_tpu.ops.normalize import min_max_normalize

    idx_all = np.asarray(idx_flat).reshape(-1)
    mask_all = np.asarray(pods_flat.pod_mask)
    p = mask_all.shape[0]
    chunk = 256
    total, count = 0.0, 0
    for lo in range(0, p, chunk):
        hi = min(lo + chunk, p)
        sub = type(pods_flat)(*[np.asarray(a)[lo:hi] for a in pods_flat])
        raw = compute_scores(snapshot, sub, policy)
        norm = min_max_normalize(raw, snapshot.node_mask)
        idx = jnp.asarray(idx_all[lo:hi])
        ok = (idx >= 0) & jnp.asarray(mask_all[lo:hi])
        take = jnp.take_along_axis(
            norm, jnp.clip(idx, 0, norm.shape[1] - 1)[:, None], axis=1
        )[:, 0]
        total += float(jnp.where(ok, take, 0.0).sum())
        count += int(ok.sum())
    return total / max(count, 1)


def suite_rate(name: str) -> dict:
    """One BASELINE.md config end-to-end: pods/s on the batch engine and
    the vs-baseline ratio, with the same windowed schedule_windows program
    as the headline metric. Configs below the host's adaptive-dispatch
    threshold run the C++ scalar path instead, as host.scheduler would."""
    import jax
    from kubernetes_scheduler_tpu.engine import schedule_windows, stack_windows
    from kubernetes_scheduler_tpu.sim import gen_config
    from kubernetes_scheduler_tpu.sim.cluster_gen import BENCH_CONFIGS
    from kubernetes_scheduler_tpu.utils.padding import pad_pod_batch

    cfg = BENCH_CONFIGS[name]
    if (
        cfg["n_pods"] * cfg["n_nodes"] < (1 << 20)
        and not cfg.get("gpu")
        and not cfg.get("constraints")
    ):
        return native_rate(name, cfg)
    snapshot, pods = gen_config(name, seed=0)
    n_pods = cfg["n_pods"]
    # windows: measured knees (PARITY.md) — constraint configs amortize the
    # per-round dynamic-affinity cost best at 1024; selector-free configs
    # converge in fewer rounds per window at 512
    window = min(1024 if cfg.get("constraints") else 512, max(8, n_pods))
    n_padded = -(-n_pods // window) * window
    # the auction enforces hard (anti)affinity exactly (dynamic round
    # masks + conflict eviction), so constraint configs use it too;
    # selector-free configs skip the dynamic machinery entirely
    assigner = "auction"
    policy = "card" if cfg.get("gpu") else "balanced_cpu_diskio"
    affinity_aware = bool(cfg.get("constraints"))
    fused = FUSED and not cfg.get("gpu")  # card policy has no fused kernel
    snapshot = jax.device_put(snapshot)
    pods_flat = pad_pod_batch(pods, n_padded)
    pods_w = jax.device_put(stack_windows(pods_flat, window))

    def run(which=assigner):
        return schedule_windows(
            snapshot, pods_w, assigner=which, fused=fused,
            policy=policy,
            affinity_aware=affinity_aware,
            auction_price_frac=PRICE_FRAC,
        )

    out = run()
    assigned = int(out.n_assigned)  # readback = real sync (see tpu_rate)
    reps = max(1, min(REPS, 65_536 // n_pods))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = run()
    if int(out.n_assigned) <= 0:
        raise RuntimeError("timed run scheduled zero pods")
    dt = time.perf_counter() - t0
    rate = reps * n_pods / dt
    base = baseline_rate(snapshot, pods)
    # quality oracle (untimed): greedy on the SAME matrices settles
    # whether an assigned-count shortfall is genuine infeasibility
    # (greedy strands them too) or auction quality loss, and the mean
    # chosen score compares placement quality in-data
    gout = run("greedy")
    g_assigned = int(gout.n_assigned)
    return {
        "config": name,
        "pods": n_pods,
        "nodes": cfg["n_nodes"],
        "assigner": assigner,
        "assigned": assigned,
        "pods_per_sec": round(rate, 1),
        "vs_baseline": round(rate / base, 2),
        "assigned_greedy": g_assigned,
        "auction_vs_greedy_assigned": round(assigned / max(g_assigned, 1), 4),
        "mean_score_auction": round(
            _mean_chosen_score(snapshot, pods_flat, out.node_idx, policy), 2
        ),
        "mean_score_greedy": round(
            _mean_chosen_score(snapshot, pods_flat, gout.node_idx, policy), 2
        ),
    }


# the deployed default max_windows_per_cycle the bare host_loop metric
# measures; the BENCH_LOOP_PODS override scales against the same anchor
DEFAULT_LOOP_WINDOWS = 8


def _pipelined_loop_rate() -> dict:
    """The pipelined host-loop metric (host_loop_*_pipelined): SAME total
    backlog as the default host_loop metric, but one window per cycle
    with pipeline_depth=1, so the drain runs 8 pipelined cycles whose
    host work overlaps the in-flight engine calls — before/after on the
    same snapshot (vs. the serial metric's strictly alternating loop)."""
    return loop_rate(
        n_pods=int(os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)),
        max_windows=1,
        pipeline_depth=1,
        force_device=True,
        metric_suffix="_pipelined",
    )


def _resident_loop_rate() -> dict:
    """The resident-state host-loop metric (host_loop_*_resident): the
    pipelined shape with config.resident_state on — after the first full
    upload per bucket shape the engine retains the snapshot on device
    and cycles ship SnapshotDeltas applied by the jitted donated-buffer
    scatter. Reported beside host_loop_* / host_loop_*_pipelined with
    the delta hit rate and the snapshot payload actually shipped, so the
    upload win is measurable in-data (the acceptance gate: >= 15% more
    pods/s or >= 20% lower cycle p50 than the serial metric, with
    fallback_cycles 0 and PARITY-pinned identical bindings)."""
    return loop_rate(
        n_pods=int(os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)),
        max_windows=1,
        pipeline_depth=1,
        force_device=True,
        resident=True,
        metric_suffix="_resident",
    )


def _streaming_loop_rate() -> dict:
    """The streaming-ingestion metric (host_loop_*_streaming): the
    resident pipelined drain with the event-sourced snapshot mirror ON
    over a metric-churn workload, measured BESIDE an identical
    mirror-off drain in the same round. Both drains emit spans, so the
    replacement is in-data per round: mirror_emit (+ event_apply) p50
    against the baseline's snapshot_build + delta_derive p50 — the
    >=5x acceptance comparison at real sizes (reported, not asserted,
    at smoke sizes where ~ms cycles drown in jitter)."""
    import shutil
    import tempfile

    from kubernetes_scheduler_tpu.trace.analyze import build_report

    churn = int(os.environ.get("BENCH_CHURN_NODES", 64))
    n_pods = int(os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS))
    kw = dict(
        n_pods=n_pods, max_windows=1, pipeline_depth=1, force_device=True,
        resident=True, churn_nodes=churn,
    )
    t_on = tempfile.mkdtemp(prefix="yoda-stream-on-")
    t_off = tempfile.mkdtemp(prefix="yoda-stream-off-")
    try:
        # baseline FIRST: the two drains share one process's jit caches,
        # and whichever runs first pays the compiles — the probe paying
        # them keeps the headline row's engine/cycle numbers clean
        base = loop_rate(
            metric_suffix="_streaming_off_probe", span_path=t_off, **kw
        )
        # the sub-50ms cycle gate rides the streaming drain with its
        # own alarm armed: the SLO watchdog counts breaches live while
        # the row reports the p50 the gate reads
        out = loop_rate(
            metric_suffix="_streaming", mirror=True, span_path=t_on,
            slo_ms=50.0, **kw
        )
        rep_on = build_report(t_on)
        rep_off = build_report(t_off)

        def p50(rep, stage):
            s = rep["stages"].get(stage)
            return float(s["p50_ms"]) if s else 0.0

        out["mirror_emit_p50_ms"] = p50(rep_on, "mirror_emit")
        out["event_apply_p50_ms"] = p50(rep_on, "event_apply")
        out["baseline_snapshot_build_p50_ms"] = p50(rep_off, "snapshot_build")
        out["baseline_delta_derive_p50_ms"] = p50(rep_off, "delta_derive")
        out["baseline_pods_per_sec"] = base["pods_per_sec"]
        out["baseline_cycle_p50_ms"] = base["cycle_p50_ms"]
        baseline_stages = (
            out["baseline_snapshot_build_p50_ms"]
            + out["baseline_delta_derive_p50_ms"]
        )
        # the acceptance ratio: the stage that REPLACED snapshot_build +
        # delta_derive against what it replaced (>= 5x at real sizes)
        out["mirror_emit_speedup"] = round(
            baseline_stages / max(out["mirror_emit_p50_ms"], 1e-6), 2
        )
        # the conservative composite: event_apply added too (it also
        # covers the advisor's own changed-node fetch, which the
        # baseline pays under state_fetch — so this UNDERSTATES)
        out["streaming_stage_speedup"] = round(
            baseline_stages
            / max(
                out["mirror_emit_p50_ms"] + out["event_apply_p50_ms"], 1e-6
            ),
            2,
        )
        return out
    finally:
        shutil.rmtree(t_on, ignore_errors=True)
        shutil.rmtree(t_off, ignore_errors=True)


def _idle_streaming_rate() -> dict:
    """The idle-cluster streaming metric (host_loop_*_idle_streaming):
    what a cycle costs when NOTHING happened — the mirror emits a
    zero-row delta from a clean dirty set (the pre-mirror loop paid the
    full O(nodes) rebuild + row diff on every idle tick), plus the
    event->wakeup latency of the cycle trigger (config.cycle_trigger=
    "event")."""
    import threading

    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.sim.host_gen import (
        gen_host_cluster,
        gen_host_pods,
    )
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    nodes, advisor = gen_host_cluster(n_nodes, seed=0)
    running: list = []
    sched = Scheduler(
        SchedulerConfig(
            batch_window=256, normalizer="none", adaptive_dispatch=False,
            min_device_work=1, snapshot_mirror=True, cycle_trigger="event",
        ),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )
    # warm: one small backlog seeds the mirror and compiles the engine
    for pod in gen_host_pods(min(128, n_nodes), seed=1):
        sched.submit(pod)
    for _ in range(8):
        if len(sched.queue) == 0:
            break
        sched.run_cycle()
        for b in sched.binder.bindings[len(running):]:
            running.append(b.pod)
    reps = 20
    mir = sched.mirror
    prev, _, _ = mir.emit([], pending_all_plain=True, prev=None)
    emits = []
    zero_rows = True
    for _ in range(reps):
        t0 = time.perf_counter()
        snap, delta, _ = mir.emit([], pending_all_plain=True, prev=prev)
        emits.append(time.perf_counter() - t0)
        zero_rows &= delta is not None and bool(
            (np.asarray(delta.req_rows) >= n_nodes).all()
            and (np.asarray(delta.util_rows) >= n_nodes).all()
            and (np.asarray(delta.dom_rows) >= n_nodes).all()
        )
        prev = snap
    lats = []
    sched.trigger.wait(0)  # drain notifies latched during the warmup
    for _ in range(reps):
        holder = {}

        def poke():
            holder["t0"] = time.perf_counter()
            sched.trigger.notify()

        timer = threading.Timer(0.001, poke)
        timer.start()
        # a stray notify can wake the first wait before the timer fires
        # — keep waiting until the measured notify actually landed
        while "t0" not in holder:
            sched.trigger.wait(1.0)
        lats.append(time.perf_counter() - holder["t0"])
        timer.join()
    return {
        "metric": f"host_loop_{n_nodes}nodes_idle_streaming",
        "events_per_cycle": 0,
        "idle_zero_row_deltas": bool(zero_rows),
        "mirror_emit_idle_p50_ms": round(
            1e3 * float(np.percentile(emits, 50)), 4
        ),
        "trigger_latency_p50_ms": round(
            1e3 * float(np.percentile(lats, 50)), 4
        ),
        "trigger_latency_p99_ms": round(
            1e3 * float(np.percentile(lats, 99)), 4
        ),
    }


def _drift_streaming_rate() -> dict:
    """The layout-drift streaming metric (host_loop_*_streaming_drift):
    a mirror-on resident drain where EVERY backlog drifts the layout —
    one never-seen anti-affinity selector per round, plus a hostPort
    remap (the oldest port pod retires, a fresh port arrives, live
    count pinned at two). The pre-extension mirror flushed to a full
    rebuild on every such round; with the in-place extension paths
    (mirror_incremental_extensions_total{kind}) the recurring classes
    are absorbed and the only surviving rebuilds are power-of-two
    bucket/slot crossings — O(log drifts), ~0 per round post-warmup.
    The row ends with an on-demand bitwise verify() cross-check, so
    the absorbed rounds are proven equal to what a rebuild would have
    served."""
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.host.types import Pod, PodAffinityTerm
    from kubernetes_scheduler_tpu.sim.host_gen import (
        gen_host_cluster,
        gen_host_pods,
    )
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    rounds = int(os.environ.get("BENCH_DRIFT_ROUNDS", 12))
    backlog = max(32, min(256, n_nodes // 4))
    nodes, advisor = gen_host_cluster(n_nodes, seed=0, constraints=True)
    running: list = []
    sched = Scheduler(
        SchedulerConfig(
            batch_window=256, normalizer="none", adaptive_dispatch=False,
            min_device_work=1, snapshot_mirror=True, resident_state=True,
            pipeline_depth=1, max_windows_per_cycle=1,
        ),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )

    def drain():
        seen = len(sched.binder.bindings)
        for _ in range(64):
            if len(sched.queue) == 0 and sched._prefetched is None:
                break
            sched.run_cycle()
            for b in sched.binder.bindings[seen:]:
                running.append(b.pod)
            seen = len(sched.binder.bindings)

    # warmup: constraint traffic mints the steady-state selector
    # population (the generator's svc-app anti keys — enough to fill a
    # real power-of-two bucket), two port pods warm the two hostPort
    # slots the churn rounds then live inside, the mirror seeds, and
    # the compiles are paid
    port_live: list[str] = []
    for pod in gen_host_pods(max(backlog, 128), seed=1, constraints=True):
        sched.submit(pod)
    for name, pt in (("port-w0", 19998), ("port-w1", 19999)):
        sched.submit(Pod(name=name, namespace="bench", host_ports=[pt]))
        port_live.append(name)
    drain()
    mir = sched.mirror
    base_rebuilds = int(mir.ctr_rebuilds.total())
    bound0 = len(sched.binder.bindings)
    t0 = time.perf_counter()
    for k in range(rounds):
        if len(port_live) >= 2:
            # the oldest port pod terminates (informer DELETE): live
            # ports stay within the two allocated slots, so the fresh
            # port below is a same-width REMAP, never slot growth
            victim_name = port_live.pop(0)
            victim = next(
                (
                    p for p in running
                    if p.namespace == "bench" and p.name == victim_name
                ),
                None,
            )
            if victim is not None:
                running.remove(victim)
                mir.apply_pod_event("DELETED", victim)
        sched.submit(
            Pod(
                name=f"drift-{k}", namespace="bench",
                pod_affinity=[
                    PodAffinityTerm(
                        match_labels={"drift": str(k)},
                        topology_key="kubernetes.io/hostname",
                        anti=True,
                    )
                ],
            )
        )
        port_name = f"port-{k}"
        sched.submit(
            Pod(name=port_name, namespace="bench", host_ports=[20000 + k])
        )
        port_live.append(port_name)
        for pod in gen_host_pods(backlog, seed=100 + k):
            sched.submit(pod)
        drain()
    elapsed = time.perf_counter() - t0
    bound = len(sched.binder.bindings) - bound0
    ext = {key[0]: int(v) for key, v in mir.ctr_extensions._series.items()}
    reasons = {
        key[0]: int(n)
        for key, n in sorted(mir.ctr_rebuilds.breakdown().items())
    }
    return {
        "metric": f"host_loop_{n_nodes}nodes_streaming_drift",
        "drift_rounds": rounds,
        "pods_bound": bound,
        "pods_per_sec": round(bound / max(elapsed, 1e-9), 1),
        "mirror_incremental_extensions": ext,
        "mirror_full_rebuilds": int(mir.ctr_rebuilds.total()),
        "mirror_rebuild_reasons": reasons,
        # the headline: rebuilds actually paid across the drifting
        # rounds (bucket/slot crossings only — NOT one per round)
        "drift_rebuilds": int(mir.ctr_rebuilds.total()) - base_rebuilds,
        "mirror_verify_failures": int(
            mir.ctr_verify_failures._series.get((), 0)
        ),
        "final_verify_ok": bool(mir.verify()),
    }


def _fused_loop_rate() -> dict:
    """The fused-megakernel metric (host_loop_*_fused): the pipelined
    single-window drain with the fused Pallas device step explicitly ON,
    measured BESIDE an otherwise-identical unfused drain in the same
    round — so the fused/unfused engine delta (the sub-50ms-cycle
    tentpole's win) is visible in-data every round, not inferred from
    cross-round comparisons. The headline fields are the FUSED drain's;
    the unfused companion rides as unfused_* plus the p50 speedups."""
    n_pods = int(os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS))
    kw = dict(
        n_pods=n_pods, max_windows=1, pipeline_depth=1, force_device=True,
    )
    out = loop_rate(metric_suffix="_fused", fused_kernel=True, **kw)
    unfused = loop_rate(
        metric_suffix="_unfused_probe", fused_kernel=False, **kw
    )
    out["unfused_pods_per_sec"] = unfused["pods_per_sec"]
    out["unfused_engine_p50_ms"] = unfused["engine_p50_ms"]
    out["unfused_cycle_p50_ms"] = unfused["cycle_p50_ms"]
    out["fused_engine_speedup"] = round(
        unfused["engine_p50_ms"] / max(out["engine_p50_ms"], 1e-9), 3
    )
    out["fused_cycle_speedup"] = round(
        unfused["cycle_p50_ms"] / max(out["cycle_p50_ms"], 1e-9), 3
    )
    return out


def _telemetry_loop_rate(pipelined: dict | None) -> tuple[dict, dict]:
    """The full-telemetry metric (host_loop_*_telemetry): the pipelined
    drain with per-cycle spans ON (config.span_path -> Chrome-trace
    files) and a /metrics exporter being scraped concurrently — the
    everything-on production shape, measured BESIDE the telemetry-off
    pipelined baseline so the overhead is in-data. The acceptance gate
    (<5% drain-rate overhead with full telemetry on) reads
    telemetry_overhead_pct straight from the artifact; at smoke sizes
    the ratio is reported, not asserted (~ms cycles drown in jitter).

    Returns (telemetry metric, attribution metric): the drain's own
    span files are fed through trace/analyze.build_report before the
    tempdir is dropped, so host_loop_*_attribution — the per-stage
    cycle budget table, percentages summing to 100 by construction —
    rides every bench round beside the drain rate."""
    import shutil
    import tempfile

    n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    tmp = tempfile.mkdtemp(prefix="yoda-spans-bench-")
    try:
        out = loop_rate(
            n_pods=int(
                os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)
            ),
            max_windows=1,
            pipeline_depth=1,
            force_device=True,
            metric_suffix="_telemetry",
            span_path=tmp,
            scrape_metrics=True,
        )
        if pipelined and pipelined.get("pods_per_sec"):
            base = pipelined["pods_per_sec"]
            out["pipelined_pods_per_sec"] = base
            out["vs_pipelined"] = round(out["pods_per_sec"] / base, 4)
            out["telemetry_overhead_pct"] = round(
                100.0 * (1.0 - out["pods_per_sec"] / base), 2
            )
        from kubernetes_scheduler_tpu.trace.analyze import build_report

        rep = build_report(tmp)
        attrib = {
            "metric": f"host_loop_{n_nodes}nodes_attribution",
            "cycles": rep["cycles"],
            "cycle_p50_ms": rep["cycle_ms"]["p50_ms"],
            "pods_per_sec": out["pods_per_sec"],
            # per-stage share of cycle wall time (+ "other" residual),
            # summing to ~100 — the budget table the sub-50ms-cycle
            # ROADMAP item reads to pick the next bottleneck
            "attribution_pct": rep["attribution_pct"],
            "stage_p50_ms": {
                name: s["p50_ms"] for name, s in rep["stages"].items()
            },
        }
        return out, attrib
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _replay_loop_rate() -> dict:
    """The flight-recorder metric (host_loop_*_replay): run the
    pipelined host-loop drain with the cycle recorder on (trace/), then
    REPLAY the captured journal through the engine and diff bindings
    bitwise — perf numbers from a captured workload instead of a fresh
    generator, plus in-data proof that recording survives the bench
    workload and that replay reproduces production decisions exactly
    (binding_diffs MUST be 0). traced_pods_per_sec sits beside the
    host_loop_*_pipelined metric so the recorder's overhead is readable
    from the artifact (<5% is the acceptance gate)."""
    import shutil
    import tempfile

    from kubernetes_scheduler_tpu.trace.replay import replay_journal

    n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    tmp = tempfile.mkdtemp(prefix="yoda-trace-bench-")
    try:
        traced = loop_rate(
            n_pods=int(
                os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)
            ),
            max_windows=1,
            pipeline_depth=1,
            force_device=True,
            metric_suffix="_traced",
            trace_path=tmp,
        )
        rep = replay_journal(tmp, mode="serial")
        if rep.binding_diffs:
            raise RuntimeError(
                f"replay diverged from the recording: {rep.binding_diffs} "
                f"binding diffs over {rep.replayed} cycles"
            )
        return {
            "metric": f"host_loop_{n_nodes}nodes_replay",
            "cycles_replayed": rep.replayed,
            "cycles_skipped": rep.skipped,
            "binding_diffs": rep.binding_diffs,
            "pods_replayed": rep.pods_replayed,
            "pods_per_sec": round(rep.pods_replayed / max(rep.seconds, 1e-9), 1),
            # the recorder-on drain beside host_loop_*_pipelined = the
            # recorder's overhead, measured in-data
            "traced_pods_per_sec": traced["pods_per_sec"],
            "traced_cycle_p50_ms": traced["cycle_p50_ms"],
            "trace_record_seconds": traced["trace_record_seconds"],
            "trace_overhead_pct": traced["trace_overhead_pct"],
            "trace_bytes": traced["trace_bytes"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _shadow_rescore_rate() -> dict:
    """The shadow-serving metric (host_loop_*_shadow): record a
    pipelined drain with the flight recorder on, then tail the journal
    through host/shadow.ShadowScheduler under an IDENTICAL candidate
    config. Two in-data proofs ride the rate: the decision diff MUST be
    zero (same config => same bindings, the rollout-gate null
    hypothesis), and shadow_pods_per_sec / latency_ratio say whether a
    colocated shadow can keep up with the primary it is auditioning
    against (keep-up ratio >= 1 means yes)."""
    import shutil
    import tempfile

    from kubernetes_scheduler_tpu.host.shadow import ShadowScheduler
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    tmp = tempfile.mkdtemp(prefix="yoda-shadow-bench-")
    try:
        loop_rate(
            n_pods=int(
                os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)
            ),
            max_windows=1,
            pipeline_depth=1,
            force_device=True,
            metric_suffix="_shadow_recorded",
            trace_path=tmp,
        )
        shadow = ShadowScheduler(
            tmp,
            SchedulerConfig(
                batch_window=1024,
                normalizer="none",
                adaptive_dispatch=False,
                min_device_work=1,
            ),
        )
        t0 = time.perf_counter()
        summary = shadow.run()
        seconds = time.perf_counter() - t0
        shadow.close()
        if summary["bindings_changed"]:
            raise RuntimeError(
                "shadow diverged under an identical candidate config: "
                f"{summary['bindings_changed']} bindings over "
                f"{summary['records_applied']} records"
            )
        return {
            "metric": f"host_loop_{n_nodes}nodes_shadow",
            "records_rescored": summary["records_applied"],
            "bindings_changed": summary["bindings_changed"],
            "divergence_ratio": summary["divergence_ratio"],
            "pods_compared": summary["pods_compared"],
            "shadow_pods_per_sec": round(
                summary["pods_compared"] / max(seconds, 1e-9), 1
            ),
            # candidate engine wall time over the primary's recorded
            # engine time: < 1 means the shadow re-scores faster than
            # the primary produced the journal (it can tail live)
            "latency_ratio": round(summary["latency_ratio"], 3),
            "breaker_state": summary["breaker_state"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _scenario_rate(name: str, short: str) -> dict:
    """Scenario-harness metrics (sim/scenarios): one adversarial traffic
    program driven end to end through the host loop at the bench scale,
    reported beside the pipelined host-loop baseline. The drain rate is
    NOT comparable to host_loop_* (scenario traffic arrives over virtual
    ticks, not as one pre-queued backlog) — it is the round-over-round
    anchor for the scenario itself; the gang metric adds the admit rate
    (admitted / (admitted + deferred)), the all-or-nothing health
    signal."""
    from kubernetes_scheduler_tpu.sim import scenarios

    n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    intensity = float(os.environ.get("BENCH_SCENARIO_INTENSITY", "1.0"))
    summary = scenarios.run(
        name, n_nodes=n_nodes, intensity=intensity, seed=0
    )
    out = {
        "metric": f"scenario_{short}_{n_nodes}nodes",
        "scenario": name,
        "cycles": summary["cycles"],
        "pods_submitted": summary["pods_submitted"],
        "pods_bound": summary["pods_bound"],
        "pods_unschedulable": summary["pods_unschedulable"],
        "fallback_cycles": summary["fallback_cycles"],
        "pods_per_sec": summary["pods_per_sec"],
        "seconds": summary["seconds"],
    }
    admitted = summary["gangs_admitted"]
    deferred = summary["gangs_deferred"]
    if admitted or deferred:
        out.update(
            gangs_admitted=admitted,
            gangs_deferred=deferred,
            gang_pods_masked=summary["gang_pods_masked"],
            gang_admit_rate=round(
                admitted / max(admitted + deferred, 1), 4
            ),
        )
    return out


def _chaos_loop_rate() -> dict:
    """The chaos host-loop metric (host_loop_*_chaos): the SAME
    pipelined drain shape as host_loop_*_pipelined, under a
    deterministic RPC-flap FaultPlan (sim/faults.py) on the engine
    boundary — the clock is the CYCLE COUNTER, so the flap pattern is
    identical run over run. Reported beside the clean drain: the
    degraded-cycle rate, the circuit breaker's open/half-open/closed
    transition counts, and the recovery latency (wall time from a
    degradation episode's first degraded cycle back to every ladder
    rung at top with the breaker closed) p50/p99 over episodes. The
    plan quiesces with a recovery tail, so the row also asserts the
    run ENDS recovered — a chaos drain that stays degraded is a
    failure, not a number."""
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.sim.faults import (
        FaultInjector,
        FaultPlan,
        FaultWindow,
        FaultyEngine,
    )
    from kubernetes_scheduler_tpu.engine import LocalEngine
    from kubernetes_scheduler_tpu.sim.host_gen import (
        gen_host_cluster,
        gen_host_pods,
    )
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    n_pods = int(
        os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)
    )
    # window sized for enough cycles that the flap pattern and the
    # recovery tail are both visible at any BENCH_* scale
    window = max(8, n_pods // 16)
    cycles_per_drain = -(-n_pods // window)
    samples = int(os.environ.get("BENCH_LOOP_SAMPLES", "0")) or 3
    measured = samples * cycles_per_drain
    # flap over the middle of the measured cycles; quiesce with a tail
    flap_start = max(2, measured // 4)
    flap_end = max(flap_start + 4, (2 * measured) // 3)
    # flap first (retry/fallback churn), then a solid outage long
    # enough to trip the breaker (threshold 2) so the open ->
    # half-open -> closed arc is in the transition counts every run
    outage_start = float(flap_end) + 2.0
    plan = FaultPlan((
        FaultWindow(
            boundary="engine", kind="flap",
            start=float(flap_start), end=float(flap_end), period=2,
        ),
        FaultWindow(
            boundary="engine", kind="error",
            start=outage_start, end=outage_start + 3.0,
        ),
    ))
    cycle_clock = [0.0]
    injector = FaultInjector(plan, clock=lambda: cycle_clock[0])
    nodes, advisor = gen_host_cluster(n_nodes, seed=0)
    running: list = []
    sched = Scheduler(
        SchedulerConfig(
            batch_window=window,
            max_windows_per_cycle=1,
            pipeline_depth=1,
            adaptive_dispatch=False,
            min_device_work=1,
            normalizer="none",
            breaker_failure_threshold=2,
            breaker_recovery_window_s=3.0,
        ),
        advisor=advisor,
        engine=FaultyEngine(LocalEngine(), injector),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        queue_clock=lambda: cycle_clock[0],
    )
    cycles = []
    episodes = []  # recovery latency (seconds) per degradation episode
    episode_t0 = None

    def drain(measure: bool):
        nonlocal episode_t0
        seen = len(sched.binder.bindings)
        for _ in range(64):
            if len(sched.queue) == 0 and sched._prefetched is None:
                break
            m = sched.run_cycle()
            if measure:
                cycle_clock[0] += 1.0
                cycles.append(m)
                recovered = (
                    sched.ladder.fully_recovered()
                    and sched.engine_breaker.state() == "closed"
                )
                if not recovered and episode_t0 is None:
                    episode_t0 = time.perf_counter()
                elif recovered and episode_t0 is not None:
                    episodes.append(time.perf_counter() - episode_t0)
                    episode_t0 = None
            for b in sched.binder.bindings[seen:]:
                running.append(b.pod)
            seen = len(sched.binder.bindings)

    for pod in gen_host_pods(n_pods, seed=1):
        sched.submit(pod)
    drain(measure=False)  # warmup: compiles, no injected clock ticks
    for seed in range(2, 2 + samples):
        for pod in gen_host_pods(n_pods, seed=seed):
            sched.submit(pod)
        drain(measure=True)
    # recovery tail: the sample drains already advanced the cycle
    # clock through BOTH fault windows (measured cycles span the plan
    # by construction), so these trailing drains idle-advance past the
    # plan's end and give the half-open probe + ladder climb traffic
    # to land on
    for tail_seed in (90, 91):
        cycle_clock[0] = max(cycle_clock[0], plan.last_end()) + 4.0
        for pod in gen_host_pods(window, seed=tail_seed):
            sched.submit(pod)
        drain(measure=True)
    # an episode still open at the end never recovered: count it
    # separately instead of poisoning the percentiles (float('inf')
    # would serialize as bare `Infinity` — invalid JSON on the one
    # line that reports the failure)
    unrecovered = int(episode_t0 is not None)
    bound = sum(c.pods_bound for c in cycles)
    lat = [c.cycle_seconds for c in cycles]
    degraded = sum(1 for c in cycles if c.degraded or c.used_fallback)
    rec_ms = sorted(1e3 * e for e in episodes)
    out = {
        "metric": f"host_loop_{n_nodes}nodes_chaos",
        "cycles": len(cycles),
        "pods_bound": bound,
        "pods_per_sec": round(bound / max(sum(lat), 1e-9), 1),
        "cycle_p50_ms": round(1e3 * float(np.percentile(lat, 50)), 2),
        "fallback_cycles": int(sum(c.used_fallback for c in cycles)),
        "degraded_cycles": degraded,
        "degraded_cycle_rate": round(degraded / max(len(cycles), 1), 4),
        "faults_injected": injector.summary(),
        "breaker_transitions": dict(
            sched.engine_breaker.transition_counts
        ),
        "breaker_state": sched.engine_breaker.state(),
        "recovery_episodes": len(episodes),
        "unrecovered_episodes": unrecovered,
        "recovery_latency_ms_p50": (
            round(float(np.percentile(rec_ms, 50)), 2) if rec_ms else 0.0
        ),
        "recovery_latency_ms_p99": (
            round(float(np.percentile(rec_ms, 99)), 2) if rec_ms else 0.0
        ),
        "recovered": (
            sched.ladder.fully_recovered()
            and sched.engine_breaker.state() == "closed"
        ),
    }
    return out


class _ChurnAdvisor:
    """Metric-churn wrapper over a StaticAdvisor: every fetch perturbs a
    FIXED-SIZE rotating slice of nodes' utilization series. The churn
    size is independent of the cluster size, so the resident-delta
    payload it induces (changed util rows) is too — the workload the
    flat-bytes gate measures: per-cycle host->device delta bytes must
    not grow with node count."""

    def __init__(self, base, node_names, churn_nodes: int, seed: int = 7):
        from kubernetes_scheduler_tpu.host.advisor import NodeUtil

        self._NodeUtil = NodeUtil
        self._base = base
        self._names = list(node_names)
        self._k = min(churn_nodes, len(self._names))
        self._pos = 0
        self._rng = np.random.default_rng(seed)

    def fetch(self):
        utils = dict(self._base.fetch())
        self._changed = {}
        for i in range(self._k):
            name = self._names[(self._pos + i) % len(self._names)]
            u = utils[name]
            utils[name] = self._NodeUtil(
                cpu_pct=float(min(u.cpu_pct + self._rng.uniform(0.1, 2.0), 100.0)),
                mem_pct=u.mem_pct,
                disk_io=float(min(u.disk_io + self._rng.uniform(0.01, 0.5), 50.0)),
                net_up=u.net_up,
                net_down=u.net_down,
            )
            self._changed[name] = utils[name]
        self._pos = (self._pos + self._k) % max(len(self._names), 1)
        self._base.utils = utils  # churn accumulates across cycles
        return utils

    def fetch_changed(self):
        """The advisor-coalescing surface (host/mirror events): the
        churn advisor knows EXACTLY which nodes it perturbed, so the
        changed-node drain is O(churn) with no diff pass at all."""
        self.fetch()
        return dict(getattr(self, "_changed", {}))


def loop_rate(
    *,
    n_pods: int | None = None,
    n_nodes: int | None = None,
    max_windows: int = DEFAULT_LOOP_WINDOWS,
    pipeline_depth: int = 0,
    force_device: bool = False,
    resident: bool = False,
    sharded: bool = False,
    churn_nodes: int = 0,
    metric_suffix: str = "",
    trace_path: str | None = None,
    span_path: str | None = None,
    scrape_metrics: bool = False,
    fused_kernel: bool | None = None,
    mirror: bool = False,
    slo_ms: float = 0.0,
) -> dict:
    """END-TO-END host loop at the north-star scale: queue pop -> snapshot
    build -> device program -> binds, through host.Scheduler on a simulated
    cluster (the BASELINE.md latency metric: per-cycle bind latency p50/p99
    including all host-side work, not just the device step).

    max_windows is SchedulerConfig.max_windows_per_cycle: how deep a
    pending backlog one cycle pops into a single device dispatch. The
    default (8) is the deployed default; the deep-backlog variant (16)
    amortizes the device round-trip over twice the pods — higher
    throughput, higher per-cycle latency, both reported honestly.

    pipeline_depth=1 measures the double-buffered host loop (one window
    per cycle, the engine call in flight while the host pops and
    prebuilds the next window) — the serialized-host-work recovery the
    host_loop_*_pipelined metric exists to capture.

    force_device pins the engine path (adaptive_dispatch off,
    min_device_work 1): at single-window shapes the adaptive model can
    legitimately route scalar (the C++ cycle beats a tunneled device
    round-trip below the crossover), which would measure the scalar
    path under a device-pipelining label — the overlap metric and the
    routing dial are separate questions."""
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster, gen_host_pods
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    if n_nodes is None:
        n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    if n_pods is None:
        # BENCH_LOOP_PODS names the DEFAULT (8-window) backlog size; the
        # deep variant scales it so an override keeps the configurations
        # proportional (a flat override would quietly turn the "deep"
        # run into the default workload under a different label)
        n_pods = (
            int(os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS))
            * max_windows
            // DEFAULT_LOOP_WINDOWS
        )
    # ONE scheduler, two backlogs: the first compiles the device
    # program(s) and warms the steady-state caches a resident scheduler
    # accumulates (request-row/flag memos, the engine's uniform-leaf
    # device constants); the second — fresh pods, with the first
    # backlog's binds as the running set — is the measured steady state,
    # paying the real per-cycle costs (snapshot re-sum over every
    # running pod, cold pod-side caches for newly arrived pods).
    nodes, advisor = gen_host_cluster(n_nodes, seed=0)
    if churn_nodes:
        advisor = _ChurnAdvisor(
            advisor, [nd.name for nd in nodes], churn_nodes
        )
    running: list = []
    extra = (
        {"adaptive_dispatch": False, "min_device_work": 1}
        if force_device
        else {}
    )
    if sharded:
        extra["sharded_engine"] = True
    # streaming state ingestion: the event-sourced snapshot mirror
    # replaces the per-cycle rebuild; the churn advisor's fetch_changed
    # feeds utilization events and the scheduler self-applies its binds
    # as pod events. Pinned EXPLICITLY both ways: the config default is
    # mirror-on, but the non-mirror rows exist to measure the rebuild
    # loop the mirror is compared against
    extra["snapshot_mirror"] = mirror
    if slo_ms:
        # the live SLO watchdog rides the measured drain: breaches are
        # counted (slo_breaches_total{path}) and reported beside the
        # percentile they gate — the <50ms claim with its own alarm on
        extra["cycle_slo_ms"] = slo_ms
    if fused_kernel is not None:
        # the fused/unfused A-B knob (host_loop_*_fused): everything
        # else identical, only the feature gate moves
        from kubernetes_scheduler_tpu.utils.config import FeatureGates

        extra["feature_gates"] = FeatureGates(fused_kernel=fused_kernel)
    sched = Scheduler(
        SchedulerConfig(
            batch_window=1024,
            normalizer="none",
            max_windows_per_cycle=max_windows,
            pipeline_depth=pipeline_depth,
            resident_state=resident,
            trace_path=trace_path,
            span_path=span_path,
            **extra,
        ),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )
    # full-telemetry shape: a live exporter being scraped mid-drain (the
    # /metrics contention is part of what the telemetry metric measures)
    exporter = None
    scrape_stop = None
    scrapes = [0]
    if scrape_metrics:
        import threading
        import urllib.request

        from kubernetes_scheduler_tpu.host.observe import MetricsExporter

        exporter = MetricsExporter(sched)
        mport = exporter.serve(0, host="127.0.0.1")
        scrape_stop = threading.Event()

        def _scrape_loop():
            while not scrape_stop.is_set():
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/metrics", timeout=5
                    ) as r:
                        r.read()
                    scrapes[0] += 1
                except Exception:
                    pass
                scrape_stop.wait(0.05)

        threading.Thread(target=_scrape_loop, daemon=True).start()

    def drain() -> tuple[list, float]:
        t0 = time.perf_counter()
        out = []
        seen = len(sched.binder.bindings)
        for _ in range(64):
            # a pipelined scheduler may hold a prefetched window outside
            # the queue — the drain is not done until it dispatched too
            if len(sched.queue) == 0 and sched._prefetched is None:
                break
            out.append(sched.run_cycle())
            # feed binds back as running pods, so later cycles pay the
            # real steady-state snapshot cost and capacity accrues
            for b in sched.binder.bindings[seen:]:
                running.append(b.pod)
            seen = len(sched.binder.bindings)
        return out, time.perf_counter() - t0

    for pod in gen_host_pods(n_pods, seed=1):
        sched.submit(pod)
    drain()  # warmup backlog (compiles; populates `running`)
    # recorder time spent on the warmup drain must not count against
    # the measured cycles' overhead ratio
    trace_warmup_s = (
        sched.recorder.seconds_spent if sched.recorder is not None else 0.0
    )
    cycles = []
    # enough measured backlogs for a STABLE p50/p99: the single-dispatch
    # shapes (serial 8-window, deep16w) drain one cycle per backlog, so
    # the old fixed 3 samples left 3-cycle percentiles — meaningless
    # order statistics the sub-50ms gate cannot be judged on. Target
    # >= 10 cycles (BENCH_LOOP_SAMPLES overrides), floor 3 samples:
    # the tunnel's per-RPC latency is bimodal either way.
    window_cap = 1024 * max(1, max_windows)
    cycles_per_drain = max(1, -(-n_pods // min(max(n_pods, 1), window_cap)))
    samples = int(os.environ.get("BENCH_LOOP_SAMPLES", "0")) or max(
        3, -(-10 // cycles_per_drain)
    )
    for seed in range(2, 2 + samples):
        for pod in gen_host_pods(n_pods, seed=seed):
            sched.submit(pod)
        got, _ = drain()
        cycles.extend(got)
    if scrape_stop is not None:
        scrape_stop.set()
    if exporter is not None:
        exporter.close()
    if sched.recorder is not None:
        sched.recorder.close()
    if sched.spans is not None:
        sched.spans.close()
    bound = sum(c.pods_bound for c in cycles)
    lat = [c.cycle_seconds for c in cycles]
    eng = [c.engine_seconds for c in cycles]
    p50 = float(np.percentile(lat, 50))
    rates = [
        c.pods_bound / c.cycle_seconds
        for c in cycles
        if c.cycle_seconds > 0
    ]
    out = {
        "metric": f"host_loop_{n_nodes}nodes{metric_suffix}",
        "cycles": len(cycles),
        "pods_bound": bound,
        # HEADLINE = aggregate throughput (all binds / all cycle time),
        # the same definition as BASELINE.md's rates — comparable across
        # rounds. The p50 companion is the per-cycle median, robust to
        # the dev tunnel's bimodal per-RPC latency (a colocated sidecar
        # does not pay those outlier RPCs) but NOT comparable to an
        # aggregate baseline.
        "pods_per_sec": round(bound / max(sum(lat), 1e-9), 1),
        "pods_per_sec_p50": round(float(np.percentile(rates, 50)), 1),
        "cycle_p50_ms": round(1e3 * p50, 2),
        "cycle_p99_ms": round(1e3 * float(np.percentile(lat, 99)), 2),
        # device dispatch+compute+sync; on a tunneled dev chip the per-RPC
        # round-trip dominates — a colocated sidecar pays ~ms
        "engine_p50_ms": round(1e3 * float(np.percentile(eng, 50)), 2),
        "fallback_cycles": int(sum(c.used_fallback for c in cycles)),
        # pipelined-loop observability (zeros on the serial metrics):
        # host work hidden under in-flight engine calls, and speculative
        # discards — the acceptance gate is cycle_p50 approaching
        # engine_p50 with flushes staying ~0 on a churn-free drain
        "host_overlap_p50_ms": round(
            1e3 * float(np.percentile(
                [c.host_overlap_seconds for c in cycles], 50
            )), 2,
        ),
        "pipeline_flushes": int(sum(c.pipeline_flushes for c in cycles)),
    }
    if slo_ms:
        out["cycle_slo_ms"] = slo_ms
        out["slo_breaches"] = int(sched.slo_breaches)
    if sched.recorder is not None:
        # the recorder's own wall time vs the drain's cycle time — the
        # direct <5%-overhead evidence (recording runs AFTER each
        # cycle's bookkeeping, so cycle_seconds cannot show it)
        spent = sched.recorder.seconds_spent - trace_warmup_s
        out["trace_record_seconds"] = round(spent, 4)
        out["trace_overhead_pct"] = round(
            100.0 * spent / max(sum(lat), 1e-9), 2
        )
        out["trace_bytes"] = sched.recorder.bytes_written
    if sched.spans is not None:
        out["spans_written"] = sched.spans.spans_written
        out["span_bytes"] = sched.spans.bytes_written
        out["spans_dropped"] = sched.spans.spans_dropped
    if scrape_metrics:
        out["metrics_scrapes"] = scrapes[0]
    if resident:
        # resident-state observability: delta hit rate and the snapshot
        # payload actually shipped. snapshot_upload_bytes is the full
        # per-cycle payload MINUS what the deltas avoided — measured
        # against the same cycles, so the win is in-data, not inferred.
        from kubernetes_scheduler_tpu.engine import snapshot_nbytes

        deltas = int(sum(c.delta_uploads for c in cycles))
        fulls = int(sum(c.full_uploads for c in cycles))
        saved = int(sum(c.delta_bytes_saved for c in cycles))
        snap_bytes = snapshot_nbytes(
            sched.builder.build_snapshot(
                nodes, sched.advisor.fetch(), running, ephemeral=True
            )
        )
        out.update(
            delta_uploads=deltas,
            full_uploads=fulls,
            delta_hit_rate=round(deltas / max(deltas + fulls, 1), 4),
            delta_bytes_saved=saved,
            snapshot_upload_bytes=(deltas + fulls) * snap_bytes - saved,
        )
    if mirror and sched.mirror is not None:
        # streaming-ingestion observability: events the mirror applied
        # (by kind), flush-to-full rebuilds, and verify outcomes —
        # events_per_cycle is the O(events) claim's in-data evidence
        ev = {k[0]: int(v) for k, v in sched.mirror.ctr_events._series.items()}
        out["mirror_events"] = ev
        out["mirror_events_per_cycle"] = round(
            sum(ev.values()) / max(len(cycles), 1), 2
        )
        out["mirror_full_rebuilds"] = int(sched.mirror.ctr_rebuilds.total())
        out["mirror_rebuild_reasons"] = {
            key[0]: int(n)
            for key, n in sorted(sched.mirror.ctr_rebuilds.breakdown().items())
        }
        out["mirror_verify_failures"] = int(
            sched.mirror.ctr_verify_failures._series.get((), 0)
        )
    if sharded:
        # mesh-sharded observability: the per-cycle routed delta payload
        # (summed over shards — the total host->device bytes a delta
        # cycle ships) and its worst single shard. The flat-bytes gate
        # compares shard_delta_bytes_per_cycle across node scales.
        delta_cycles = [c for c in cycles if c.shard_delta_bytes]
        per_cycle = [float(sum(c.shard_delta_bytes)) for c in delta_cycles]
        out["mesh_devices"] = int(getattr(sched.engine, "n_shards", 1))
        out["sharded_cycles"] = int(sum(c.sharded_cycles for c in cycles))
        out["shard_delta_bytes_per_cycle"] = (
            round(float(np.mean(per_cycle)), 1) if per_cycle else 0.0
        )
        out["shard_delta_bytes_max_shard"] = (
            int(max(max(c.shard_delta_bytes) for c in delta_cycles))
            if delta_cycles
            else 0
        )
    return out


def _sharded_loop_rate() -> list[dict]:
    """The 100k-node mesh-sharded host loop (host_loop_100000nodes):
    config.sharded_engine + resident_state on a metric-churn workload
    (a fixed-size rotating slice of nodes changes utilization every
    fetch — the workload whose resident deltas must stay FLAT as the
    cluster grows). Emits the 100k row plus a reference row at a tenth
    the nodes; the 100k row carries flat_bytes_ratio = its per-cycle
    routed delta payload over the reference's — the gate is <= 2x
    (asserted at compressed scale in tests/test_bench_smoke.py; at
    real scale the ratio rides the artifact)."""
    n_nodes = int(os.environ.get("BENCH_SHARDED_NODES", 100_000))
    n_pods = int(
        os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)
    )
    churn = int(os.environ.get("BENCH_CHURN_NODES", 256))
    kw = dict(
        n_pods=n_pods, max_windows=1, pipeline_depth=1, force_device=True,
        resident=True, sharded=True, churn_nodes=churn,
    )
    ref = loop_rate(
        n_nodes=max(n_nodes // 10, 8), metric_suffix="_sharded_ref", **kw
    )
    out = loop_rate(n_nodes=n_nodes, **kw)
    out["ref_shard_delta_bytes_per_cycle"] = ref[
        "shard_delta_bytes_per_cycle"
    ]
    if ref["shard_delta_bytes_per_cycle"]:
        out["flat_bytes_ratio"] = round(
            out["shard_delta_bytes_per_cycle"]
            / ref["shard_delta_bytes_per_cycle"],
            3,
        )
    # the combined scale row: streaming ingestion AND the mesh-sharded
    # resident engine on the same drain — the mirror's O(events) emits
    # feed shard-routed deltas, so the 100k-node cycle pays neither the
    # full host rebuild nor the full upload
    stream = loop_rate(
        n_nodes=n_nodes, metric_suffix="_streaming", mirror=True, **kw
    )
    return [ref, out, stream]


def _replica_loop_rate() -> list[dict]:
    """Replicated scheduler fleet over the partitioned queue
    (host_loop_*nodes_replicas): 1 vs 2 vs 4 FULL Schedulers, each
    draining its crc32(namespace) partition against the shared
    first-bind-wins BindTable (host/replica.py — the checked
    `replica-bind` protocol).

    Scaling phase: each fleet drains the SAME namespaced backlog
    sequentially (ReplicaFleet.run_sequential); the reported aggregate
    is total_bound / max(per-replica busy seconds) — N single-host
    processes run their partitions in true parallel, one GIL cannot, so
    the max-busy quotient is the honest deployment-topology number. The
    per-cycle dispatch shape is held CONSTANT across fleet sizes
    (max_windows_per_cycle tuned so every replica pops full windows):
    scaling then measures the partitioned drain's parallelism, not
    dispatch-shape effects.

    Conflict phase: the deterministic 2-replica storm — the pipelined
    prefetch slot holds replica 0's overlap window popped-but-unbound
    across the round replica 1 binds its copies, so replica 0's bind
    loses the CAS (bind_lose: requeue + 409-drop) and its next pop
    retires the requeued copy via drop_bound. Every loser resolves,
    zero double binds, requeue latency in-data."""
    from kubernetes_scheduler_tpu.host.queue import namespace_partition
    from kubernetes_scheduler_tpu.host.replica import ReplicaFleet
    from kubernetes_scheduler_tpu.host.types import Container, Pod
    from kubernetes_scheduler_tpu.sim.host_gen import (
        gen_host_cluster,
        gen_host_pods,
    )
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    n_nodes = int(os.environ.get("BENCH_LOOP_NODES", 4000))
    n_pods = int(os.environ.get("BENCH_REPLICA_PODS", 0)) or int(
        os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)
    )
    samples = int(os.environ.get("BENCH_LOOP_SAMPLES", "0")) or 3
    fleet_sizes = (1, 2, 4)
    # window sizing: the LARGEST fleet must still pop full dispatches,
    # so cap the per-cycle dispatch at (backlog / max_replicas) windows
    # — at the default 8192-pod backlog that is 2 windows/cycle: r=1
    # runs 4 cycles, r=2 runs 2/replica, r=4 runs 1/replica, all the
    # same dispatch shape
    max_windows = max(1, min(DEFAULT_LOOP_WINDOWS,
                             n_pods // (max(fleet_sizes) * 1024)))
    # one namespace per crc32 % 4 residue: round-robin over these four
    # is exactly balanced at every fleet size (residues alternate mod 2,
    # so the mod-2 split inherits the balance)
    by_res: dict = {}
    i = 0
    while len(by_res) < 4:
        ns = f"tenant-{i}"
        by_res.setdefault(namespace_partition(ns, 4), ns)
        i += 1
    tenants = [by_res[r] for r in range(4)]

    nodes, advisor = gen_host_cluster(n_nodes, seed=0)
    rows: list = []
    base_rate = None
    double_binds = 0
    for n_replicas in fleet_sizes:
        running: list = []
        fleet = ReplicaFleet(
            SchedulerConfig(
                batch_window=1024,
                normalizer="none",
                max_windows_per_cycle=max_windows,
                adaptive_dispatch=False,
                min_device_work=1,
            ),
            n_replicas=n_replicas,
            advisor_factory=lambda i: advisor,
            list_nodes=lambda: nodes,
            list_running_pods=lambda: running,
        )
        cursors = [0] * n_replicas

        def absorb():
            # feed binds back as running pods (per-scheduler cursors:
            # fleet.bindings concatenates, so a flat cursor would skew)
            for k, sched in enumerate(fleet.schedulers):
                bs = sched.binder.bindings
                running.extend(b.pod for b in bs[cursors[k]:])
                cursors[k] = len(bs)

        def backlog(seed_):
            # per-seed unique names: the bind table keys on
            # namespace/name, and a re-run of "pod-0" would be fenced
            # off as already-bound
            for j, pod in enumerate(gen_host_pods(n_pods, seed=seed_)):
                pod.name = f"{pod.name}-s{seed_}"
                pod.namespace = tenants[j % 4]
                fleet.submit(pod)

        backlog(1)
        fleet.run_sequential()  # warmup: compiles; populates `running`
        absorb()
        bound0 = fleet.evidence()["total_binds"]
        agg_s = 0.0
        busy = [0.0] * n_replicas
        for s in range(2, 2 + samples):
            backlog(s)
            ev = fleet.run_sequential()
            absorb()
            agg_s += ev["aggregate_drain_seconds"]
            busy = [a + b for a, b in zip(busy, ev["replica_busy_seconds"])]
        ev = fleet.evidence()
        bound = ev["total_binds"] - bound0
        rate = bound / max(agg_s, 1e-9)
        if base_rate is None:
            base_rate = rate
        double_binds = max(double_binds, ev["double_binds"])
        rows.append({
            "metric": f"host_loop_{n_nodes}nodes_replicas{n_replicas}",
            "replicas": n_replicas,
            "pods_bound": bound,
            "aggregate_pods_per_sec": round(rate, 1),
            "scaling_x": round(rate / max(base_rate, 1e-9), 2),
            "aggregate_drain_seconds": round(agg_s, 3),
            "replica_busy_seconds": [round(b, 3) for b in busy],
            "binds_per_replica": ev["binds_per_replica"],
            "double_binds": ev["double_binds"],
        })

    # -- shared-engine fleet (ONE resident sidecar, coalesced dispatch) --
    # Same backlog/accounting model as the private rows — N single-host
    # processes drain their partitions in true parallel, so the quotient
    # is max per-replica busy seconds — with one refinement: the fused
    # coalesced execute is ONE device invocation serving every
    # participant, so its wall time is apportioned evenly across the
    # requests it carried (each replica's private-engine alternative
    # would have paid a whole dispatch alone; sharing it IS the win this
    # row measures). Host-side dispatch/complete work stays charged to
    # the replica that did it.
    from kubernetes_scheduler_tpu.engine import snapshot_nbytes

    shared_rows: list = []
    shared_base = None
    for n_replicas in (1, 4):
        running_s: list = []
        fleet = ReplicaFleet(
            SchedulerConfig(
                batch_window=1024,
                normalizer="none",
                max_windows_per_cycle=max_windows,
                adaptive_dispatch=False,
                min_device_work=1,
                pipeline_depth=1,
                shared_engine=True,
            ),
            n_replicas=n_replicas,
            advisor_factory=lambda i: advisor,
            list_nodes=lambda: nodes,
            list_running_pods=lambda: running_s,
        )
        pool = fleet.engine_pool
        cursors_s = [0] * n_replicas

        def absorb_s():
            for k, sched in enumerate(fleet.schedulers):
                bs = sched.binder.bindings
                running_s.extend(b.pod for b in bs[cursors_s[k]:])
                cursors_s[k] = len(bs)

        def backlog_s(seed_):
            for j, pod in enumerate(gen_host_pods(n_pods, seed=seed_)):
                pod.name = f"{pod.name}-s{seed_}"
                pod.namespace = tenants[j % 4]
                fleet.submit(pod)

        round_walls: list = []
        round_bound: list = []
        rounds = [0]

        def drain_s(measure: bool):
            for _ in range(256):
                live = [
                    (k, s) for k, s in enumerate(fleet.schedulers)
                    if len(s.queue) or s._prefetched is not None
                ]
                if not live:
                    break
                rounds[0] += measure
                bound_before = sum(
                    len(s.binder.bindings) for s in fleet.schedulers
                )
                exec0 = pool.execute_seconds
                charge = {}
                handles = []
                for k, s in live:
                    t0 = time.perf_counter()
                    handles.append((k, s.run_cycle_split()))
                    charge[k] = time.perf_counter() - t0
                t_complete = {}
                for k, h in handles:
                    t0 = time.perf_counter()
                    h.complete()
                    t_complete[k] = time.perf_counter() - t0
                dev = pool.execute_seconds - exec0
                if measure:
                    # the fused execute landed inside ONE leader's
                    # complete(): strip it there, then charge every
                    # participant an even share of the shared dispatch
                    lead = max(t_complete, key=t_complete.get)
                    t_complete[lead] = max(t_complete[lead] - dev, 0.0)
                    share = dev / max(len(handles), 1)
                    for k, _ in handles:
                        charge[k] += t_complete[k] + share
                    round_walls.append(max(charge.values()))
                    round_bound.append(
                        sum(len(s.binder.bindings) for s in fleet.schedulers)
                        - bound_before
                    )
                absorb_s()

        backlog_s(1)
        drain_s(False)  # warmup: compiles; populates `running_s`
        # second warmup backlog: the first round's replica snapshots are
        # identical (zero-delta elements); once the mirrors diverge the
        # fleet program's element structure carries real deltas — a
        # DIFFERENT jit signature whose compile must not land measured
        backlog_s(99)
        drain_s(False)
        bound0 = fleet.evidence()["total_binds"]
        st0 = pool.stats()
        for s in range(2, 2 + samples):
            backlog_s(s)
            drain_s(True)
        ev = fleet.evidence()
        st = pool.stats()
        bound = ev["total_binds"] - bound0
        # rate from the MEDIAN round (same reasoning as the host-loop
        # p50 companions): delta row buckets occasionally cross a
        # power-of-two during measured rounds, and that round's one-time
        # XLA recompile is a cache event, not the steady-state cost the
        # scaling gate compares
        wall_p50 = float(np.percentile(round_walls, 50))
        bound_p50 = float(np.percentile(round_bound, 50))
        rate = bound_p50 / max(wall_p50, 1e-9)
        if shared_base is None:
            shared_base = rate
        dispatches = st["device_dispatches"] - st0["device_dispatches"]
        shared_bytes = sum(st["upload_bytes"].values()) - sum(
            st0["upload_bytes"].values()
        )
        # what the SAME measured traffic costs with private engines: one
        # full snapshot upload per replica-dispatch (the non-resident
        # fleet rows above device_put the whole snapshot every cycle)
        s0 = fleet.schedulers[0]
        snap_bytes = snapshot_nbytes(
            s0.builder.build_snapshot(
                nodes, s0.advisor.fetch(), running_s, ephemeral=True
            )
        )
        # one dispatch per live replica-round under private engines
        private_bytes = rounds[0] * n_replicas * snap_bytes
        row = {
            "metric": f"host_loop_{n_nodes}nodes_replicas{n_replicas}_shared",
            "replicas": n_replicas,
            "pods_bound": bound,
            "aggregate_pods_per_sec": round(rate, 1),
            "scaling_x": round(rate / max(shared_base, 1e-9), 2),
            "round_wall_p50_ms": round(1e3 * wall_p50, 2),
            "rounds": rounds[0],
            "device_dispatches": dispatches,
            "dispatches_per_round": round(dispatches / max(rounds[0], 1), 2),
            "coalesced_dispatches": st["coalesced_dispatches"]
            - st0["coalesced_dispatches"],
            "uploads": {
                k: st["uploads"][k] - st0["uploads"][k]
                for k in ("full", "delta", "dedup")
            },
            # per-fleet bytes actually shipped vs what N private engines
            # ship for the same traffic — the <= ~1/N dedupe gate
            "snapshot_upload_bytes": shared_bytes,
            "private_engine_upload_bytes": private_bytes,
            "upload_bytes_vs_private": round(
                shared_bytes / max(private_bytes, 1), 4
            ),
            "double_binds": ev["double_binds"],
        }
        if n_replicas == 4:
            row["scaling_x_4"] = row["scaling_x"]
        shared_rows.append(row)

    # -- conflict storm (deterministic; evidence for the headline row) --
    ns0 = next(
        f"tenant-{i}" for i in range(64)
        if namespace_partition(f"tenant-{i}", 2) == 0
    )
    storm_running: list = []
    storm = ReplicaFleet(
        SchedulerConfig(
            batch_window=32,
            normalizer="none",
            max_windows_per_cycle=1,
            pipeline_depth=1,
            adaptive_dispatch=False,
            min_device_work=1,
        ),
        n_replicas=2,
        advisor_factory=lambda i: advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: storm_running,
    )

    def _storm_pod(name, prio):
        return Pod(
            name=name,
            namespace=ns0,
            labels={"scv/priority": str(prio)},
            containers=[Container(
                requests={"cpu": 100.0, "memory": float(2**28)}
            )],
        )

    n_overlap = 8
    for j in range(32):  # filler: replica 0 binds these first...
        storm.submit(_storm_pod(f"filler-{j}", 10))
    for j in range(n_overlap):  # ...while PREFETCHING the overlap window
        storm.submit_overlap(_storm_pod(f"overlap-{j}", 5))
    for _ in range(64):  # round-robin cycles (the scenario runner's drain)
        progressed = False
        active = False
        for sched in storm.schedulers:
            if len(sched.queue) == 0 and sched._prefetched is None:
                continue
            active = True
            m = sched.run_cycle()
            if m.pods_bound > 0 or m.pods_dropped > 0:
                progressed = True
        if not active or not progressed:
            break
    for sched in storm.schedulers:
        sched.drain_pipeline()
    sev = storm.evidence()

    # -- shared-engine storm: the same deterministic conflict program
    # through ONE pooled engine — under contention the fleet must still
    # resolve every loser (no pod lost, no double bind) while the pool
    # coalesces the per-tick dispatches below one-per-replica
    storm2_running: list = []
    storm2 = ReplicaFleet(
        SchedulerConfig(
            batch_window=32,
            normalizer="none",
            max_windows_per_cycle=1,
            pipeline_depth=1,
            adaptive_dispatch=False,
            min_device_work=1,
            shared_engine=True,
        ),
        n_replicas=2,
        advisor_factory=lambda i: advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: storm2_running,
    )
    for j in range(32):
        storm2.submit(_storm_pod(f"filler-{j}", 10))
    for j in range(n_overlap):
        storm2.submit_overlap(_storm_pod(f"overlap-{j}", 5))
    storm_ticks = 0
    for _ in range(64):
        live = [
            s for s in storm2.schedulers
            if len(s.queue) or s._prefetched is not None
        ]
        if not live:
            break
        storm_ticks += 1
        handles = [s.run_cycle_split() for s in live]
        progressed = False
        for h in handles:
            m = h.complete()
            progressed |= m.pods_bound > 0 or m.pods_dropped > 0
        if not progressed:
            break
    for sched in storm2.schedulers:
        sched.drain_pipeline()
    sev2 = storm2.evidence()
    st2 = storm2.engine_pool.stats()

    head = {
        "metric": f"host_loop_{n_nodes}nodes_replicas",
        # HEADLINE = aggregate-throughput scaling at 2 replicas with
        # zero double binds (the acceptance gate reads scaling_x_2 and
        # double_binds off this row)
        "scaling_x_2": rows[1]["scaling_x"],
        "scaling_x_4": rows[2]["scaling_x"],
        "aggregate_pods_per_sec": {
            str(r["replicas"]): r["aggregate_pods_per_sec"] for r in rows
        },
        "double_binds": max(double_binds, sev["double_binds"]),
        # storm accounting: 32 filler + 8 overlap must bind exactly
        # once each — every overlap loser resolved, never a lost pod
        "storm_overlap_pods": n_overlap,
        "bind_conflicts": sev["bind_conflicts_total"],
        "conflict_rate": round(
            sev["bind_conflicts_total"] / n_overlap, 2
        ),
        "pods_discarded": sev["pods_discarded"],
        "pods_lost": 32 + n_overlap - sev["total_binds"],
        "requeue_latency_count": sev["requeue_latency_count"],
        "requeue_latency_mean_ms": round(
            1e3 * sev["requeue_latency_mean_s"], 2
        ),
        "requeue_latency_max_ms": round(
            1e3 * sev["requeue_latency_max_s"], 2
        ),
        # shared-engine storm: contention semantics intact (no pod lost,
        # no double bind, every loser resolved) while the pool coalesces
        # below one dispatch per replica per tick — the <N gate
        "shared_storm_double_binds": sev2["double_binds"],
        "shared_storm_pods_lost": 32 + n_overlap - sev2["total_binds"],
        "shared_storm_bind_conflicts": sev2["bind_conflicts_total"],
        "shared_storm_ticks": storm_ticks,
        "shared_storm_device_dispatches": st2["device_dispatches"],
        "shared_storm_dispatches_per_tick": round(
            st2["device_dispatches"] / max(storm_ticks, 1), 2
        ),
        "shared_storm_coalesced_dispatches": st2["coalesced_dispatches"],
    }
    return rows + shared_rows + [head]


def _sharded_throughput() -> dict:
    """The 100k-node engine headline (scheduling_throughput_100000nodes):
    the whole 50k-pod backlog as ONE mesh-sharded device program
    (make_sharded_windows_fn — the node axis sharded over every visible
    device, capacity/affinity carries threaded between windows on
    device), measured pipelined like tpu_rate. The ROADMAP's "millions
    of users" scale step: 100k nodes x 50k pending pods in one
    device-resident assignment problem."""
    import jax
    from kubernetes_scheduler_tpu.engine import stack_windows
    from kubernetes_scheduler_tpu.parallel import (
        make_mesh,
        make_sharded_windows_fn,
        sharded_device_count,
    )
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods
    from kubernetes_scheduler_tpu.utils.padding import pad_pod_batch

    n_nodes = int(os.environ.get("BENCH_SHARDED_NODES", 100_000))
    n_pods = int(os.environ.get("BENCH_SHARDED_PODS", 50_000))
    window = min(WINDOW, max(8, n_pods))
    d = sharded_device_count()
    n_nodes -= n_nodes % d  # keep the node axis mesh-divisible
    mesh = make_mesh(d)
    snapshot = gen_cluster(n_nodes, seed=0)
    pods = gen_pods(n_pods, seed=1)
    n_padded = -(-n_pods // window) * window
    from jax.sharding import NamedSharding, PartitionSpec as P
    from kubernetes_scheduler_tpu.parallel.mesh import NODE_AXIS

    node = NamedSharding(mesh, P(NODE_AXIS))
    rep = NamedSharding(mesh, P())
    snapshot = jax.device_put(
        snapshot, type(snapshot)(*[node] * len(snapshot))
    )
    pods_w_host = stack_windows(pad_pod_batch(pods, n_padded), window)
    pods_w = jax.device_put(
        pods_w_host, type(pods_w_host)(*[rep] * len(pods_w_host))
    )
    fn = make_sharded_windows_fn(
        mesh, assigner="auction", normalizer="none", fused=FUSED,
        auction_price_frac=PRICE_FRAC,
    )
    out = fn(snapshot, pods_w)
    assigned = int(out.n_assigned)
    if assigned == 0:
        raise RuntimeError("sharded benchmark scheduled zero pods")
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(snapshot, pods_w)
    if int(out.n_assigned) <= 0:
        raise RuntimeError("timed sharded run scheduled zero pods")
    dt = time.perf_counter() - t0
    rate = REPS * n_pods / dt
    return {
        "metric": f"scheduling_throughput_{n_nodes}nodes",
        "value": round(rate, 1),
        "unit": "pods/s",
        "mesh_devices": d,
        "pods": n_pods,
        "assigned": assigned,
    }


_PROBE_SRC = (
    "import os, jax\n"
    "p = os.environ.get('JAX_PLATFORMS')\n"
    "if p: jax.config.update('jax_platforms', p)\n"
    "d = jax.devices()\n"
    "print(d[0].platform, len(d))\n"
)


def _pin_platform():
    """Honor JAX_PLATFORMS even under a sitecustomize platform pin (the
    env var alone is defeated by it; the config update is not)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def _backend_diag():
    """Probe backend init in a SUBPROCESS with a deadline, emitting a
    diagnostic JSON line BEFORE any metric so a red bench is attributable
    from the artifact alone. BENCH_r01 died with rc=1 and no evidence;
    a wedged device tunnel is worse — jax.devices() hangs, so an
    in-process probe could never report anything. One clean retry (fresh
    subprocess) covers transient init flakes."""
    import subprocess

    for attempt in (1, 2):
        try:
            probe = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=240,
            )
        except subprocess.TimeoutExpired:
            print(
                json.dumps(
                    {"diag": "backend_probe_timeout", "attempt": attempt,
                     "timeout_s": 240}
                ),
                flush=True,
            )
            continue
        if probe.returncode == 0 and probe.stdout.strip():
            plat, count = probe.stdout.split()[-2:]
            print(
                json.dumps(
                    {"diag": "backend", "platform": plat,
                     "device_count": int(count), "attempt": attempt}
                ),
                flush=True,
            )
            _pin_platform()
            return
        print(
            json.dumps(
                {"diag": "backend_init_failed", "attempt": attempt,
                 "rc": probe.returncode,
                 "error": (probe.stderr or "")[-300:]}
            ),
            flush=True,
        )
        time.sleep(5)
    sys.exit(1)


def main():
    from kubernetes_scheduler_tpu.sim import gen_cluster, gen_pods

    _backend_diag()
    if "--perf-gate-spans" in sys.argv:
        # `make perf-gate`: ONE telemetry-shaped pipelined drain whose
        # span directory `spans diff` then gates against the committed
        # BENCH_SPAN_BASELINE.json — a fusion regression in any stage
        # (e.g. an interpreter-mode kernel sneaking onto the CPU path)
        # fails the build loudly, per stage, with numbers attached
        out_dir = sys.argv[sys.argv.index("--perf-gate-spans") + 1]
        n_pods = int(
            os.environ.get("BENCH_LOOP_PODS", 1024 * DEFAULT_LOOP_WINDOWS)
        )
        print(
            json.dumps(
                loop_rate(
                    n_pods=n_pods,
                    max_windows=1,
                    pipeline_depth=1,
                    force_device=True,
                    metric_suffix="_perfgate",
                    span_path=out_dir,
                )
            ),
            flush=True,
        )
        # the mesh-sharded resident drain writes into the SAME span
        # directory, so the committed baseline (and the gate diffing
        # against it) covers the sharded path's stage costs too —
        # a regression in the shard_map program or the routed delta
        # fold moves engine_step/delta_derive like any other
        print(
            json.dumps(
                loop_rate(
                    n_pods=n_pods,
                    n_nodes=int(os.environ.get("BENCH_SHARDED_NODES", 4000)),
                    max_windows=1,
                    pipeline_depth=1,
                    force_device=True,
                    resident=True,
                    sharded=True,
                    churn_nodes=int(os.environ.get("BENCH_CHURN_NODES", 64)),
                    metric_suffix="_perfgate_sharded",
                    span_path=out_dir,
                )
            ),
            flush=True,
        )
        # the streaming-ingestion drain adds the mirror stages
        # (event_apply, mirror_emit) to the same baseline: a mirror
        # regression (e.g. a flush storm putting build_snapshot back on
        # the hot path) moves mirror_emit like any other stage
        print(
            json.dumps(
                loop_rate(
                    n_pods=n_pods,
                    max_windows=1,
                    pipeline_depth=1,
                    force_device=True,
                    resident=True,
                    mirror=True,
                    churn_nodes=int(os.environ.get("BENCH_CHURN_NODES", 64)),
                    metric_suffix="_perfgate_streaming",
                    span_path=out_dir,
                )
            ),
            flush=True,
        )
        return
    if "--loop" in sys.argv:
        print(json.dumps(loop_rate()))
        print(json.dumps(loop_rate(max_windows=16, metric_suffix="_deep16w")))
        pipe = _pipelined_loop_rate()
        print(json.dumps(pipe))
        print(json.dumps(_fused_loop_rate()))
        print(json.dumps(_resident_loop_rate()))
        print(json.dumps(_streaming_loop_rate()), flush=True)
        print(json.dumps(_idle_streaming_rate()), flush=True)
        print(json.dumps(_drift_streaming_rate()), flush=True)
        # the mesh-sharded resident loop at the 100k-node scale (plus
        # its tenth-scale flat-bytes reference) and the 100k x 50k
        # sharded engine headline
        for row in _sharded_loop_rate():
            print(json.dumps(row), flush=True)
        print(json.dumps(_sharded_throughput()), flush=True)
        # the replicated fleet: 1 vs 2 vs 4 schedulers over the
        # partitioned queue + first-bind-wins table, plus the
        # deterministic conflict-storm evidence row
        for row in _replica_loop_rate():
            print(json.dumps(row), flush=True)
        print(json.dumps(_replay_loop_rate()))
        print(json.dumps(_shadow_rescore_rate()))
        tel, attrib = _telemetry_loop_rate(pipe)
        print(json.dumps(tel))
        print(json.dumps(attrib))
        print(json.dumps(_scenario_rate("burst", "burst")))
        print(json.dumps(_scenario_rate("gang-mix", "gang")))
        print(json.dumps(_chaos_loop_rate()), flush=True)
        return
    if "--suite" in sys.argv:
        from kubernetes_scheduler_tpu.sim.cluster_gen import BENCH_CONFIGS

        results = [suite_rate(name) for name in BENCH_CONFIGS]
        with open("BENCH_SUITE.json", "w") as f:
            json.dump(results, f, indent=2)
        for r in results:
            print(json.dumps(r))
        return

    # images=True adds the ImageLocality signal for the weighted-combination
    # measurement; the yoda-only programs never read those tensors (XLA
    # DCEs them), so the headline numbers are unaffected
    snapshot = gen_cluster(N_NODES, seed=0, images=True)
    pods = gen_pods(N_PODS, seed=1, images=True)

    base = baseline_rate(snapshot, pods)
    # the deployed-default configuration (the SchedulerConfig defaults:
    # price step + dynamic affinity on) measured BESIDE the
    # throughput-first headline — round-3 verdict: the shipped default's
    # number belongs next to the headline, not only in PARITY.md.
    # Emitted first; the driver records the LAST line as the headline.
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    dep = tpu_rate(
        snapshot, pods,
        price_frac=SchedulerConfig().auction_price_frac,
        affinity_aware=True,
    )
    print(
        json.dumps(
            {
                "metric": f"scheduling_throughput_{N_NODES}nodes_deployed_default",
                "value": round(dep, 1),
                "unit": "pods/s",
                "vs_baseline": round(dep / base, 2),
            }
        ),
        flush=True,
    )
    # the END-TO-END host loop (queue pop -> snapshot build -> device
    # program -> binds) recorded beside the engine headline — the number
    # a real deployment experiences (round-4 verdict #1): the deployed
    # default (8 windows/cycle) and the deep-backlog configuration (16
    # windows/cycle, amortizing the device round-trip). Failures must
    # not cost the headline metric.
    try:
        print(json.dumps(loop_rate()), flush=True)
        print(
            json.dumps(loop_rate(max_windows=16, metric_suffix="_deep16w")),
            flush=True,
        )
        # the double-buffered loop beside the serial one: BENCH_r06's
        # before/after for the pipelined host-loop change
        pipe = _pipelined_loop_rate()
        print(json.dumps(pipe), flush=True)
        # fused megakernel vs unfused device step on the same drain
        # shape: the per-round fused/unfused engine delta
        print(json.dumps(_fused_loop_rate()), flush=True)
        # device-resident cluster state with epoch-validated delta
        # uploads, measured against the same cluster/backlog shape
        print(json.dumps(_resident_loop_rate()), flush=True)
        # streaming state ingestion: the event-sourced mirror drain
        # beside an identical rebuild drain (stage-level replacement
        # evidence), and the idle-cluster zero-event row
        print(json.dumps(_streaming_loop_rate()), flush=True)
        print(json.dumps(_idle_streaming_rate()), flush=True)
        print(json.dumps(_drift_streaming_rate()), flush=True)
        # the mesh-sharded resident loop at the 100k-node scale (with
        # the flat-bytes reference) and the sharded engine headline:
        # 100k nodes x 50k pods in one device-resident program
        for row in _sharded_loop_rate():
            print(json.dumps(row), flush=True)
        print(json.dumps(_sharded_throughput()), flush=True)
        # the replicated scheduler fleet: 1 vs 2 vs 4 full Schedulers
        # over the partitioned queue + first-bind-wins bind table —
        # aggregate-throughput scaling with zero double binds, plus the
        # deterministic conflict-storm row (conflict rate, requeue
        # latency, loser accounting)
        for row in _replica_loop_rate():
            print(json.dumps(row), flush=True)
        # flight recorder on, then replay-from-trace: perf from a
        # captured workload + bitwise binding parity (binding_diffs=0)
        print(json.dumps(_replay_loop_rate()), flush=True)
        # shadow serving over the same journal shape: identical
        # candidate config must re-derive every binding (divergence 0),
        # and the re-score rate says the shadow keeps up with the
        # primary it audits
        print(json.dumps(_shadow_rescore_rate()), flush=True)
        # full telemetry on (spans + scraped exporter) beside the
        # pipelined baseline: the <5%-overhead observability gate, and
        # the per-stage cycle budget table over the same drain's spans
        tel, attrib = _telemetry_loop_rate(pipe)
        print(json.dumps(tel), flush=True)
        print(json.dumps(attrib), flush=True)
        # scenario harness (sim/scenarios) beside the pipelined
        # baseline: the burst program (time-varying arrivals) and the
        # gang-heavy mix (all-or-nothing admit rate)
        print(json.dumps(_scenario_rate("burst", "burst")), flush=True)
        print(json.dumps(_scenario_rate("gang-mix", "gang")), flush=True)
        # the chaos drain beside the clean pipelined one: the same
        # backlog shape under a deterministic engine RPC-flap plan —
        # degraded-cycle rate, breaker transitions, recovery latency
        print(json.dumps(_chaos_loop_rate()), flush=True)
    except Exception as e:  # pragma: no cover - diagnostic path
        print(json.dumps({"diag": "host_loop_failed", "error": str(e)[-200:]}),
              flush=True)

    # the reference's PRODUCTION scoring: yoda at weight 2 beside the
    # k8s 1.22 default shape scorers (example/config:25-27 +
    # deploy/yoda-scheduler.yaml:21-47 disabling nothing) — measured as
    # the framework's weighted multi-plugin combination
    wsp = tpu_rate(
        snapshot, pods, affinity_aware=True,
        score_plugins=(
            ("balanced_cpu_diskio", 2.0), ("least_allocated", 1.0),
            ("balanced_allocation", 1.0), ("image_locality", 1.0),
        ),
    )
    print(
        json.dumps(
            {
                "metric": f"scheduling_throughput_{N_NODES}nodes_weighted_multi_scorer",
                "value": round(wsp, 1),
                "unit": "pods/s",
                "vs_baseline": round(wsp / base, 2),
            }
        ),
        flush=True,
    )
    tpu = tpu_rate(snapshot, pods)
    print(
        json.dumps(
            {
                "metric": f"scheduling_throughput_{N_NODES}nodes",
                "value": round(tpu, 1),
                "unit": "pods/s",
                "vs_baseline": round(tpu / base, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
