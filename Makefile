# Mirrors the reference Makefile's local/build/push trio (fmt+vet+compile,
# docker image builds) for the Python/JAX + C++ implementation.

PY ?= python
IMAGE_REPO ?= registry.example.com/yoda-tpu
TAG ?= latest

.PHONY: local test test-fast bench trace-smoke obs-smoke scenario-smoke chaos-smoke replica-smoke soak-smoke perf-gate perf-baseline lint lint-fast lint-sarif collective-baseline model-check native native-asan native-tsan proto clean build push

# "make local" in the reference = fmt + vet + compile. Here: byte-compile
# the package, build the native library, lint, run the fast tests.
local: native lint
	$(PY) -m compileall -q kubernetes_scheduler_tpu bench.py __graft_entry__.py
	$(PY) -m pytest tests/ -x -q -m "not slow"

# repo-native static analysis (kubernetes_scheduler_tpu/analysis):
# eighteen AST rule families over the interprocedural dataflow core
# (thread-race/determinism-taint ride the declared thread model in
# analysis/threads.py with its seeded thread-mutant harness;
# spmd-collective rides the replication-lattice interpreter in
# analysis/spmd.py), plus the engine-contract layer (jax.eval_shape
# traces of every engine entry point on CPU — the mesh-sharded
# surfaces traced THROUGH shard_map on the virtual 8-device topology,
# with the sharded==dense spec pin, the COLLECTIVE_BUDGET.json gate,
# and the seeded SPMD mutant harness) and the protocol-model layer
# (bounded model checking of the session/epoch/capability protocol
# with anchor-drift detection and the seeded mutation harness — `make
# model-check` is the standalone loop). Exits non-zero on any unwaived
# violation; see the README's "Static analysis" section for the
# inline-waiver syntax. The run drops a findings-JSON artifact for CI
# diffing and asserts a wall-time budget — the parse-once index must
# keep full-repo lint (contracts and models included) inside
# LINT_BUDGET seconds; tests/test_bench_smoke.py holds the sharper
# relative gate.
LINT_BUDGET ?= 120
LINT_ARTIFACT ?= /tmp/yoda-lint.json
# the sharded-contract traces need the virtual multi-device topology
LINT_ENV = env JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8"
lint:
	$(LINT_ENV) $(PY) -m kubernetes_scheduler_tpu.analysis \
	  --budget-seconds $(LINT_BUDGET) --json-artifact $(LINT_ARTIFACT)

# the pre-commit loop: `graftlint --changed-only` against the merge
# base — findings scoped to the changed files' reverse-dependency
# closure from the shared call graph, the whole-program layers
# (contracts incl. the sharded/collective-budget gates, protocol
# models) tracing only when a file on their declared SURFACE is in the
# closure. Changed-only findings are a subset of the full run's by
# construction (pinned in tests/test_analysis.py). Override LINT_BASE
# to diff against any ref (default: merge-base with origin/main when
# one exists, else HEAD — uncommitted work is always included).
LINT_BASE ?= $(shell git merge-base HEAD origin/main 2>/dev/null || echo HEAD)
lint-fast:
	$(LINT_ENV) $(PY) -m kubernetes_scheduler_tpu.analysis \
	  --changed-only $(LINT_BASE)

# regenerate the sharded engine's collective budget from the traced
# jaxprs after an INTENTIONAL collective-structure change — `make
# lint` diffs every sharded surface's static psum/pmax/pmin/
# all_gather/axis_index counts against this checked-in file, so an
# accidental extra collective in the election scan body fails lint
# with a diff instead of surfacing as a bench regression.
collective-baseline:
	$(LINT_ENV) $(PY) -c "import json; \
	  from kubernetes_scheduler_tpu.analysis.contracts import write_collective_budget; \
	  doc = write_collective_budget(); \
	  print(json.dumps(doc['surfaces'], indent=2))"

# bounded model checking of the session/epoch/capability protocol
# (kubernetes_scheduler_tpu/analysis/model/): exhausts every shipped
# protocol model's state space, verifies every transition's code
# anchors against the live source, and runs the seeded mutation
# harness (protocol-bug reintroductions the checker must each catch).
# The same layer is folded into `make lint` as pseudo-rule
# `protocol-model`; this target is the standalone loop with per-model
# state counts, mutant verdicts, and a JSON artifact for CI diffing.
# Exit 3 = a model blew the budget — the bounded proof is incomplete;
# raise the budget or shrink the model, never ignore it.
MODEL_BUDGET ?= 60
MODEL_ARTIFACT ?= /tmp/yoda-model-check.json
model-check:
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu.analysis.model \
	  --budget-seconds $(MODEL_BUDGET) --json-artifact $(MODEL_ARTIFACT)

# SARIF 2.1.0 artifact (CI code-scanning upload). The renderer
# structurally validates the document before printing — a malformed
# artifact fails HERE, not in the uploader; the smoke test re-validates
# the written file.
LINT_SARIF ?= /tmp/yoda-lint.sarif
lint-sarif:
	@rc=0; $(LINT_ENV) $(PY) -m kubernetes_scheduler_tpu.analysis \
	  --format sarif > $(LINT_SARIF) || rc=$$?; \
	$(PY) -c "import json; from kubernetes_scheduler_tpu.analysis.sarif import validate_sarif; validate_sarif(json.load(open('$(LINT_SARIF)'))); print('sarif ok: $(LINT_SARIF)')" || exit $$?; \
	exit $$rc

# the full suite (sharding parity sweeps, e2e loops, learned-model
# training included) — run before committing a milestone. xdist cuts the
# wall time roughly in half even on few cores (the slow tests block on
# device sync, not CPU); override WORKERS=0 for a single process.
WORKERS ?= 4
test:
	@if [ "$(WORKERS)" != "0" ] && $(PY) -c "import xdist" 2>/dev/null; then \
		$(PY) -m pytest tests/ -q -p xdist -n $(WORKERS) -x; \
	else \
		$(PY) -m pytest tests/ -x -q; \
	fi

# the iteration loop: per-kernel/unit tests only (<~2 min on 1 CPU);
# `slow` marking lives in tests/conftest.py
test-fast:
	$(PY) -m pytest tests/ -x -q -m "not slow"

bench:
	$(PY) bench.py

# the bench path itself must not rot between rounds: the full bench.py
# flow (engine headline, host loop incl. the pipelined, resident-
# state/delta-upload, and mesh-SHARDED resident variants, weighted
# multi-scorer) at toy sizes on CPU — seconds of compute, all
# compiles. The forced 8-device host-platform topology (the multichip
# dryrun recipe) gives the sharded rows a real mesh; same invocation
# tests/test_bench_smoke.py wraps as a slow-marked test.
bench-smoke:
	env JAX_PLATFORMS=cpu \
	  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  BENCH_NODES=64 BENCH_PODS=128 BENCH_WINDOW=32 \
	  BENCH_REPS=2 BENCH_BASELINE_PODS=8 BENCH_LOOP_NODES=32 \
	  BENCH_LOOP_PODS=64 BENCH_LOOP_SAMPLES=3 \
	  BENCH_SHARDED_NODES=256 BENCH_SHARDED_PODS=96 \
	  BENCH_CHURN_NODES=8 $(PY) bench.py

# flight-recorder round trip on CPU: record a short sim-driven run (the
# config pins the device path — tiny cycles would otherwise route to
# the scalar fallback, which records decisions but is not replayable),
# replay the journal, and diff the recorded vs replayed journals —
# `trace replay` exits non-zero on ANY binding diff, `trace diff` on
# any decision difference. tests/test_bench_smoke.py wraps the same
# flow as a slow-marked test.
TRACE_SMOKE_DIR ?= /tmp/yoda-trace-smoke
trace-smoke:
	rm -rf $(TRACE_SMOKE_DIR)
	mkdir -p $(TRACE_SMOKE_DIR)
	printf '{"batch_window": 64, "min_device_work": 1, "adaptive_dispatch": false}' \
	  > $(TRACE_SMOKE_DIR)/config.json
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scheduler \
	  --nodes 48 --pods 192 --config $(TRACE_SMOKE_DIR)/config.json \
	  --trace $(TRACE_SMOKE_DIR)/journal
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(TRACE_SMOKE_DIR)/journal --out $(TRACE_SMOKE_DIR)/replayed
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace diff \
	  $(TRACE_SMOKE_DIR)/journal $(TRACE_SMOKE_DIR)/replayed

# scenario harness round trip on CPU: the two fastest registered
# scenarios (burst, gang-mix) at small scale, each emitting a flight-
# recorder journal that is then replayed — `trace replay` exits
# non-zero on ANY binding diff, which is the replay-pinning gate every
# scenario ships under. tests/test_bench_smoke.py wraps the same flow
# as a slow-marked test.
SCENARIO_SMOKE_DIR ?= /tmp/yoda-scenario-smoke
scenario-smoke:
	rm -rf $(SCENARIO_SMOKE_DIR)
	mkdir -p $(SCENARIO_SMOKE_DIR)
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scenario run \
	  burst --nodes 32 --trace $(SCENARIO_SMOKE_DIR)/burst
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(SCENARIO_SMOKE_DIR)/burst
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scenario run \
	  gang-mix --nodes 32 --trace $(SCENARIO_SMOKE_DIR)/gang-mix
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(SCENARIO_SMOKE_DIR)/gang-mix

# chaos smoke: the compound-storm chaos program (sim/faults.py) at
# compressed scale — deterministic fault injection at every boundary
# at once (advisor flap past the stale TTL, engine crash-restart,
# informer partition, journal ENOSPC, added latency, mirror
# corruption) — run with --require-recovery, which exits 1 unless the
# run ends FULLY recovered: every degradation-ladder rung back at top,
# both circuit breakers closed. The emitted journal is then
# replay-pinned (`trace replay` exits non-zero on ANY binding diff) —
# chaos runs are as deterministic as clean ones.
# tests/test_bench_smoke.py wraps the same flow as a slow-marked test.
CHAOS_SMOKE_DIR ?= /tmp/yoda-chaos-smoke
chaos-smoke:
	rm -rf $(CHAOS_SMOKE_DIR)
	mkdir -p $(CHAOS_SMOKE_DIR)
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scenario run \
	  compound-storm --nodes 24 --require-recovery \
	  --trace $(CHAOS_SMOKE_DIR)/compound-storm
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(CHAOS_SMOKE_DIR)/compound-storm

# replica smoke: the 2-replica conflict-storm scenario (partitioned
# queue + first-bind-wins bind table, host/replica.py) at compressed
# scale. The summary gate asserts the replica-bind protocol's whole
# point: conflicts actually HAPPENED (bind_conflicts > 0), every loser
# resolved (pods_bound == pods_submitted — requeued then retired,
# never lost), and ZERO double binds. Then BOTH per-replica journals
# are replay-pinned independently (`trace replay` exits non-zero on
# ANY binding diff) — the fenced CAS sits downstream of the replayed
# engine boundary, so conflict cycles replay bitwise too.
# tests/test_bench_smoke.py wraps the same flow as a slow-marked test.
REPLICA_SMOKE_DIR ?= /tmp/yoda-replica-smoke
replica-smoke:
	rm -rf $(REPLICA_SMOKE_DIR)
	mkdir -p $(REPLICA_SMOKE_DIR)
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scenario run \
	  replica-conflict-storm --nodes 24 \
	  --trace $(REPLICA_SMOKE_DIR)/storm > $(REPLICA_SMOKE_DIR)/summary.out
	tail -n 1 $(REPLICA_SMOKE_DIR)/summary.out | $(PY) -c "import json,sys; \
	  s = json.loads(sys.stdin.read()); \
	  assert s['double_binds'] == 0, s; \
	  assert s['bind_conflicts'] > 0, s; \
	  assert s['pods_bound'] == s['pods_submitted'], s; \
	  print('replica-smoke: conflicts resolved =', s['bind_conflicts'], \
	        'double_binds = 0')"
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(REPLICA_SMOKE_DIR)/storm/r0
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(REPLICA_SMOKE_DIR)/storm/r1
	# the SAME storm through the fleet-shared engine (--shared-engine:
	# one pooled resident engine, cross-replica dispatch coalescing).
	# Gates: contention semantics intact (conflicts happened and every
	# loser resolved, zero double binds), the pool actually coalesced
	# (coalesced_dispatches > 0), and the fleet paid FEWER device
	# dispatches than scheduler cycles — under a 2-replica storm that is
	# the dispatches-per-tick < N claim. Both journals replay-pinned
	# through a PRIVATE engine: shared-engine decisions are bitwise the
	# decisions a private engine makes.
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scenario run \
	  replica-conflict-storm --nodes 24 --shared-engine \
	  --trace $(REPLICA_SMOKE_DIR)/storm-shared \
	  > $(REPLICA_SMOKE_DIR)/summary-shared.out
	tail -n 1 $(REPLICA_SMOKE_DIR)/summary-shared.out | $(PY) -c "import json,sys; \
	  s = json.loads(sys.stdin.read()); se = s['shared_engine']; \
	  assert s['double_binds'] == 0, s; \
	  assert s['bind_conflicts'] > 0, s; \
	  assert s['pods_bound'] == s['pods_submitted'], s; \
	  assert se['coalesced_dispatches'] > 0, se; \
	  assert se['device_dispatches'] < s['cycles'], (se, s['cycles']); \
	  print('replica-smoke (shared): conflicts resolved =', s['bind_conflicts'], \
	        'coalesced =', se['coalesced_dispatches'], \
	        'dispatches', se['device_dispatches'], '< cycles', s['cycles'])"
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(REPLICA_SMOKE_DIR)/storm-shared/r0
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(REPLICA_SMOKE_DIR)/storm-shared/r1

# shadow-mode serving + soak trend gate, end to end on CPU:
# 1. a baseline soak run (same seed, no shadow) pins the journal the
#    primary writes when NOTHING is tailing it;
# 2. the live run starts in the background and a `yoda-tpu shadow`
#    process attaches to its journal DIRECTORY as soon as the first
#    file appears — tailing through every rotation
#    (trace_file_bytes=64KiB forces several) while the primary is
#    still writing, re-scoring each cycle through an IDENTICAL
#    candidate config;
# 3. the shadow's own /metrics exporter is scraped while it tails
#    (decision-diff series must be present);
# 4. the shadow summary must show every record scored with ZERO
#    divergence, >= 1 rotation followed live, breaker closed — and
#    `trace diff` pins baseline vs live journal bitwise equal: a
#    tailing shadow perturbs NOTHING;
# 5. the BASELINE run's span stream passes `spans report --trend` (no
#    leak; the live run's spans would carry the colocated shadow's own
#    CPU contention ramping up, which is drift in the harness, not the
#    scheduler), a perturb_trend-seeded copy (engine_step durations
#    ramped 1x->4x over the soak) must FAIL it with exit 1 exactly,
#    and `trace trend` over the journal must stay clean.
# tests/test_bench_smoke.py wraps the same flow as a slow-marked test.
SOAK_SMOKE_DIR ?= /tmp/yoda-soak-smoke
SOAK_SMOKE_METRICS_PORT ?= 9163
soak-smoke:
	rm -rf $(SOAK_SMOKE_DIR)
	mkdir -p $(SOAK_SMOKE_DIR)
	printf '{"batch_window": 256, "normalizer": "none", "min_device_work": 1, "adaptive_dispatch": false, "trace_file_bytes": 65536, "cycle_slo_ms": 15000.0}' \
	  > $(SOAK_SMOKE_DIR)/candidate.json
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scenario run soak \
	  --nodes 16 --seed 0 --trace $(SOAK_SMOKE_DIR)/journal-off \
	  --spans $(SOAK_SMOKE_DIR)/spans \
	  > $(SOAK_SMOKE_DIR)/summary-off.out
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scenario run soak \
	  --nodes 16 --seed 0 --trace $(SOAK_SMOKE_DIR)/journal \
	  > $(SOAK_SMOKE_DIR)/summary.out 2>&1 & echo $$! > $(SOAK_SMOKE_DIR)/scenario.pid
	for i in `seq 1 240`; do \
	  ls $(SOAK_SMOKE_DIR)/journal/journal-*.ytrj >/dev/null 2>&1 && break; \
	  kill -0 `cat $(SOAK_SMOKE_DIR)/scenario.pid` 2>/dev/null \
	    || { cat $(SOAK_SMOKE_DIR)/summary.out; exit 1; }; \
	  sleep 0.5; done
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu shadow \
	  $(SOAK_SMOKE_DIR)/journal \
	  --candidate-config $(SOAK_SMOKE_DIR)/candidate.json \
	  --follow --idle-timeout-s 15 \
	  --metrics-port $(SOAK_SMOKE_METRICS_PORT) --metrics-host 127.0.0.1 \
	  --spans $(SOAK_SMOKE_DIR)/shadow-spans \
	  > $(SOAK_SMOKE_DIR)/shadow.out 2>&1 & echo $$! > $(SOAK_SMOKE_DIR)/shadow.pid
	for i in `seq 1 120`; do \
	  $(PY) -c "import urllib.request; \
	    body = urllib.request.urlopen('http://127.0.0.1:$(SOAK_SMOKE_METRICS_PORT)/metrics', timeout=5).read().decode(); \
	    assert 'shadow_records_applied_total' in body, body[:400]; \
	    assert 'shadow_cycles_total' in body, body[:400]" 2>/dev/null \
	    && { echo 'soak-smoke: shadow exporter scraped live'; break; }; \
	  test $$i -lt 120 || { echo 'shadow exporter never served'; \
	    kill `cat $(SOAK_SMOKE_DIR)/shadow.pid` 2>/dev/null; exit 1; }; \
	  sleep 0.5; done
	for i in `seq 1 240`; do \
	  kill -0 `cat $(SOAK_SMOKE_DIR)/scenario.pid` 2>/dev/null || break; sleep 0.5; done
	for i in `seq 1 360`; do \
	  kill -0 `cat $(SOAK_SMOKE_DIR)/shadow.pid` 2>/dev/null || break; sleep 0.5; done
	kill -0 `cat $(SOAK_SMOKE_DIR)/shadow.pid` 2>/dev/null \
	  && { kill `cat $(SOAK_SMOKE_DIR)/shadow.pid`; exit 1; } || true
	tail -n 1 $(SOAK_SMOKE_DIR)/shadow.out | $(PY) -c "import json,sys; \
	  s = json.loads(sys.stdin.read()); \
	  assert s['records_applied'] > 0, s; \
	  assert s['cycles'].get('scored') == s['records_applied'], s; \
	  assert s['bindings_changed'] == 0 and s['divergence_ratio'] == 0.0, s; \
	  assert s['gangs_diverged'] == 0, s; \
	  assert s['breaker_state'] == 'closed', s; \
	  assert s['tail']['rotations_followed'] >= 1, s['tail']; \
	  print('soak-smoke: shadow scored', s['records_applied'], \
	        'cycles live, divergence 0, rotations', \
	        s['tail']['rotations_followed'])"
	tail -n 1 $(SOAK_SMOKE_DIR)/summary.out | $(PY) -c "import json,sys; \
	  s = json.loads(sys.stdin.read()); \
	  assert s['fallback_cycles'] == 0, s"
	tail -n 1 $(SOAK_SMOKE_DIR)/summary-off.out | $(PY) -c "import json,sys; \
	  s = json.loads(sys.stdin.read()); \
	  assert s['slo_breaches'] == 0, s; \
	  assert s['fallback_cycles'] == 0, s"
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace diff \
	  $(SOAK_SMOKE_DIR)/journal-off $(SOAK_SMOKE_DIR)/journal
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace replay \
	  $(SOAK_SMOKE_DIR)/journal
	# coarse floor at smoke scale (the perf-gate convention): each time
	# window holds ~15 cycles, so a micro-stage p99 is max-like noise —
	# 0.2 ms p50 / 2 ms p99 floors are far above that jitter and far
	# below the 3x-median additive drift the seeded leak plants
	$(PY) -m kubernetes_scheduler_tpu spans report --trend \
	  $(SOAK_SMOKE_DIR)/spans --min-ms 0.2
	$(PY) -c "from kubernetes_scheduler_tpu.trace.trend import perturb_trend; \
	  perturb_trend('$(SOAK_SMOKE_DIR)/spans', \
	  '$(SOAK_SMOKE_DIR)/spans-leaky', stage='engine_step', factor=4.0)"
	$(PY) -m kubernetes_scheduler_tpu spans report --trend \
	  $(SOAK_SMOKE_DIR)/spans-leaky --min-ms 0.2; \
	  test $$? -eq 1  # exactly the regression exit — 2 (error) must fail
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu trace trend \
	  $(SOAK_SMOKE_DIR)/journal

# end-to-end telemetry round trip on CPU: a sidecar with its own
# /metrics + span files, a short sim-driven host run with spans + the
# host exporter on, a sidecar-metrics scrape (device-step histograms
# must be there), the `spans merge` join — which exits non-zero when
# host and sidecar span files share no trace ids (broken metadata
# propagation) — and the analytics round trip: `spans report` over the
# host spans, a self-diff that must exit 0, and a diff against a
# synthetically slowed copy (perturb_spans, the test harness for the
# gate) that must exit 1. tests/test_bench_smoke.py wraps the same
# flow as a slow-marked test.
OBS_SMOKE_DIR ?= /tmp/yoda-obs-smoke
OBS_SMOKE_PORT ?= 50161
OBS_SMOKE_METRICS_PORT ?= 9161
OBS_SMOKE_HOST_METRICS_PORT ?= 9162
obs-smoke:
	rm -rf $(OBS_SMOKE_DIR)
	mkdir -p $(OBS_SMOKE_DIR)
	printf '{"batch_window": 64, "min_device_work": 1, "adaptive_dispatch": false}' \
	  > $(OBS_SMOKE_DIR)/config.json
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu sidecar \
	  --port $(OBS_SMOKE_PORT) --metrics-port $(OBS_SMOKE_METRICS_PORT) \
	  --metrics-host 127.0.0.1 --span-path $(OBS_SMOKE_DIR)/sidecar-spans \
	  > $(OBS_SMOKE_DIR)/sidecar.log 2>&1 & echo $$! > $(OBS_SMOKE_DIR)/sidecar.pid
	sleep 8
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu scheduler \
	  --nodes 48 --pods 192 --config $(OBS_SMOKE_DIR)/config.json \
	  --engine 127.0.0.1:$(OBS_SMOKE_PORT) --spans $(OBS_SMOKE_DIR)/host-spans \
	  --metrics-port $(OBS_SMOKE_HOST_METRICS_PORT) \
	  || { kill `cat $(OBS_SMOKE_DIR)/sidecar.pid`; exit 1; }
	$(PY) -c "import urllib.request; body = urllib.request.urlopen('http://127.0.0.1:$(OBS_SMOKE_METRICS_PORT)/metrics', timeout=10).read().decode(); assert 'device_step_duration_seconds_bucket' in body, body" \
	  || { kill `cat $(OBS_SMOKE_DIR)/sidecar.pid`; exit 1; }
	kill `cat $(OBS_SMOKE_DIR)/sidecar.pid`
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_scheduler_tpu spans merge \
	  $(OBS_SMOKE_DIR)/host-spans $(OBS_SMOKE_DIR)/sidecar-spans \
	  --out $(OBS_SMOKE_DIR)/merged.trace.json
	$(PY) -m kubernetes_scheduler_tpu spans report \
	  $(OBS_SMOKE_DIR)/host-spans > $(OBS_SMOKE_DIR)/report.json
	$(PY) -m kubernetes_scheduler_tpu spans diff \
	  $(OBS_SMOKE_DIR)/report.json $(OBS_SMOKE_DIR)/host-spans
	$(PY) -c "from kubernetes_scheduler_tpu.trace.analyze import perturb_spans; \
	  perturb_spans('$(OBS_SMOKE_DIR)/host-spans', \
	  '$(OBS_SMOKE_DIR)/host-spans-slow', stage='engine_step', factor=4.0)"
	$(PY) -m kubernetes_scheduler_tpu spans diff \
	  $(OBS_SMOKE_DIR)/host-spans $(OBS_SMOKE_DIR)/host-spans-slow; \
	  test $$? -eq 1  # exactly the regression exit — 2 (error) must fail

# span-based perf regression gate: ONE telemetry-shaped pipelined drain
# at smoke scale on CPU emits a fresh span directory, which `spans diff`
# gates against the committed BENCH_SPAN_BASELINE.json with per-stage
# thresholds. The floors are deliberately COARSE — a stage must grow by
# >20 ms absolute AND >100%/the per-stage override. Every smoke-scale
# stage p50 sits under ~6 ms, so a machine 3x slower than the baseline
# machine (or the same one under load) cannot trip the gate, while the
# regression class it exists for — an interpreter-mode Pallas kernel
# sneaking onto the CPU host path (measured ~2x engine step, and 10x+
# at interpret-unfriendly shapes), a serialization pass landing on the
# dispatch path — blows through both floors. Regenerate the committed
# baseline with `make perf-baseline` after an intentional stage-cost
# change. tests/test_bench_smoke.py wraps the same flow as a
# slow-marked test.
PERF_GATE_DIR ?= /tmp/yoda-perf-gate
PERF_GATE_ENV = env JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  BENCH_LOOP_NODES=32 BENCH_LOOP_PODS=64 \
  BENCH_SHARDED_NODES=64 BENCH_CHURN_NODES=8
perf-gate:
	rm -rf $(PERF_GATE_DIR)
	mkdir -p $(PERF_GATE_DIR)
	$(PERF_GATE_ENV) $(PY) bench.py --perf-gate-spans $(PERF_GATE_DIR)/spans
	$(PY) -m kubernetes_scheduler_tpu spans diff \
	  BENCH_SPAN_BASELINE.json $(PERF_GATE_DIR)/spans \
	  --threshold-pct 100 --min-ms 20 \
	  --stage-threshold engine_step=150 \
	  --stage-threshold snapshot_build=150 \
	  --stage-threshold cycle=150

perf-baseline:
	rm -rf $(PERF_GATE_DIR)
	mkdir -p $(PERF_GATE_DIR)
	$(PERF_GATE_ENV) $(PY) bench.py --perf-gate-spans $(PERF_GATE_DIR)/spans
	$(PY) -m kubernetes_scheduler_tpu spans report $(PERF_GATE_DIR)/spans \
	  > BENCH_SPAN_BASELINE.json

native:
	$(MAKE) -C native

# sanitized native builds (ASan+UBSan / TSan) for the host loop;
# tests/test_native_sanitized.py drives the full native test surface
# against the ASan library (also: make test SANITIZED=... not needed —
# the slow suite includes it)
native-asan:
	$(MAKE) -C native asan

native-tsan:
	$(MAKE) -C native tsan

# regenerate the gRPC schema (bridge/schedule.proto -> schedule_pb2.py)
proto:
	protoc --python_out=kubernetes_scheduler_tpu/bridge \
	  -I kubernetes_scheduler_tpu/bridge kubernetes_scheduler_tpu/bridge/schedule.proto

build:
	docker build -f Dockerfile.host -t $(IMAGE_REPO)/host:$(TAG) .
	docker build -f Dockerfile.sidecar -t $(IMAGE_REPO)/sidecar:$(TAG) .

push: build
	docker push $(IMAGE_REPO)/host:$(TAG)
	docker push $(IMAGE_REPO)/sidecar:$(TAG)

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
